"""Light client with bisection ("skipping") verification.

Reference: light/client.go:133-1184. The client tracks a primary provider
plus witnesses, persists verified light blocks in a trusted store, and
verifies headers either sequentially (adjacent, hash-chained) or by
bisection: try the non-adjacent trust-level check straight to the target;
on NewValSetCantBeTrusted, pivot to an intermediate height and recurse.
Every commit check lands in the batched verifiers, so a deep catch-up is
a few TPU launches rather than thousands of host verifies.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field

from ..types.validation import DEFAULT_TRUST_LEVEL, Fraction
from ..types.light_block import LightBlock
from . import verifier
from .errors import (
    BadLightBlockError,
    ConflictingHeadersError,
    FailedHeaderCrossReferencingError,
    LightBlockNotFoundError,
    LightClientError,
    NewValSetCantBeTrustedError,
    NoWitnessesError,
    VerificationFailedError,
)
from .provider import Provider
from .store import Store

SECOND_NS = verifier.SECOND_NS

# pivot = trusted + 9/10 * (target - trusted)  (client.go:46-52)
_PIVOT_NUM = 9
_PIVOT_DEN = 10


@dataclass(frozen=True)
class TrustOptions:
    """Subjective-initialization root of trust (light/trust_options.go)."""

    period_ns: int  # trusting period
    height: int
    hash: bytes

    def validate_basic(self) -> None:
        if self.period_ns <= 0:
            raise LightClientError("trusting period must be > 0")
        if self.height <= 0:
            raise LightClientError("trust height must be > 0")
        if len(self.hash) != 32:
            raise LightClientError("trust hash must be 32 bytes")


@dataclass
class Client:
    chain_id: str
    trust_options: TrustOptions
    primary: Provider
    witnesses: list[Provider] = field(default_factory=list)
    trusted_store: Store = field(default_factory=Store)
    trust_level: Fraction = DEFAULT_TRUST_LEVEL
    max_clock_drift_ns: int = verifier.DEFAULT_MAX_CLOCK_DRIFT_NS
    # verification trace of the latest skipping run: fed to the detector
    latest_trace: list[LightBlock] = field(default_factory=list)
    # pluggable commit-verification plane (light/verifier.CommitVerifier);
    # None = the default batched verifiers. The proof service injects a
    # caching/deadline-aware plane here — planes never change verdicts.
    commit_verifier: object | None = None

    def __post_init__(self) -> None:
        verifier.validate_trust_level(self.trust_level)
        self.trust_options.validate_basic()
        self._check_trusted_header_using_options()

    # -- initialization ----------------------------------------------------

    def _check_trusted_header_using_options(self) -> None:
        """client.go:303-401: restore from store or fetch + pin the trusted
        header against the subjective trust options."""
        last_h = self.trusted_store.last_light_block_height()
        if last_h > 0:
            return  # previously initialized: keep the store's root of trust
        lb = self._block_from(self.primary, self.trust_options.height)
        if lb.height != self.trust_options.height:
            raise LightClientError(
                f"trusted provider returned height {lb.height}, "
                f"expected {self.trust_options.height}"
            )
        if lb.hash() != self.trust_options.hash:
            raise LightClientError(
                f"trusted header hash mismatch: got {lb.hash().hex()}, "
                f"expected {self.trust_options.hash.hex()}"
            )
        lb.validate_basic(self.chain_id)
        # 2/3 of the block's own validator set must have signed it
        # (initializeWithTrustOptions, client.go:362-401) — through the
        # plane, so the proof service's root checks cache/dedupe too.
        cv = self.commit_verifier or verifier.DEFAULT_COMMIT_VERIFIER
        cv.verify_commit_light(
            self.chain_id,
            lb.validator_set,
            lb.signed_header.commit.block_id,
            lb.height,
            lb.signed_header.commit,
        )
        self.trusted_store.save_light_block(lb)

    # -- public API --------------------------------------------------------

    def trusted_light_block(self, height: int = 0) -> LightBlock:
        """client.go:404-433 (0 = latest trusted)."""
        if height == 0:
            height = self.trusted_store.last_light_block_height()
        return self.trusted_store.light_block(height)

    def last_trusted_height(self) -> int:
        return self.trusted_store.last_light_block_height()

    def first_trusted_height(self) -> int:
        return self.trusted_store.first_light_block_height()

    def update(self, now_ns: int | None = None) -> LightBlock | None:
        """Fetch + verify the primary's latest block (client.go:436-471)."""
        now_ns = self._now(now_ns)
        latest = self._block_from(self.primary, 0)
        last = self.last_trusted_height()
        if latest.height > last:
            self.verify_light_block(latest, now_ns)
            return latest
        return None

    def verify_light_block_at_height(
        self, height: int, now_ns: int | None = None
    ) -> LightBlock:
        """client.go:474-522: return trusted block at height, fetching and
        verifying (forwards or backwards) as needed."""
        if height <= 0:
            raise LightClientError("height must be positive")
        now_ns = self._now(now_ns)
        try:
            return self.trusted_store.light_block(height)
        except LightBlockNotFoundError:
            pass
        lb = self._block_from(self.primary, height)
        self.verify_light_block(lb, now_ns)
        return lb

    def verify_light_block(
        self, new_lb: LightBlock, now_ns: int | None = None
    ) -> None:
        """client.go:558-610: sequential/backwards/skipping dispatch."""
        now_ns = self._now(now_ns)
        new_lb.validate_basic(self.chain_id)
        last = self.last_trusted_height()
        first = self.first_trusted_height()
        if last < 0:
            raise LightClientError("uninitialized client")
        if new_lb.height >= last + 1:
            trusted = self.trusted_store.light_block(last)
            self._verify_skipping(trusted, new_lb, now_ns)
        elif new_lb.height < first:
            self._verify_backwards(new_lb, now_ns)
        else:
            existing = None
            try:
                existing = self.trusted_store.light_block(new_lb.height)
            except LightBlockNotFoundError:
                trusted = self.trusted_store.light_block_before(new_lb.height)
                self._verify_skipping(trusted, new_lb, now_ns)
            if existing is not None and existing.hash() != new_lb.hash():
                raise LightClientError(
                    f"header at height {new_lb.height} conflicts with "
                    f"existing trusted header"
                )

    # -- verification strategies ------------------------------------------

    def _verify_skipping(
        self, trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> None:
        """Bisection (client.go:706-775). Verified pivots land in the
        trusted store; the full trace is kept for the attack detector."""
        if target.height == trusted.height + 1:
            verifier.verify_adjacent(
                trusted.signed_header,
                target.signed_header,
                target.validator_set,
                self.trust_options.period_ns,
                now_ns,
                self.max_clock_drift_ns,
                self.commit_verifier,
            )
            self.trusted_store.save_light_block(target)
            self.latest_trace = [trusted, target]
            return
        block_cache = [target]
        depth = 0
        verified = trusted
        trace = [trusted]
        while True:
            try:
                verifier.verify(
                    verified.signed_header,
                    verified.validator_set,
                    block_cache[depth].signed_header,
                    block_cache[depth].validator_set,
                    self.trust_options.period_ns,
                    now_ns,
                    self.max_clock_drift_ns,
                    self.trust_level,
                    self.commit_verifier,
                )
            except NewValSetCantBeTrustedError:
                # pivot deeper: fetch an intermediate block
                if depth == len(block_cache) - 1:
                    pivot = (
                        verified.height
                        + (block_cache[depth].height - verified.height)
                        * _PIVOT_NUM
                        // _PIVOT_DEN
                    )
                    interim = self._block_from(self.primary, pivot)
                    block_cache.append(interim)
                depth += 1
                continue
            except Exception as e:
                raise VerificationFailedError(
                    verified.height, block_cache[depth].height, e
                ) from e
            # verified block_cache[depth]
            if depth == 0:
                trace.append(target)
                self.trusted_store.save_light_block(target)
                self.latest_trace = trace
                return
            verified = block_cache[depth]
            self.trusted_store.save_light_block(verified)
            trace.append(verified)
            del block_cache[depth:]
            depth = 0

    def _verify_backwards(self, target: LightBlock, now_ns: int) -> None:
        """Hash-chain walk below the earliest trusted header
        (client.go:933-987)."""
        trusted = self.trusted_store.light_block(self.first_trusted_height())
        if verifier.header_expired(
            trusted.signed_header, self.trust_options.period_ns, now_ns
        ):
            raise LightClientError("can't verify backwards: trusted expired")
        cur = trusted
        for height in range(trusted.height - 1, target.height - 1, -1):
            interim = (
                target
                if height == target.height
                else self._block_from(self.primary, height)
            )
            verifier.verify_backwards(
                interim.signed_header.header, cur.signed_header.header
            )
            self.trusted_store.save_light_block(interim)
            cur = interim

    # -- witness management (client.go:1019-1129) --------------------------

    def compare_first_header_with_witnesses(self, sh) -> None:
        """Each witness must serve the same header; conflicting headers
        raise ConflictingHeadersError (client.go:1131+)."""
        if not self.witnesses:
            return
        errors = []
        bad: list[int] = []
        for i, w in enumerate(self.witnesses):
            try:
                alt = self._block_from(w, sh.height)
            except Exception as e:
                errors.append(e)
                bad.append(i)
                continue
            if alt.hash() != sh.hash():
                raise ConflictingHeadersError(alt, i)
        if len(errors) == len(self.witnesses):
            raise FailedHeaderCrossReferencingError(errors)
        for i in reversed(bad):
            del self.witnesses[i]

    def remove_witnesses(self, indexes: list[int]) -> None:
        if len(indexes) >= len(self.witnesses) and self.witnesses:
            self.witnesses = []
            raise NoWitnessesError()
        for i in sorted(indexes, reverse=True):
            del self.witnesses[i]

    # -- maintenance -------------------------------------------------------

    def cleanup_after(self, height: int) -> None:
        """Drop all trusted blocks above height (client.go:881-907)."""
        last = self.last_trusted_height()
        for h in range(height + 1, last + 1):
            self.trusted_store.delete_light_block(h)

    # -- internals ---------------------------------------------------------

    def _block_from(self, p: Provider, height: int) -> LightBlock:
        lb = p.light_block(height)
        if lb is None:
            raise LightBlockNotFoundError(height)
        try:
            lb.validate_basic(self.chain_id)
        except BadLightBlockError:
            raise
        except Exception as e:
            raise BadLightBlockError(e) from e
        return lb

    @staticmethod
    def _now(now_ns: int | None) -> int:
        return _time.time_ns() if now_ns is None else now_ns
