"""Persisted trusted light blocks (reference: light/store/db/db.go:328).

Backed by the shared KV abstraction (libs/db): keys are
``lb/<height:020d>`` so lexicographic iteration is height order; a size
key tracks the pair count for O(1) Size().
"""

from __future__ import annotations

from ..libs import sync as libsync

from ..libs import db as dbm
from ..libs.db import prefix_end
from ..types import serialization as ser
from ..types.light_block import LightBlock
from .errors import LightBlockNotFoundError

_PREFIX = b"lb/"
_SIZE_KEY = b"lb_size"


def _key(height: int) -> bytes:
    return _PREFIX + b"%020d" % height


class Store:
    """Trusted light block store with the reference Store contract."""

    def __init__(self, db: dbm.DB | None = None):
        self._db = db if db is not None else dbm.MemDB()
        self._mtx = libsync.Mutex("light.store._mtx")

    # -- writes ------------------------------------------------------------

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("height must be positive")
        with self._mtx:  # cometlint: disable=CLNT009 -- light-store writes are atomic under its mutex; off the consensus hot path
            existed = self._db.get(_key(lb.height)) is not None
            self._db.set(_key(lb.height), ser.dumps(lb))
            if not existed:
                self._bump_size(+1)

    def delete_light_block(self, height: int) -> None:
        if height <= 0:
            raise ValueError("height must be positive")
        with self._mtx:  # cometlint: disable=CLNT009 -- light-store deletes are atomic under its mutex; off the consensus hot path
            if self._db.get(_key(height)) is not None:
                self._db.delete(_key(height))
                self._bump_size(-1)

    def prune(self, size: int) -> None:
        """Delete oldest blocks until at most ``size`` remain
        (light/store/db/db.go Prune)."""
        with self._mtx:  # cometlint: disable=CLNT009 -- light-store pruning is atomic under its mutex; off the consensus hot path
            excess = self._size() - size
            if excess <= 0:
                return
            for k, _ in self._iter():
                if excess == 0:
                    break
                self._db.delete(k)
                self._bump_size(-1)
                excess -= 1

    # -- reads -------------------------------------------------------------

    def light_block(self, height: int) -> LightBlock:
        if height <= 0:
            raise ValueError("height must be positive")
        raw = self._db.get(_key(height))
        if raw is None:
            raise LightBlockNotFoundError(height)
        return ser.loads(raw)

    def last_light_block_height(self) -> int:
        """-1 when empty (store.go:27-30)."""
        for k, _ in self._db.reverse_iterator(_PREFIX, prefix_end(_PREFIX)):
            return int(k[len(_PREFIX):])
        return -1

    def first_light_block_height(self) -> int:
        for k, _ in self._iter():
            return int(k[len(_PREFIX):])
        return -1

    def light_block_before(self, height: int) -> LightBlock:
        """Latest stored block strictly below ``height``."""
        for _, v in self._db.reverse_iterator(_PREFIX, _key(height)):
            return ser.loads(v)
        raise LightBlockNotFoundError(height)

    def size(self) -> int:
        with self._mtx:
            return self._size()

    # -- internals ---------------------------------------------------------

    def _iter(self):
        return self._db.iterator(_PREFIX, prefix_end(_PREFIX))

    def _size(self) -> int:
        raw = self._db.get(_SIZE_KEY)
        return int(raw) if raw else 0

    def _bump_size(self, delta: int) -> None:
        self._db.set(_SIZE_KEY, b"%d" % (self._size() + delta))


class MemStore:
    """Ephemeral trusted-block store with the same contract as Store.

    The light proof service (light/service.py) builds one per request:
    each client verifies relative to ITS OWN trust root, so request
    stores are short-lived and thrown away — paying the KV store's
    serialization round trip (ser.dumps/loads per save and load) for
    every bisection pivot of every request would dominate the service's
    host cost. This keeps the typed LightBlock objects directly.
    """

    def __init__(self):
        self._mtx = libsync.Mutex("light.store.MemStore._mtx")
        self._blocks: dict[int, LightBlock] = {}

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("height must be positive")
        with self._mtx:
            self._blocks[lb.height] = lb

    def delete_light_block(self, height: int) -> None:
        if height <= 0:
            raise ValueError("height must be positive")
        with self._mtx:
            self._blocks.pop(height, None)

    def prune(self, size: int) -> None:
        with self._mtx:
            excess = len(self._blocks) - size
            for h in sorted(self._blocks):
                if excess <= 0:
                    break
                del self._blocks[h]
                excess -= 1

    def light_block(self, height: int) -> LightBlock:
        if height <= 0:
            raise ValueError("height must be positive")
        with self._mtx:
            lb = self._blocks.get(height)
        if lb is None:
            raise LightBlockNotFoundError(height)
        return lb

    def last_light_block_height(self) -> int:
        with self._mtx:
            return max(self._blocks) if self._blocks else -1

    def first_light_block_height(self) -> int:
        with self._mtx:
            return min(self._blocks) if self._blocks else -1

    def light_block_before(self, height: int) -> LightBlock:
        with self._mtx:
            below = [h for h in self._blocks if h < height]
            if below:
                return self._blocks[max(below)]
        raise LightBlockNotFoundError(height)

    def size(self) -> int:
        with self._mtx:
            return len(self._blocks)
