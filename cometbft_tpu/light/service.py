"""Light-client verification as a service: shared-device proof serving.

The ROADMAP's "millions of users" workload: thousands of concurrent
light clients each want skipping-verification of some commit against
their own trust root, and the dominant cost of every request is
commit-signature verification (arXiv:2410.03347 measures bisection
verification dominating committee-based light clients; arXiv:2302.00418
pins that to EdDSA commit checks). One node already owns the fast path
for exactly that work — the batched verifiers and the cross-caller
VerifyCoalescer — but only for in-process callers. ``LightService``
turns it into a service with three pillars:

* **Shared verification planes** — every request runs the standard
  light ``Client`` bisection, but its commit checks go through a
  :class:`CachedCommitVerifier` plane that delegates to
  types/validation's batched verifiers. Sub-crossover commits ride the
  routed VerifyCoalescer (crypto/coalesce), so N concurrent clients'
  trust-gap proofs pack their signature lanes into the SAME device
  windows instead of racing N separate launches.
* **Commit-verification result cache** — successful checks are cached
  by ``(kind, chain_id, height, valset_hash, commit_digest)`` with TTL
  + LRU bounds, and concurrent verifications of the same key are
  single-flighted (one underlying verify; waiters share its outcome).
  Failures are NEVER cached (negative-result poisoning protection): a
  transient fault or an attacker-fed bad commit can only cost its own
  request, never poison a later honest one — and a failed verification
  can never be replayed as a cached success.
* **Backpressure + deadlines** — at most ``max_inflight`` requests
  verify at once; up to ``max_queue`` more wait for a slot and anything
  beyond that is rejected immediately (queue-depth rejection). Each
  request carries a deadline that propagates through
  ``crypto/coalesce.request_deadline`` into every coalescer ticket wait
  and provider fetch, so a deadline-exceeded request unwinds cleanly —
  no leaked in-flight slot, no post-deadline device work.

Per-request isolation: each request verifies relative to the CLIENT's
trust root in a throwaway :class:`~cometbft_tpu.light.store.MemStore`,
so one client's root never widens another's trust — the shared state is
only the (verdict-identical) commit result cache. Results are therefore
bit-identical to a standalone ``Client`` run with the same options.

The RPC surface is ``light_verify`` / ``light_status`` on
rpc/core/routes.py, served by the existing jsonrpc server; the node
boots the service behind ``COMETBFT_TPU_LIGHT`` (node/node.py).

Locking: ``light.service._mtx`` guards admission (in-flight/queue
counters; its condition wait is the sanctioned own-lock case) and
``light.service._cache_mtx`` guards the result cache. The cache lock is
a LEAF — nothing is acquired and nothing blocks under it (asserted
edge-free in tests/test_lint_graph.py like ``libs.trace._mtx``): the
single-flight leader verifies OUTSIDE it and publishes code-last.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from ..crypto import coalesce as crypto_coalesce
from ..crypto import tmhash
from ..libs import devledger as libdevledger
from ..libs import metrics as libmetrics
from ..libs import sync as libsync
from ..libs.service import BaseService
from ..types import serialization as ser
from ..types.validation import (
    DEFAULT_TRUST_LEVEL,
    Fraction,
    verify_commit_light,
    verify_commit_light_trusting,
)
from . import verifier as light_verifier
from .client import Client, TrustOptions
from .errors import LightClientError
from .provider import Provider
from .store import MemStore

SECOND_NS = light_verifier.SECOND_NS

_DEFAULT_MAX_INFLIGHT = 64
_DEFAULT_MAX_QUEUE = 256
_DEFAULT_DEADLINE_S = 10.0
_DEFAULT_CACHE_SIZE = 4096
_DEFAULT_CACHE_TTL_S = 600.0
_DEFAULT_TRUSTING_PERIOD_NS = 14 * 24 * 3600 * SECOND_NS
# poll granularity of a single-flight waiter between outcome checks
_FLIGHT_WAIT_S = 0.05


class LightServiceError(LightClientError):
    """Base of the service's request-rejection taxonomy (the RPC layer
    maps each subclass to a distinct JSON-RPC error code)."""


class ServiceBusyError(LightServiceError):
    """Backpressure rejection: in-flight AND queue bounds both full."""


class ServiceStoppedError(LightServiceError):
    """Request arrived after the drain began (or before start)."""


class DeadlineExceededError(LightServiceError):
    """The request's deadline expired before verification finished."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def configured_mode() -> str:
    """COMETBFT_TPU_LIGHT: "0"/off (default) | "1"/on — serve
    light_verify/light_status from this node."""
    v = os.environ.get("COMETBFT_TPU_LIGHT", "0").lower()
    if v in ("1", "on", "true", "yes"):
        return "on"
    return "off"


def node_wants_light_service() -> bool:
    """Whether a booting node should start a LightService."""
    return configured_mode() == "on"


def _check_deadline(what: str = "") -> None:
    rem = crypto_coalesce.deadline_remaining()
    if rem is not None and rem <= 0:
        raise DeadlineExceededError(
            "request deadline exceeded" + (f" ({what})" if what else "")
        )


def _find_deadline(exc: BaseException) -> DeadlineExceededError | None:
    """Dig a DeadlineExceededError out of the wrapper chain.

    The light client wraps causes (VerificationFailedError.reason,
    BadLightBlockError.reason, __cause__/__context__) — a deadline that
    fired deep inside a commit check must still surface as a clean
    deadline rejection, not a generic verification failure."""
    seen: set[int] = set()
    stack: list = [exc]
    while stack:
        e = stack.pop()
        if not isinstance(e, BaseException) or id(e) in seen:
            continue
        seen.add(id(e))
        if isinstance(e, DeadlineExceededError):
            return e
        stack.extend(
            (getattr(e, "reason", None), e.__cause__, e.__context__)
        )
    return None


class _Flight:
    """One in-progress commit verification being single-flighted."""

    __slots__ = ("event", "ok", "exc")

    def __init__(self):
        self.event = threading.Event()
        self.ok = False
        self.exc: BaseException | None = None


class CommitResultCache:
    """TTL + LRU cache of SUCCESSFUL commit verifications, with
    single-flight dedupe of concurrent identical checks.

    Only success is ever cached: verification failures propagate to the
    requester (and to concurrent single-flight waiters of the same key
    — verification is deterministic) but leave no entry behind, so a
    fault can never be replayed and a failure can never masquerade as a
    cached success. ``now`` is injectable for TTL tests.

    The one lock, ``light.service._cache_mtx``, is a leaf: every body
    below is pure dict bookkeeping — no metric, no other lock, no
    blocking call runs under it (tests/test_lint_graph.py pins it
    edge-free like libs.trace._mtx).
    """

    def __init__(
        self,
        capacity: int = _DEFAULT_CACHE_SIZE,
        ttl_s: float = _DEFAULT_CACHE_TTL_S,
        now=time.monotonic,
    ):
        self.capacity = max(1, int(capacity))
        self.ttl_s = float(ttl_s)
        self._now = now
        self._mtx = libsync.Mutex("light.service._cache_mtx")
        self._entries: OrderedDict[tuple, float] = OrderedDict()
        self._flights: dict[tuple, _Flight] = {}
        self.hits = 0
        self.misses = 0
        self.shared = 0
        self.evictions = 0
        self.expired = 0

    def begin(self, key: tuple, recheck: bool = False):
        """One lookup step: ("hit", None) — cached success;
        ("leader", None) — this caller must verify and call done();
        ("wait", flight) — another caller is verifying this key.

        Stats count ONE outcome per logical lookup: a waiter's re-polls
        pass ``recheck=True`` so the wait state tallies nothing here
        (the resolution — shared success, shared failure, or promotion
        to leader — does the counting), and a post-wait cache hit
        counts as ``shared``, not ``hit``.
        """
        with self._mtx:
            exp = self._entries.get(key)
            if exp is not None:
                if self._now() < exp:
                    self._entries.move_to_end(key)
                    if recheck:
                        self.shared += 1
                    else:
                        self.hits += 1
                    return "hit", None
                del self._entries[key]
                self.expired += 1
            fl = self._flights.get(key)
            if fl is not None:
                return "wait", fl
            self._flights[key] = _Flight()
            self.misses += 1
            return "leader", None

    def note_shared(self) -> None:
        """A waiter resolved through the flight outcome directly."""
        with self._mtx:
            self.shared += 1

    def done(self, key: tuple, success: bool,
             exc: BaseException | None = None) -> None:
        """Publish the leader's outcome and release the flight."""
        with self._mtx:
            fl = self._flights.pop(key, None)
            if success:
                self._entries[key] = self._now() + self.ttl_s
                self._entries.move_to_end(key)
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                    self.evictions += 1
        if fl is not None:
            # outcome fields BEFORE the event: a waiter that sees the
            # event set must see a consistent verdict
            fl.ok = success
            fl.exc = exc
            fl.event.set()

    def size(self) -> int:
        with self._mtx:
            return len(self._entries)

    def stats(self) -> dict:
        with self._mtx:
            return {
                "size": len(self._entries),
                "capacity": self.capacity,
                "ttl_s": self.ttl_s,
                "hits": self.hits,
                "misses": self.misses,
                "shared": self.shared,
                "evictions": self.evictions,
                "expired": self.expired,
            }


def _commit_digest(commit) -> bytes:
    """Stable digest of a commit's full content (block id + every
    commit-sig) — the cache key component that pins WHAT was verified."""
    return tmhash.sum(ser.dumps(commit))


class CachedCommitVerifier(light_verifier.CommitVerifier):
    """The service's shared verification plane.

    Misses delegate to the standard types/validation commit checks (the
    batched verifiers; sub-crossover commits ride the routed
    VerifyCoalescer) — so verdicts are bit-identical to the default
    plane — while hits and single-flight waiters skip the signature
    work entirely. Every entry point honors the thread's
    ``crypto/coalesce.request_deadline`` budget.
    """

    def __init__(self, cache: CommitResultCache):
        self.cache = cache

    def verify_commit_light(
        self, chain_id, vals, block_id, height, commit
    ) -> None:
        key = (
            "light",
            chain_id,
            height,
            bytes(vals.hash()),
            _commit_digest(commit),
            # the FULL expected block id, not just its hash:
            # verify_commit_light compares part_set_header too, and a
            # cached success must never mask a mismatch there
            tmhash.sum(ser.dumps(block_id)),
        )
        # outermost ledger tenant: a proof-service client's coalescer
        # lanes attribute to "light", not the commit-verify mechanism
        with libdevledger.caller_class("light"):
            self._cached(
                key,
                lambda: verify_commit_light(
                    chain_id, vals, block_id, height, commit
                ),
            )

    def verify_commit_light_trusting(
        self, chain_id, vals, commit, trust_level
    ) -> None:
        key = (
            "trusting",
            chain_id,
            commit.height,
            bytes(vals.hash()),
            _commit_digest(commit),
            (trust_level.numerator, trust_level.denominator),
        )
        with libdevledger.caller_class("light"):
            self._cached(
                key,
                lambda: verify_commit_light_trusting(
                    chain_id, vals, commit, trust_level
                ),
            )

    def _cached(self, key: tuple, run) -> None:
        m = libmetrics.node_metrics()
        waited = False
        while True:
            _check_deadline("commit verification")
            state, flight = self.cache.begin(key, recheck=waited)
            if state == "hit":
                # a hit after waiting is the flight's success landing
                # in the cache: one logical lookup, counted shared
                m.light_cache_lookups.labels(
                    "shared" if waited else "hit"
                ).inc()
                return
            if state == "wait":
                waited = True
                rem = crypto_coalesce.deadline_remaining()
                wait_s = _FLIGHT_WAIT_S if rem is None \
                    else max(min(rem, _FLIGHT_WAIT_S), 0.0)
                flight.event.wait(wait_s)
                if flight.event.is_set():
                    if flight.ok:
                        self.cache.note_shared()
                        m.light_cache_lookups.labels("shared").inc()
                        return
                    exc = flight.exc
                    if exc is not None and _find_deadline(exc) is None:
                        # deterministic verification: the leader's
                        # failure IS this caller's failure
                        self.cache.note_shared()
                        m.light_cache_lookups.labels("shared").inc()
                        raise exc
                    # the leader aborted on ITS OWN deadline — that
                    # says nothing about the commit; retry as leader
                    # (this caller's deadline bounds the loop)
                # leader still running: loop — the deadline check
                # bounds this; re-polls count nothing
                continue
            # leader: verify OUTSIDE the cache lock, publish code-last
            # (a waiter promoted to leader really verifies: a miss)
            m.light_cache_lookups.labels("miss").inc()
            exc: BaseException | None = None
            try:
                run()
            except BaseException as e:
                exc = e
                raise
            finally:
                self.cache.done(key, exc is None, exc)
            return


class _DeadlineProvider(Provider):
    """Per-request provider wrapper: the request deadline is checked
    before AND after every fetch, so a stalled provider cannot burn
    post-deadline verification work (the fetch itself is bounded by the
    provider's own timeout — rpc_provider carries retry + per-call
    timeout)."""

    def __init__(self, inner: Provider):
        self._inner = inner

    def chain_id(self) -> str:
        return self._inner.chain_id()

    def light_block(self, height: int):
        _check_deadline(f"fetching light block {height}")
        lb = self._inner.light_block(height)
        _check_deadline(f"fetched light block {height}")
        return lb

    def report_evidence(self, ev) -> None:
        self._inner.report_evidence(ev)


class LightService(BaseService):
    """Skipping-verification proof service over one shared device.

    ``verify_at_height`` is the whole request surface: admit under the
    backpressure bounds, build a per-request ``Client`` rooted at the
    caller's trust height (or the service's own root), run the standard
    bisection with the caching plane, and return the verified block's
    identity. ``stop()`` drains: queued waiters are rejected
    immediately, in-flight requests complete (each bounded by its own
    deadline) before stop returns.
    """

    def __init__(
        self,
        provider: Provider,
        chain_id: str,
        trust_options: TrustOptions | None = None,
        witnesses=(),
        trusting_period_ns: int = _DEFAULT_TRUSTING_PERIOD_NS,
        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = light_verifier.DEFAULT_MAX_CLOCK_DRIFT_NS,
        root_height: int = 1,
        max_inflight: int | None = None,
        max_queue: int | None = None,
        default_deadline_s: float | None = None,
        cache_size: int | None = None,
        cache_ttl_s: float | None = None,
        own_coalescer: bool = False,
        coalescer_device: bool | None = None,
        coalescer_window_us: int | None = None,
        logger=None,
    ):
        super().__init__("LightService", logger)
        self.provider = provider
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.witnesses = list(witnesses)
        self.trusting_period_ns = trusting_period_ns
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.root_height = root_height
        self.max_inflight = max(
            1,
            max_inflight
            if max_inflight is not None
            else _env_int(
                "COMETBFT_TPU_LIGHT_MAX_INFLIGHT", _DEFAULT_MAX_INFLIGHT
            ),
        )
        self.max_queue = max(
            0,
            max_queue
            if max_queue is not None
            else _env_int("COMETBFT_TPU_LIGHT_MAX_QUEUE", _DEFAULT_MAX_QUEUE),
        )
        self.default_deadline_s = (
            default_deadline_s
            if default_deadline_s is not None
            else _env_float(
                "COMETBFT_TPU_LIGHT_DEADLINE_S", _DEFAULT_DEADLINE_S
            )
        )
        self.cache = CommitResultCache(
            capacity=(
                cache_size
                if cache_size is not None
                else _env_int(
                    "COMETBFT_TPU_LIGHT_CACHE_SIZE", _DEFAULT_CACHE_SIZE
                )
            ),
            ttl_s=(
                cache_ttl_s
                if cache_ttl_s is not None
                else _env_float(
                    "COMETBFT_TPU_LIGHT_CACHE_TTL_S", _DEFAULT_CACHE_TTL_S
                )
            ),
        )
        self.plane = CachedCommitVerifier(self.cache)
        # admission state under light.service._mtx; the condition's own
        # wait is the sanctioned case (queue waiters under their lock)
        self._mtx = libsync.Mutex("light.service._mtx")
        self._cv = libsync.Condition(self._mtx, name="light.service._mtx")
        self._accepting = False
        self._inflight = 0
        self._queued = 0
        self._counts = {
            "ok": 0, "error": 0, "rejected": 0, "deadline": 0, "stopped": 0,
        }
        self._lazy_root: TrustOptions | None = None
        self._want_own_coalescer = own_coalescer
        self._coalescer_device = coalescer_device
        self._coalescer_window_us = coalescer_window_us
        self._own_coalescer = None

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self._want_own_coalescer:
            co = crypto_coalesce.VerifyCoalescer(
                window_us=self._coalescer_window_us,
                device=self._coalescer_device,
                logger=self.logger,
            )
            co.start()
            crypto_coalesce.push_active(co)
            self._own_coalescer = co
        with self._mtx:
            self._accepting = True

    def on_stop(self) -> None:
        """Drain: reject queued waiters, let in-flight requests finish."""
        with self._mtx:
            self._accepting = False
            self._cv.notify_all()
        # every in-flight request is bounded by its own deadline; the
        # slack covers unwind work after the deadline fires
        limit = time.monotonic() + self.default_deadline_s + 5.0
        with self._mtx:
            while self._inflight > 0 and time.monotonic() < limit:
                self._cv.wait(0.1)
        if self._own_coalescer is not None:
            crypto_coalesce.pop_active(self._own_coalescer)
            try:
                if self._own_coalescer.is_running():
                    self._own_coalescer.stop()
            except Exception:
                pass

    # -- admission (backpressure) ------------------------------------------

    def _admit(self, deadline: float) -> None:
        with self._mtx:
            if not self._accepting:
                raise ServiceStoppedError("light service is not running")
            if self._inflight < self.max_inflight:
                self._inflight += 1
                return
            if self._queued >= self.max_queue:
                raise ServiceBusyError(
                    f"light service at capacity ({self.max_inflight} in "
                    f"flight, {self.max_queue} queued)"
                )
            self._queued += 1
            try:
                while self._accepting and self._inflight >= self.max_inflight:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        raise DeadlineExceededError(
                            "deadline exceeded waiting for an in-flight slot"
                        )
                    self._cv.wait(min(rem, 0.2))
                if not self._accepting:
                    raise ServiceStoppedError(
                        "light service stopped while queued"
                    )
                self._inflight += 1
            finally:
                self._queued -= 1

    def _release(self, outcome: str) -> int:
        with self._mtx:
            self._inflight -= 1
            self._counts[outcome] = self._counts.get(outcome, 0) + 1
            self._cv.notify_all()
            return self._inflight

    def _count_rejection(self, outcome: str) -> None:
        with self._mtx:
            self._counts[outcome] = self._counts.get(outcome, 0) + 1

    # -- the request surface -----------------------------------------------

    def verify_at_height(
        self,
        height: int,
        trust_height: int | None = None,
        trust_hash: bytes | None = None,
        deadline_s: float | None = None,
        now_ns: int | None = None,
    ) -> dict:
        """Serve one skipping-verification request.

        Verifies the chain's block at ``height`` relative to the
        caller's trust root (``trust_height``/``trust_hash``; the
        service's own root when omitted; the root's hash is fetched
        from the provider when only a height is given — the caller
        trusts this service's view, the usual proxy posture).
        ``deadline_s`` may only tighten the service default. Returns
        the verified block's identity and the bisection trace. Raises
        :class:`ServiceBusyError` (backpressure),
        :class:`DeadlineExceededError`, :class:`ServiceStoppedError`,
        or the standard light-client errors on verification failure.
        """
        if height is None or int(height) <= 0:
            raise LightServiceError("height must be positive")
        height = int(height)
        # a caller's deadline may only TIGHTEN the service default: the
        # default is also the drain bound (on_stop waits it out plus
        # slack) and the slot-hold ceiling — an unclamped client value
        # could pin every in-flight slot and outlive shutdown
        dl = self.default_deadline_s
        if deadline_s is not None:
            dl = min(max(float(deadline_s), 0.0), dl)
        deadline = time.monotonic() + dl
        m = libmetrics.node_metrics()
        t_enq = time.perf_counter()
        try:
            self._admit(deadline)
        except ServiceBusyError:
            self._count_rejection("rejected")
            m.light_requests.labels("rejected").inc()
            raise
        except ServiceStoppedError:
            self._count_rejection("stopped")
            m.light_requests.labels("stopped").inc()
            raise
        except DeadlineExceededError:
            self._count_rejection("deadline")
            m.light_requests.labels("deadline").inc()
            raise
        m.light_queue_wait.observe(time.perf_counter() - t_enq)
        m.light_inflight.set(self._inflight)
        outcome = "error"
        try:
            with crypto_coalesce.request_deadline(deadline):
                result = self._serve(height, trust_height, trust_hash, now_ns)
            outcome = "ok"
            return result
        except BaseException as e:
            dexc = _find_deadline(e)
            if dexc is not None:
                outcome = "deadline"
                if dexc is e:
                    raise
                raise DeadlineExceededError(str(dexc)) from e
            raise
        finally:
            left = self._release(outcome)
            m.light_requests.labels(outcome).inc()
            m.light_inflight.set(left)

    def _serve(self, height, trust_height, trust_hash, now_ns) -> dict:
        provider = _DeadlineProvider(self.provider)
        opts = self._request_options(provider, trust_height, trust_hash)
        client = Client(
            chain_id=self.chain_id,
            trust_options=opts,
            primary=provider,
            witnesses=list(self.witnesses),
            trusted_store=MemStore(),
            trust_level=self.trust_level,
            max_clock_drift_ns=self.max_clock_drift_ns,
            commit_verifier=self.plane,
        )
        lb = client.verify_light_block_at_height(height, now_ns)
        return {
            "height": str(lb.height),
            "hash": lb.hash().hex().upper(),
            "time_ns": str(lb.signed_header.time_ns),
            "trust_height": str(opts.height),
            "trust_hash": opts.hash.hex().upper(),
            "verified_heights": [b.height for b in client.latest_trace],
        }

    def _request_options(
        self, provider, trust_height, trust_hash
    ) -> TrustOptions:
        if trust_height is None:
            return self._root_options(provider)
        th = int(trust_height)
        if th <= 0:
            raise LightServiceError("trust_height must be positive")
        if trust_hash:
            root = bytes(trust_hash)
        else:
            root = provider.light_block(th).hash()
        return TrustOptions(
            period_ns=self.trusting_period_ns, height=th, hash=root
        )

    def _root_options(self, provider) -> TrustOptions:
        """The service's own root of trust: the ctor's options, or a
        lazily-derived root at ``root_height`` — derived on first use
        because a freshly-booted node may not have any block yet."""
        if self.trust_options is not None:
            return self.trust_options
        opts = self._lazy_root
        if opts is not None:
            return opts
        lb = provider.light_block(self.root_height)
        opts = TrustOptions(
            period_ns=self.trusting_period_ns,
            height=lb.height,
            hash=lb.hash(),
        )
        # benign race: two first requests derive identical roots
        self._lazy_root = opts
        return opts

    # -- introspection (the light_status route) ----------------------------

    def status(self) -> dict:
        with self._mtx:
            counts = dict(self._counts)
            inflight = self._inflight
            queued = self._queued
            running = self._accepting
        out = {
            "running": running,
            "inflight": inflight,
            "queued": queued,
            "max_inflight": self.max_inflight,
            "max_queue": self.max_queue,
            "default_deadline_s": self.default_deadline_s,
            "requests": counts,
            "cache": self.cache.stats(),
        }
        root = self.trust_options or self._lazy_root
        if root is not None:
            out["root"] = {
                "height": str(root.height),
                "hash": root.hash.hex().upper(),
            }
        co = self._own_coalescer or crypto_coalesce.active()
        if co is not None:
            out["coalescer"] = {
                "windows": co.windows,
                "device_windows": co.device_windows,
                "tickets": co.tickets,
            }
        return out
