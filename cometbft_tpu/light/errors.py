"""Light client error taxonomy (reference: light/errors.go).

The error TYPE drives control flow: bisection pivots on
NewValSetCantBeTrustedError, the client replaces providers on
BadLightBlockError/UnreliableProviderError, and the detector reacts to
header conflicts — so these are real classes, not strings.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class LightClientError(Exception):
    pass


@dataclass
class OldHeaderExpiredError(LightClientError):
    """Trusted header is outside the trusting period (errors.go:16)."""

    expired_at_ns: int
    now_ns: int

    def __str__(self) -> str:
        return (
            f"old header has expired at {self.expired_at_ns} "
            f"(now: {self.now_ns})"
        )


@dataclass
class InvalidHeaderError(LightClientError):
    """New header could not be verified (errors.go:48)."""

    reason: Exception

    def __str__(self) -> str:
        return f"invalid header: {self.reason}"


@dataclass
class NewValSetCantBeTrustedError(LightClientError):
    """< trust-level of the trusted set signed the new header — the
    bisection signal, NOT a failure (errors.go:38)."""

    reason: Exception

    def __str__(self) -> str:
        return f"cant trust new val set: {self.reason}"


@dataclass
class VerificationFailedError(LightClientError):
    """Verification chain broke between two heights (errors.go:26)."""

    from_height: int
    to_height: int
    reason: Exception

    def __str__(self) -> str:
        return (
            f"verify from #{self.from_height} to #{self.to_height} "
            f"failed: {self.reason}"
        )


@dataclass
class LightBlockNotFoundError(LightClientError):
    """Provider has no block at the height (provider/errors.go:12)."""

    height: int = 0

    def __str__(self) -> str:
        return f"light block at height {self.height} not found"


@dataclass
class NoWitnessesError(LightClientError):
    """All witnesses exhausted (errors.go:77)."""

    def __str__(self) -> str:
        return "no witnesses connected. please reset light client"


@dataclass
class BadLightBlockError(LightClientError):
    """Provider returned a malformed/foreign light block — malevolent
    signal, provider must be dropped (provider/errors.go:22)."""

    reason: Exception

    def __str__(self) -> str:
        return f"bad light block: {self.reason}"


@dataclass
class ConflictingHeadersError(LightClientError):
    """A witness returned a header conflicting with the primary
    (errors.go:84) — input to the attack detector."""

    block: object  # LightBlock from the witness
    witness_index: int = 0

    def __str__(self) -> str:
        return (
            f"witness #{self.witness_index} has a different header at "
            f"height {getattr(self.block, 'height', '?')}"
        )


@dataclass
class FailedHeaderCrossReferencingError(LightClientError):
    """All witnesses failed to respond during cross-checking
    (errors.go:60)."""

    errors: list = field(default_factory=list)

    def __str__(self) -> str:
        return f"all witnesses failed cross-referencing: {self.errors}"
