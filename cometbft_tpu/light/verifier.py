"""Core light-client verification (reference: light/verifier.go).

Both checks bottom out in the batched commit verifiers
(types/validation.py), i.e. the TPU kernel for big validator sets and the
OpenSSL host path for small ones — a 10k-validator light replay is a
handful of device launches, which is the BASELINE "light replay" bench
configuration.
"""

from __future__ import annotations

from ..types.light_block import SignedHeader
from ..types.validation import (
    DEFAULT_TRUST_LEVEL,
    Fraction,
    NotEnoughVotingPowerError,
    verify_commit_light,
    verify_commit_light_trusting,
)
from ..types.validator_set import ValidatorSet
from .errors import (
    InvalidHeaderError,
    LightClientError,
    NewValSetCantBeTrustedError,
    OldHeaderExpiredError,
)

SECOND_NS = 1_000_000_000
DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * SECOND_NS


class CommitVerifier:
    """Pluggable commit-verification plane for the light checks.

    The default plane delegates straight to types/validation — i.e. the
    batched commit verifiers (crypto/batch.create_commit_batch_verifier
    under the hood: one device launch or one host MSM per commit, with
    sub-crossover batches riding the cross-caller coalescer when one is
    routed). light/service.py substitutes a caching + single-flight +
    deadline-aware plane so thousands of concurrent proof requests
    share one verification of each (height, valset, commit) triple.
    Any plane MUST be verdict-identical to this default — planes may
    dedupe or reroute the work, never change an answer.
    """

    def verify_commit_light(
        self, chain_id, vals, block_id, height, commit
    ) -> None:
        verify_commit_light(chain_id, vals, block_id, height, commit)

    def verify_commit_light_trusting(
        self, chain_id, vals, commit, trust_level
    ) -> None:
        verify_commit_light_trusting(chain_id, vals, commit, trust_level)


DEFAULT_COMMIT_VERIFIER = CommitVerifier()


def validate_trust_level(lvl: Fraction) -> None:
    """Trust level must lie in [1/3, 1] (verifier.go:197-205)."""
    if (
        lvl.numerator * 3 < lvl.denominator
        or lvl.numerator > lvl.denominator
        or lvl.denominator == 0
    ):
        raise LightClientError(
            f"trustLevel must be within [1/3, 1], given {lvl}"
        )


def header_expired(h: SignedHeader, trusting_period_ns: int, now_ns: int) -> bool:
    """verifier.go:208-211."""
    return h.time_ns + trusting_period_ns <= now_ns


def _verify_new_header_and_vals(
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusted_header: SignedHeader,
    now_ns: int,
    max_clock_drift_ns: int,
) -> None:
    """verifier.go:153-195."""
    untrusted_header.validate_basic(trusted_header.chain_id)
    if untrusted_header.height <= trusted_header.height:
        raise ValueError(
            f"expected new header height {untrusted_header.height} to be "
            f"greater than old header height {trusted_header.height}"
        )
    if untrusted_header.time_ns <= trusted_header.time_ns:
        raise ValueError(
            "expected new header time to be after old header time"
        )
    if untrusted_header.time_ns >= now_ns + max_clock_drift_ns:
        raise ValueError(
            f"new header has a time from the future "
            f"({untrusted_header.time_ns} > now {now_ns} + drift "
            f"{max_clock_drift_ns})"
        )
    if untrusted_header.header.validators_hash != untrusted_vals.hash():
        raise ValueError(
            "header validators_hash does not match supplied validator set"
        )


def verify_adjacent(
    trusted_header: SignedHeader,  # height X
    untrusted_header: SignedHeader,  # height X+1
    untrusted_vals: ValidatorSet,  # height X+1
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
    commit_verifier: CommitVerifier | None = None,
) -> None:
    """Hash-chain + 2/3 check for adjacent headers (verifier.go:93-132)."""
    cv = commit_verifier if commit_verifier is not None \
        else DEFAULT_COMMIT_VERIFIER
    if untrusted_header.height != trusted_header.height + 1:
        raise LightClientError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now_ns):
        raise OldHeaderExpiredError(
            trusted_header.time_ns + trusting_period_ns, now_ns
        )
    try:
        _verify_new_header_and_vals(
            untrusted_header, untrusted_vals, trusted_header,
            now_ns, max_clock_drift_ns,
        )
    except Exception as e:
        raise InvalidHeaderError(e) from e
    if (
        untrusted_header.header.validators_hash
        != trusted_header.header.next_validators_hash
    ):
        raise LightClientError(
            "expected old header next validators to match those from new "
            "header"
        )
    try:
        cv.verify_commit_light(
            trusted_header.chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.height,
            untrusted_header.commit,
        )
    except Exception as e:
        raise InvalidHeaderError(e) from e


def verify_non_adjacent(
    trusted_header: SignedHeader,  # height X
    trusted_vals: ValidatorSet,  # height X or X+1
    untrusted_header: SignedHeader,  # height Y
    untrusted_vals: ValidatorSet,  # height Y
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    commit_verifier: CommitVerifier | None = None,
) -> None:
    """Skipping verification (verifier.go:32-80): trust-level fraction of
    the TRUSTED set plus 2/3 of the NEW set must have signed.

    The order of the two commit checks matters: the trusted-set check runs
    first because untrusted_vals can be made arbitrarily large to DoS the
    client (verifier.go:69-72)."""
    cv = commit_verifier if commit_verifier is not None \
        else DEFAULT_COMMIT_VERIFIER
    if untrusted_header.height == trusted_header.height + 1:
        raise LightClientError("headers must be non adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now_ns):
        raise OldHeaderExpiredError(
            trusted_header.time_ns + trusting_period_ns, now_ns
        )
    try:
        _verify_new_header_and_vals(
            untrusted_header, untrusted_vals, trusted_header,
            now_ns, max_clock_drift_ns,
        )
    except Exception as e:
        raise InvalidHeaderError(e) from e

    try:
        cv.verify_commit_light_trusting(
            trusted_header.chain_id,
            trusted_vals,
            untrusted_header.commit,
            trust_level,
        )
    except NotEnoughVotingPowerError as e:
        raise NewValSetCantBeTrustedError(e) from e

    try:
        cv.verify_commit_light(
            trusted_header.chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.height,
            untrusted_header.commit,
        )
    except Exception as e:
        raise InvalidHeaderError(e) from e


def verify(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    commit_verifier: CommitVerifier | None = None,
) -> None:
    """Dispatch adjacent/non-adjacent (verifier.go:135-151)."""
    if untrusted_header.height != trusted_header.height + 1:
        verify_non_adjacent(
            trusted_header, trusted_vals, untrusted_header, untrusted_vals,
            trusting_period_ns, now_ns, max_clock_drift_ns, trust_level,
            commit_verifier,
        )
    else:
        verify_adjacent(
            trusted_header, untrusted_header, untrusted_vals,
            trusting_period_ns, now_ns, max_clock_drift_ns,
            commit_verifier,
        )


def verify_backwards(untrusted_header, trusted_header) -> None:
    """Hash-chain check one height backwards (verifier.go:214-244):
    trusted.last_block_id.hash must equal hash(untrusted)."""
    untrusted_header.validate_basic()
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise InvalidHeaderError(ValueError("header belongs to another chain"))
    if untrusted_header.time_ns >= trusted_header.time_ns:
        raise InvalidHeaderError(
            ValueError("expected older header time to be before newer")
        )
    if trusted_header.last_block_id.hash != untrusted_header.hash():
        raise InvalidHeaderError(
            ValueError(
                "trusted header last_block_id does not match hash of "
                "older header"
            )
        )
