"""RPC-backed light block provider (reference: light/provider/http).

Fetches /commit + /validators from a full node's RPC and reconstructs the
typed LightBlock. Paginates the validator set so 10k-validator chains
(the BASELINE light-replay scale) work within the per_page cap.

Transport faults are retried with exponential backoff under a per-call
timeout: a slow or flapping witness must stall ONE fetch for at most
``timeout * (retries + 1)`` plus the backoff sleeps, never the whole
bisection (the reference's http provider carries the same
timeout-per-request posture, provider/http/http.go).
"""

from __future__ import annotations

import time

from ..rpc import decoding as dec
from ..rpc.client import HTTPClient, RPCError
from ..types.light_block import LightBlock, SignedHeader
from .errors import BadLightBlockError, LightBlockNotFoundError
from .provider import Provider


class RPCProvider(Provider):
    def __init__(
        self,
        address: str,
        chain_id: str,
        timeout: float = 10.0,
        retries: int = 2,
        backoff_s: float = 0.25,
    ):
        self._client = HTTPClient(address, timeout=timeout)
        self._chain_id = chain_id
        self._retries = max(0, int(retries))
        self._backoff_s = max(0.0, backoff_s)

    def chain_id(self) -> str:
        return self._chain_id

    def _call(self, method: str, **params):
        """RPC call with per-call timeout + retry-with-backoff.

        An :class:`RPCError` is the NODE answering (method error, height
        pruned, ...) — retrying cannot change it, so it propagates
        immediately. Anything else (connect refused, socket timeout,
        short read) is transport noise: retried ``retries`` times with
        exponential backoff, then the last fault propagates for the
        caller's provider-replacement logic.
        """
        last: Exception | None = None
        for attempt in range(self._retries + 1):
            try:
                return self._client.call(method, **params)
            except RPCError:
                raise
            except Exception as e:
                last = e
                if attempt < self._retries:
                    self._sleep(self._backoff_s * (2 ** attempt))
        raise last  # type: ignore[misc]

    @staticmethod
    def _sleep(seconds: float) -> None:
        """Backoff between retries (split out so tests fake it)."""
        if seconds > 0:
            time.sleep(seconds)  # cometlint: disable=CLNT009 -- bounded retry backoff on a provider fetch: light-client bisection runs on RPC/service request threads, never under an engine mutex

    def light_block(self, height: int) -> LightBlock:
        params = {} if height == 0 else {"height": str(height)}
        try:
            commit_res = self._call("commit", **params)
        except RPCError as e:
            raise LightBlockNotFoundError(height) from e
        sh_json = commit_res["signed_header"]
        header = dec.dec_header(sh_json["header"])
        commit = dec.dec_commit(sh_json["commit"])
        vals = self._validators(header.height)
        lb = LightBlock(
            signed_header=SignedHeader(header=header, commit=commit),
            validator_set=vals,
        )
        try:
            lb.validate_basic(self._chain_id)
        except Exception as e:
            raise BadLightBlockError(e) from e
        return lb

    def _validators(self, height: int):
        rows: list[dict] = []
        page = 1
        while True:
            try:
                res = self._call(
                    "validators",
                    height=str(height),
                    page=str(page),
                    per_page="100",
                )
            except RPCError as e:
                raise LightBlockNotFoundError(height) from e
            rows.extend(res["validators"])
            if len(rows) >= int(res["total"]) or not res["validators"]:
                break
            page += 1
        return dec.dec_validator_set(rows)

    def report_evidence(self, ev) -> None:
        """Submit attack evidence to the node's broadcast_evidence route
        (light/provider/http ReportEvidence). Failures are swallowed:
        the detector reports to every witness best-effort."""
        import base64

        from ..types import serialization as ser

        try:
            self._call(
                "broadcast_evidence",
                evidence=base64.b64encode(ser.dumps(ev)).decode(),
            )
        except Exception:
            pass
