"""Light client attack detection (reference: light/detector.go:424).

After a skipping verification the client holds a trace of verified light
blocks primary-side. The detector replays the target height against every
witness; a witness serving a conflicting header triggers divergence
examination: walk the primary trace to find the common (last agreed)
block, verify the witness's conflicting block from there, and — if the
witness proves a validly-signed conflicting header — build
LightClientAttackEvidence against the primary chain and report it to the
other providers.
"""

from __future__ import annotations

from ..types.evidence import LightClientAttackEvidence
from ..types.light_block import LightBlock
from . import verifier
from .errors import (
    ConflictingHeadersError,
    LightBlockNotFoundError,
    LightClientError,
)


def detect_divergence(client, now_ns: int | None = None) -> list:
    """Cross-check client.latest_trace's target against all witnesses
    (detector.go:48-142). Returns the evidence built (possibly empty);
    raises ConflictingHeadersError after reporting when an attack is
    proven, mirroring the reference's halt signal.
    """
    now_ns = client._now(now_ns)
    trace = client.latest_trace
    if len(trace) < 2 or not client.witnesses:
        return []
    target = trace[-1]
    evidence: list[LightClientAttackEvidence] = []
    bad_witnesses: list[int] = []
    for i, witness in enumerate(client.witnesses):
        try:
            alt = witness.light_block(target.height)
        except LightBlockNotFoundError:
            continue
        except Exception:
            bad_witnesses.append(i)
            continue
        if alt.hash() == target.hash():
            continue
        try:
            ev = examine_conflicting_header_against_trace(
                trace, alt, witness, now_ns, client
            )
        except LightClientError:
            # witness can't even agree with the root of trust: faulty
            # witness, drop it and keep scanning the others
            bad_witnesses.append(i)
            continue
        if ev is not None:
            evidence.append(ev)
            # report against the primary to every witness + the primary
            witness.report_evidence(ev)
            client.primary.report_evidence(ev)
    if bad_witnesses:
        client.remove_witnesses(bad_witnesses)
    if evidence:
        raise ConflictingHeadersError(evidence[0].conflicting_block)
    return evidence


def examine_conflicting_header_against_trace(
    trace: list[LightBlock],
    divergent: LightBlock,
    source,
    now_ns: int,
    client,
) -> LightClientAttackEvidence | None:
    """detector.go:288-422: find the common block in the trace, then verify
    the divergent header from it using the witness as source. If it
    verifies, the PRIMARY equivocated: evidence targets the primary's
    block; the caller reports it."""
    common = None
    for lb in trace:
        try:
            alt = source.light_block(lb.height)
        except Exception:
            return None
        if alt.hash() == lb.hash():
            common = lb
        else:
            break
    if common is None:
        raise LightClientError(
            "witness disagrees with the root of trust itself"
        )
    # Verify the divergent block from the common checkpoint via the
    # witness's chain of headers (skipping verification).
    try:
        if divergent.height != common.height + 1:
            verifier.verify_non_adjacent(
                common.signed_header,
                common.validator_set,
                divergent.signed_header,
                divergent.validator_set,
                client.trust_options.period_ns,
                now_ns,
                client.max_clock_drift_ns,
                client.trust_level,
            )
        else:
            verifier.verify_adjacent(
                common.signed_header,
                divergent.signed_header,
                divergent.validator_set,
                client.trust_options.period_ns,
                now_ns,
                client.max_clock_drift_ns,
            )
    except Exception:
        # witness could not prove its header: witness is faulty, not the
        # primary — no evidence against the primary
        return None
    # Both chains verified from the common block: the primary's trace block
    # at the divergent height is the attack header from the witness's view;
    # evidence carries the PRIMARY's conflicting block.
    primary_block = trace[-1]
    byzantine = _byzantine_validators(common, primary_block, divergent)
    return LightClientAttackEvidence(
        conflicting_block=primary_block,
        common_height=common.height,
        byzantine_validators=byzantine,
        total_voting_power=common.validator_set.total_voting_power(),
        timestamp_ns=common.time_ns,
    )


def _byzantine_validators(common, primary_block, divergent) -> list:
    """Validators from the common set that signed the primary's conflicting
    commit (types/evidence.go GetByzantineValidators, equivocation case)."""
    out = []
    commit = primary_block.signed_header.commit
    from ..types.block import BLOCK_ID_FLAG_COMMIT

    for sig in commit.signatures:
        if sig.block_id_flag != BLOCK_ID_FLAG_COMMIT:
            continue
        idx, val = common.validator_set.get_by_address(sig.validator_address)
        if idx >= 0:
            out.append(val)
    return out
