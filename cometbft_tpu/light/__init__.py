"""Light client: trust-period verification over batched commit checks.

Reference: /root/reference/light/ (client.go, verifier.go, detector.go,
store/, provider/).
"""

from .client import Client, TrustOptions
from .detector import detect_divergence
from .errors import (
    BadLightBlockError,
    ConflictingHeadersError,
    InvalidHeaderError,
    LightBlockNotFoundError,
    LightClientError,
    NewValSetCantBeTrustedError,
    NoWitnessesError,
    OldHeaderExpiredError,
    VerificationFailedError,
)
from .provider import Provider, StoreBackedProvider
from .service import (
    CachedCommitVerifier,
    CommitResultCache,
    DeadlineExceededError,
    LightService,
    ServiceBusyError,
    ServiceStoppedError,
)
from .store import MemStore, Store
from .verifier import (
    CommitVerifier,
    header_expired,
    validate_trust_level,
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)

__all__ = [
    "Client",
    "TrustOptions",
    "detect_divergence",
    "Provider",
    "StoreBackedProvider",
    "Store",
    "MemStore",
    "LightService",
    "CommitResultCache",
    "CachedCommitVerifier",
    "CommitVerifier",
    "ServiceBusyError",
    "ServiceStoppedError",
    "DeadlineExceededError",
    "header_expired",
    "validate_trust_level",
    "verify",
    "verify_adjacent",
    "verify_backwards",
    "verify_non_adjacent",
    "BadLightBlockError",
    "ConflictingHeadersError",
    "InvalidHeaderError",
    "LightBlockNotFoundError",
    "LightClientError",
    "NewValSetCantBeTrustedError",
    "NoWitnessesError",
    "OldHeaderExpiredError",
    "VerificationFailedError",
]
