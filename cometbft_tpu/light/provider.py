"""Light block providers (reference: light/provider/provider.go).

A provider serves LightBlocks for a chain. The reference ships an
RPC-backed provider (light/provider/http); here the first-class citizens
are:

* ``StoreBackedProvider`` — reads a full node's block/state stores
  in-process (test fixtures, statesync's local path);
* the RPC client provider lives with the RPC layer (rpc/) once a node
  exposes HTTP, keeping this module transport-free.
"""

from __future__ import annotations

from ..types.light_block import LightBlock, SignedHeader
from .errors import BadLightBlockError, LightBlockNotFoundError


class Provider:
    """Provider interface (provider.go:9-32)."""

    def chain_id(self) -> str:
        raise NotImplementedError

    def light_block(self, height: int) -> LightBlock:
        """Return the light block at ``height`` (0 = latest). Raises
        LightBlockNotFoundError when unavailable."""
        raise NotImplementedError

    def report_evidence(self, ev) -> None:  # pragma: no cover - optional
        pass


class StoreBackedProvider(Provider):
    """Serve light blocks straight from a node's stores.

    Mirrors what the reference's local RPC provider returns: the signed
    header from the block store (header + its commit from height+1's
    LastCommit, i.e. the stored seen-commit) and the validator set from the
    state store.
    """

    def __init__(self, block_store, state_store, chain_id: str):
        self._bs = block_store
        self._ss = state_store
        self._chain_id = chain_id
        self._evidence: list = []

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self._bs.height()
        block = self._bs.load_block(height)
        # The canonical commit for height lands with block height+1; at the
        # tip fall back to the seen commit (rpc/core/blocks.go Commit).
        commit = self._bs.load_block_commit(height)
        if commit is None and height == self._bs.height():
            commit = self._bs.load_seen_commit()
            if commit is not None and commit.height != height:
                commit = None
        if block is None or commit is None:
            raise LightBlockNotFoundError(height)
        vals = self._ss.load_validators(height)
        if vals is None:
            raise LightBlockNotFoundError(height)
        lb = LightBlock(
            signed_header=SignedHeader(header=block.header, commit=commit),
            validator_set=vals,
        )
        try:
            lb.validate_basic(self._chain_id)
        except Exception as e:  # malformed data is a provider fault
            raise BadLightBlockError(e) from e
        return lb

    def report_evidence(self, ev) -> None:
        self._evidence.append(ev)
