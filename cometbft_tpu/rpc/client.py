"""RPC clients (reference: rpc/client/http/http.go, rpc/client/local).

``HTTPClient``  — JSON-RPC 2.0 over HTTP POST (stdlib urllib; zero deps).
``LocalClient`` — direct in-process dispatch against an Environment
                  (rpc/client/local semantics: no network, same handlers).

Both expose ``call(method, **params)`` plus pythonic helpers for the
common routes; results are the JSON dicts the server returns.
"""

from __future__ import annotations

import itertools
import json
import urllib.request

from .core.routes import ROUTES, RPCError


class HTTPClient:
    def __init__(self, base_url: str, timeout: float = 10.0):
        if base_url.startswith("tcp://"):
            base_url = "http://" + base_url[len("tcp://"):]
        if not base_url.startswith("http"):
            base_url = "http://" + base_url
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._ids = itertools.count(1)

    def call(self, method: str, **params):
        payload = {
            "jsonrpc": "2.0",
            "id": next(self._ids),
            "method": method,
            "params": params,
        }
        req = urllib.request.Request(
            self.base_url + "/",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = json.loads(resp.read())
        if "error" in body:
            err = body["error"]
            raise RPCError(
                err.get("message", "rpc error"),
                code=err.get("code", -32603),
                data=err.get("data", ""),
            )
        return body["result"]

    def __getattr__(self, name: str):
        if name in ROUTES:
            return lambda **params: self.call(name, **params)
        raise AttributeError(name)


class LocalClient:
    """In-process client over the same route handlers (rpc/client/local)."""

    def __init__(self, env):
        self.env = env

    def call(self, method: str, **params):
        fn = ROUTES.get(method)
        if fn is None:
            raise RPCError(f"method {method!r} not found", code=-32601)
        return fn(self.env, **params)

    def __getattr__(self, name: str):
        if name in ROUTES:
            return lambda **params: self.call(name, **params)
        raise AttributeError(name)
