"""RPC clients (reference: rpc/client/http/http.go, rpc/client/local).

``HTTPClient``      — JSON-RPC 2.0 over HTTP POST (stdlib urllib).
``WSClient``        — JSON-RPC over a WebSocket with event
                      subscriptions (rpc/jsonrpc/client/ws_client.go:33,
                      rpc/client/http/http.go:790): subscribe(query)
                      yields a Subscription draining NewBlock/Tx/...
                      events pushed by the server, with optional
                      auto-reconnect + resubscribe.
``LocalClient``     — direct in-process dispatch against an Environment
                      (rpc/client/local semantics: same handlers, no
                      network) including event-bus subscriptions.

All expose ``call(method, **params)`` plus pythonic helpers for the
common routes; results are the JSON dicts the server returns.
"""

from __future__ import annotations

import base64
import hashlib
import itertools
import json
import os
import queue
import socket
import struct
import threading
from ..libs import sync as libsync
import time
import urllib.request

from .core.routes import ROUTES, RPCError


class HTTPClient:
    def __init__(self, base_url: str, timeout: float = 10.0):
        if base_url.startswith("tcp://"):
            base_url = "http://" + base_url[len("tcp://"):]
        if not base_url.startswith("http"):
            base_url = "http://" + base_url
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._ids = itertools.count(1)

    def call(self, method: str, **params):
        payload = {
            "jsonrpc": "2.0",
            "id": next(self._ids),
            "method": method,
            "params": params,
        }
        req = urllib.request.Request(
            self.base_url + "/",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = json.loads(resp.read())
        if "error" in body:
            err = body["error"]
            raise RPCError(
                err.get("message", "rpc error"),
                code=err.get("code", -32603),
                data=err.get("data", ""),
            )
        return body["result"]

    def __getattr__(self, name: str):
        if name in ROUTES:
            return lambda **params: self.call(name, **params)
        raise AttributeError(name)


_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class Subscription:
    """Client-side event stream for one query.

    ``recv(timeout)`` returns the next event dict
    ({"query", "data", "events"}) or None on timeout/closed; iterate for
    a blocking stream. Closed (and drained) when the client
    unsubscribes, disconnects without reconnect, or is closed.
    """

    def __init__(self, query: str, capacity: int = 256):
        self.query = query
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self.closed = threading.Event()

    def _push(self, item) -> None:
        try:
            self._q.put_nowait(item)
        except queue.Full:
            # Slow consumer: drop oldest so the reader thread never
            # blocks the demux loop (ws_client.go uses an unbounded
            # queue by default; a bounded one with drop-oldest keeps
            # memory flat under event storms).
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            try:
                self._q.put_nowait(item)
            except queue.Full:
                pass

    def _close(self) -> None:
        """Close and WAKE blocked receivers: closed.set() alone cannot
        interrupt a queue.get, so a None sentinel rides the queue."""
        self.closed.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # queue non-empty: the receiver drains to items first

    def recv(self, timeout: float | None = None):
        if self.closed.is_set() and self._q.empty():
            return None
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is None:  # close sentinel — re-arm for other receivers
            try:
                self._q.put_nowait(None)
            except queue.Full:
                pass
            return None
        return item

    def __iter__(self):
        while not (self.closed.is_set() and self._q.empty()):
            item = self.recv(timeout=0.5)
            if item is not None:
                yield item


class WSClient:
    """WebSocket JSON-RPC client with event subscriptions.

    Mirrors rpc/jsonrpc/client/ws_client.go: one socket, a reader
    thread demuxing call responses (by id) from subscription events
    (by result.query), masked client frames per RFC 6455, pong replies,
    and optional reconnect-with-resubscribe on connection loss.
    """

    def __init__(
        self,
        addr: str,
        timeout: float = 10.0,
        reconnect: bool = True,
        max_reconnect_attempts: int = 5,
    ):
        if addr.startswith(("tcp://", "ws://", "http://")):
            addr = addr.split("://", 1)[1]
        host, _, port = addr.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.timeout = timeout
        self.reconnect = reconnect
        self.max_reconnect_attempts = max_reconnect_attempts
        self._ids = itertools.count(1)
        self._mtx = libsync.Mutex("rpc.client._mtx")  # socket write + state
        self._subs_mtx = libsync.Mutex("rpc.client._subs_mtx")  # subscribe check+insert
        self._pending: dict[int, queue.Queue] = {}
        self._inflight: set[int] = set()  # ids actually written to the wire
        self._subs: dict[str, Subscription] = {}
        self._closed = False
        self._sock: socket.socket | None = None
        self._connect()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # -- connection -------------------------------------------------------

    def _connect(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        key = base64.b64encode(os.urandom(16)).decode()
        req = (
            f"GET /websocket HTTP/1.1\r\n"
            f"Host: {self.host}:{self.port}\r\n"
            f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n"
        )
        sock.sendall(req.encode())
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(4096)
            if not chunk:
                raise ConnectionError("ws handshake: connection closed")
            buf += chunk
        head = buf.split(b"\r\n\r\n", 1)[0].decode("latin-1")
        if "101" not in head.split("\r\n", 1)[0]:
            raise ConnectionError(f"ws handshake refused: {head.splitlines()[0]}")
        expect = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()
        ).decode()
        if f"Sec-WebSocket-Accept: {expect}" not in head:
            # header names are case-insensitive; re-scan tolerantly
            ok = any(
                line.split(":", 1)[1].strip() == expect
                for line in head.splitlines()
                if line.lower().startswith("sec-websocket-accept:")
            )
            if not ok:
                raise ConnectionError("ws handshake: bad accept key")
        sock.settimeout(None)
        self._sock = sock

    def _reconnect(self) -> bool:
        """Redial with backoff and re-subscribe (ws_client.go reconnect)."""
        for attempt in range(self.max_reconnect_attempts):
            if self._closed:
                return False
            time.sleep(min(0.1 * (2**attempt), 2.0))
            try:
                with self._mtx:  # cometlint: disable=CLNT009 -- reconnect swaps the socket under the mutex so writers never race a half-open conn
                    self._connect()
                for q_str in list(self._subs):
                    self._send(
                        {
                            "jsonrpc": "2.0",
                            "id": next(self._ids),
                            "method": "subscribe",
                            "params": {"query": q_str},
                        }
                    )
                return True
            except OSError:
                continue
        return False

    # -- frame io (client frames are MASKED per RFC 6455) -----------------

    def _send_frame(self, opcode: int, payload: bytes,
                    mark_inflight: int | None = None) -> None:
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        head = bytes([0x80 | opcode])
        ln = len(payload)
        if ln < 126:
            head += bytes([0x80 | ln])
        elif ln < (1 << 16):
            head += bytes([0x80 | 126]) + struct.pack(">H", ln)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", ln)
        with self._mtx:  # cometlint: disable=CLNT009 -- websocket frames must not interleave; sendall is ordered with inflight registration
            if self._sock is None:
                raise ConnectionError("ws not connected")
            self._sock.sendall(head + mask + masked)
            if mark_inflight is not None:
                # registered under the SAME lock hold as the write, so
                # the reader's disconnect sweep (also under _mtx) either
                # sees this id or serializes before the write
                self._inflight.add(mark_inflight)

    def _send(self, payload: dict, mark_inflight: int | None = None) -> None:
        self._send_frame(
            0x1, json.dumps(payload).encode(), mark_inflight=mark_inflight
        )

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return buf

    def _read_frame(self) -> tuple[int, bytes]:
        h = self._read_exact(2)
        opcode = h[0] & 0x0F
        masked = h[1] & 0x80
        ln = h[1] & 0x7F
        if ln == 126:
            ln = struct.unpack(">H", self._read_exact(2))[0]
        elif ln == 127:
            ln = struct.unpack(">Q", self._read_exact(8))[0]
        mask = self._read_exact(4) if masked else b""
        payload = self._read_exact(ln)
        if masked:
            payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        return opcode, payload

    # -- reader / demux ---------------------------------------------------

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                opcode, payload = self._read_frame()
            except (OSError, ConnectionError, AttributeError):
                if self._closed or not self.reconnect:
                    break
                # Replies to in-flight calls died with the connection:
                # fail their waiters NOW instead of letting each wait
                # out its full timeout while we redial. Only ids whose
                # request actually went out on the wire — a call that
                # registered its waiter but hasn't sent yet will send on
                # the NEW socket and must keep its waiter. Under _mtx so
                # the sweep serializes against send+register.
                with self._mtx:
                    swept = list(self._inflight)
                    self._inflight.clear()
                for id_ in swept:
                    q = self._pending.pop(id_, None)
                    if q is not None:
                        q.put(None)
                if not self._reconnect():
                    break
                continue
            if opcode == 0x9:  # ping -> pong
                try:
                    self._send_frame(0xA, payload)
                except OSError:
                    pass
                continue
            if opcode == 0x8:  # close
                if self._closed or not self.reconnect:
                    break
                if not self._reconnect():
                    break
                continue
            if opcode not in (0x1, 0x2):
                continue
            try:
                msg = json.loads(payload)
            except json.JSONDecodeError:
                continue
            self._demux(msg)
        # terminal: fail pending calls, close subscriptions. Snapshot —
        # other threads insert into these dicts concurrently, and a
        # mid-iteration resize would kill this thread before it wakes
        # the remaining waiters.
        self._closed = True
        for q in list(self._pending.values()):
            q.put(None)
        for sub in list(self._subs.values()):
            sub._close()

    def _demux(self, msg: dict) -> None:
        result = msg.get("result")
        if isinstance(result, dict) and "query" in result:
            sub = self._subs.get(result["query"])
            if sub is not None:
                sub._push(result)
                return
        q = self._pending.pop(msg.get("id"), None)
        if q is not None:
            q.put(msg)

    # -- public api -------------------------------------------------------

    def call(self, method: str, **params):
        id_ = next(self._ids)
        waiter: queue.Queue = queue.Queue(maxsize=1)
        self._pending[id_] = waiter
        try:
            try:
                self._send(
                    {
                        "jsonrpc": "2.0",
                        "id": id_,
                        "method": method,
                        "params": params,
                    },
                    mark_inflight=id_,
                )
            except OSError as e:  # incl. mid-reconnect "ws not connected"
                raise RPCError(
                    f"ws send for {method!r} failed: {e}", code=-32603
                ) from e
            msg = waiter.get(timeout=self.timeout)
        except queue.Empty:
            raise RPCError(f"ws call {method!r} timed out", code=-32603)
        finally:
            self._pending.pop(id_, None)
            self._inflight.discard(id_)
        if msg is None:
            raise RPCError("ws connection lost", code=-32603)
        if "error" in msg:
            err = msg["error"]
            raise RPCError(
                err.get("message", "rpc error"),
                code=err.get("code", -32603),
                data=err.get("data", ""),
            )
        return msg.get("result")

    def subscribe(self, query: str, capacity: int = 256) -> Subscription:
        """Subscribe to an event query; events stream into the returned
        Subscription (rpc/client/http/http.go:790 Subscribe).

        Duplicate queries error (ws_client discipline): silently
        replacing the existing Subscription would orphan its readers."""
        sub = Subscription(query, capacity)
        with self._subs_mtx:  # check+insert atomically: two racing
            if query in self._subs:  # subscribers must not orphan one
                raise RPCError(
                    f"already subscribed to query {query!r}", code=-32603
                )
            self._subs[query] = sub
        try:
            self.call("subscribe", query=query)
        except Exception:
            with self._subs_mtx:
                if self._subs.get(query) is sub:
                    self._subs.pop(query, None)
            raise
        return sub

    def unsubscribe(self, query: str) -> None:
        sub = self._subs.pop(query, None)
        if sub is not None:
            sub._close()
        self.call("unsubscribe", query=query)

    def unsubscribe_all(self) -> None:
        for sub in list(self._subs.values()):
            sub._close()
        self._subs.clear()
        self.call("unsubscribe_all")

    def close(self) -> None:
        self._closed = True
        with self._mtx:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for sub in list(self._subs.values()):
            sub._close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __getattr__(self, name: str):
        if name in ROUTES:
            return lambda **params: self.call(name, **params)
        raise AttributeError(name)


class LocalClient:
    """In-process client over the same route handlers (rpc/client/local),
    including event subscriptions straight off the node's EventBus."""

    def __init__(self, env):
        self.env = env
        self._sub_id = f"local-client-{id(self):x}"
        self._subs: dict[str, tuple[object, Subscription, object]] = {}

    def call(self, method: str, **params):
        fn = ROUTES.get(method)
        if fn is None:
            raise RPCError(f"method {method!r} not found", code=-32601)
        return fn(self.env, **params)

    def subscribe(self, query: str, capacity: int = 256) -> Subscription:
        """Event subscription without a network hop: the same
        {"query","data","events"} items a WSClient subscription yields."""
        from ..libs import pubsub
        from .core.events import encode_event_data

        if self.env.event_bus is None:
            raise RPCError("event bus unavailable")
        q = pubsub.Query.parse(query)
        bus_sub = self.env.event_bus.subscribe(
            self._sub_id, q, capacity=capacity
        )
        sub = Subscription(query, capacity)

        def forward():
            while not sub.closed.is_set() and not bus_sub.canceled.is_set():
                try:
                    msg = bus_sub.out.get(timeout=0.5)
                except Exception:
                    continue
                sub._push(
                    {
                        "query": query,
                        "data": encode_event_data(msg.data),
                        "events": msg.events,
                    }
                )
            sub._close()

        t = threading.Thread(target=forward, daemon=True)
        t.start()
        self._subs[query] = (q, sub, bus_sub)
        return sub

    def unsubscribe(self, query: str) -> None:
        triple = self._subs.pop(query, None)
        if triple is None:
            raise RPCError(f"not subscribed to {query!r}")
        q, sub, _bus_sub = triple
        sub._close()
        self.env.event_bus.unsubscribe(self._sub_id, q)

    def unsubscribe_all(self) -> None:
        for _q, sub, _b in list(self._subs.values()):
            sub._close()
        if self._subs:
            self.env.event_bus.unsubscribe_all(self._sub_id)
        self._subs.clear()

    def close(self) -> None:
        try:
            self.unsubscribe_all()
        except Exception:
            pass

    def __getattr__(self, name: str):
        if name in ROUTES:
            return lambda **params: self.call(name, **params)
        raise AttributeError(name)
