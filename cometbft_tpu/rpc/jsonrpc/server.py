"""JSON-RPC 2.0 server over HTTP + WebSocket subscriptions.

Reference: rpc/jsonrpc/server/{http_server,http_json_handler,
http_uri_handler,ws_handler}.go. Endpoints:

* ``POST /``           — JSON-RPC 2.0 (single or batch)
* ``GET /<route>?a=b`` — URI routes, same handlers
* ``GET /``            — route listing (the reference's help page)
* ``GET /websocket``   — RFC 6455 upgrade; subscribe/unsubscribe stream
                         event-bus matches as JSON-RPC notifications

Implementation is stdlib-only (ThreadingHTTPServer + a compact RFC 6455
frame layer) — the runtime around the TPU compute path stays
dependency-free.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading
from ...libs import sync as libsync
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ...libs import pubsub
from ...libs.service import BaseService
from ..core.routes import ROUTES, RPCError

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
MAX_BODY = 1 << 20  # 1MB request cap (http_server.go maxBodyBytes)


def _rpc_response(id_, result=None, error=None) -> dict:
    out = {"jsonrpc": "2.0", "id": id_}
    if error is not None:
        out["error"] = error
    else:
        out["result"] = result
    return out


def _rpc_error(code: int, message: str, data: str = "") -> dict:
    err = {"code": code, "message": message}
    if data:
        err["data"] = data
    return err


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "cometbft-tpu-rpc"

    # injected by RPCServer
    env = None
    routes = ROUTES

    def log_message(self, fmt, *args):  # quiet by default
        logger = getattr(self.server, "logger", None)
        if logger is not None:
            logger.debug("rpc: " + fmt % args)

    # -- dispatch ----------------------------------------------------------

    def _call(self, method: str, params: dict):
        fn = self.routes.get(method)
        if fn is None:
            raise RPCError(f"method {method!r} not found", code=-32601)
        try:
            return fn(self.env, **(params or {}))
        except RPCError:
            raise
        except TypeError as e:
            raise RPCError(str(e), code=-32602)
        except Exception as e:
            raise RPCError(str(e) or repr(e))

    def _send_json(self, payload, status: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- HTTP verbs --------------------------------------------------------

    def do_POST(self) -> None:
        length = int(self.headers.get("Content-Length") or 0)
        if length > MAX_BODY:
            self._send_json(
                _rpc_response(None, error=_rpc_error(-32600, "body too large")),
                status=413,
            )
            return
        try:
            req = json.loads(self.rfile.read(length) or b"{}")
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
            # non-UTF8 / non-JSON bodies are wire noise, not a server error
            self._send_json(
                _rpc_response(None, error=_rpc_error(-32700, f"parse error: {e}"))
            )
            return
        if isinstance(req, list):
            if not req:  # JSON-RPC 2.0: empty batch is an invalid request
                self._send_json(
                    _rpc_response(
                        None, error=_rpc_error(-32600, "empty batch")
                    )
                )
                return
            self._send_json([self._handle_one(r) for r in req])
        else:
            self._send_json(self._handle_one(req))

    def _handle_one(self, req: dict) -> dict:
        id_ = req.get("id")
        method = req.get("method", "")
        params = req.get("params") or {}
        if not isinstance(params, dict):
            return _rpc_response(
                id_, error=_rpc_error(-32602, "params must be an object")
            )
        try:
            return _rpc_response(id_, result=self._call(method, params))
        except RPCError as e:
            return _rpc_response(
                id_, error=_rpc_error(e.code, str(e), e.data)
            )

    def do_GET(self) -> None:
        parsed = urllib.parse.urlsplit(self.path)
        route = parsed.path.strip("/")
        if route == "websocket":
            self._do_websocket()
            return
        if route == "":
            self._send_json({"routes": sorted(self.routes)})
            return
        if route == "metrics":
            self._do_metrics()
            return
        params = {
            k: v[0] if len(v) == 1 else v
            for k, v in urllib.parse.parse_qs(parsed.query).items()
        }
        # URI params arrive quoted (height=1, hash="AB12", tx=0x... styles);
        # bare booleans arrive as text and must not stay truthy strings —
        # but QUOTED values are explicitly strings ("true" stays "true")
        for k, v in list(params.items()):
            if isinstance(v, str) and len(v) >= 2 and v[0] == v[-1] == '"':
                params[k] = v[1:-1]
            elif isinstance(v, str) and v.lower() in ("true", "false"):
                params[k] = v.lower() == "true"
        try:
            self._send_json(
                _rpc_response(-1, result=self._call(route, params))
            )
        except RPCError as e:
            self._send_json(
                _rpc_response(-1, error=_rpc_error(e.code, str(e), e.data)),
                status=500 if e.code == -32603 else 400,
            )

    def _do_metrics(self) -> None:
        """Prometheus text exposition (node/node.go:630 analog)."""
        metrics = self.env.extra.get("metrics")
        if metrics is None:
            self._send_json(
                _rpc_response(-1, error=_rpc_error(-32601, "metrics disabled")),
                status=404,
            )
            return
        refresh = self.env.extra.get("refresh_metrics")
        if refresh is not None:
            try:
                refresh()
            except Exception as e:  # CLNT006: serve stale metrics rather
                # than failing the scrape, but record the refresh fault
                logger = getattr(self.server, "logger", None)
                if logger is not None:
                    logger.debug(
                        "metrics refresh failed", err=repr(e)[:120]
                    )
        body = metrics.registry.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- WebSocket (ws_handler.go) ----------------------------------------

    def _do_websocket(self) -> None:
        key = self.headers.get("Sec-WebSocket-Key")
        if self.headers.get("Upgrade", "").lower() != "websocket" or not key:
            self._send_json(
                _rpc_response(None, error=_rpc_error(-32600, "not a websocket"))
            , status=400)
            return
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()
        ).decode()
        self.send_response(101, "Switching Protocols")
        self.send_header("Upgrade", "websocket")
        self.send_header("Connection", "Upgrade")
        self.send_header("Sec-WebSocket-Accept", accept)
        self.end_headers()
        self.close_connection = True
        conn = _WSConn(self.connection, self.env)
        try:
            conn.serve()
        finally:
            conn.cleanup()


class _WSConn:
    """One WebSocket session: JSON-RPC over frames + event forwarding."""

    def __init__(self, sock, env):
        self.sock = sock
        self.env = env
        self.id = f"ws-{id(self):x}"
        self._write_mtx = libsync.Mutex("rpc.jsonrpc.server._write_mtx")
        self._subs: dict[str, tuple[object, object]] = {}  # query -> (q, sub)
        self._alive = True

    # frame io ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return buf

    def _read_frame(self) -> tuple[int, bytes]:
        h = self._read_exact(2)
        opcode = h[0] & 0x0F
        masked = h[1] & 0x80
        ln = h[1] & 0x7F
        if ln == 126:
            ln = struct.unpack(">H", self._read_exact(2))[0]
        elif ln == 127:
            ln = struct.unpack(">Q", self._read_exact(8))[0]
        if ln > MAX_BODY:
            raise ConnectionError("ws frame too large")
        mask = self._read_exact(4) if masked else b""
        payload = self._read_exact(ln)
        if masked:
            payload = bytes(
                b ^ mask[i % 4] for i, b in enumerate(payload)
            )
        return opcode, payload

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        with self._write_mtx:  # cometlint: disable=CLNT009 -- the per-connection write mutex serializes ws frames: its purpose
            head = bytes([0x80 | opcode])
            ln = len(payload)
            if ln < 126:
                head += bytes([ln])
            elif ln < (1 << 16):
                head += bytes([126]) + struct.pack(">H", ln)
            else:
                head += bytes([127]) + struct.pack(">Q", ln)
            self.sock.sendall(head + payload)

    def send_json(self, payload: dict) -> None:
        try:
            self._send_frame(0x1, json.dumps(payload).encode())
        except OSError:
            self._alive = False

    # session -------------------------------------------------------------

    def serve(self) -> None:
        while self._alive:
            try:
                opcode, payload = self._read_frame()
            except (ConnectionError, OSError):
                return
            if opcode == 0x8:  # close
                try:
                    self._send_frame(0x8, b"")
                except OSError:
                    pass
                return
            if opcode == 0x9:  # ping
                self._send_frame(0xA, payload)
                continue
            if opcode not in (0x1, 0x2):
                continue
            try:
                req = json.loads(payload)
            except json.JSONDecodeError:
                self.send_json(
                    _rpc_response(None, error=_rpc_error(-32700, "parse error"))
                )
                continue
            self._handle(req)

    def _handle(self, req: dict) -> None:
        id_ = req.get("id")
        method = req.get("method", "")
        params = req.get("params") or {}
        try:
            if method == "subscribe":
                self._subscribe(id_, params.get("query", ""))
            elif method == "unsubscribe":
                self._unsubscribe(id_, params.get("query", ""))
            elif method == "unsubscribe_all":
                self._unsub_all()
                self.send_json(_rpc_response(id_, result={}))
            else:
                fn = ROUTES.get(method)
                if fn is None:
                    raise RPCError(f"method {method!r} not found", code=-32601)
                self.send_json(
                    _rpc_response(id_, result=fn(self.env, **params))
                )
        except RPCError as e:
            self.send_json(_rpc_response(id_, error=_rpc_error(e.code, str(e))))
        except Exception as e:
            self.send_json(_rpc_response(id_, error=_rpc_error(-32603, str(e))))

    def _subscribe(self, id_, query_str: str) -> None:
        if not query_str:
            raise RPCError("query is required", code=-32602)
        if self.env.event_bus is None:
            raise RPCError("event bus unavailable")
        q = pubsub.Query.parse(query_str)
        sub = self.env.event_bus.subscribe(self.id, q, capacity=100)
        self._subs[query_str] = (q, sub)
        threading.Thread(
            target=self._forward, args=(query_str, sub, id_), daemon=True
        ).start()
        self.send_json(_rpc_response(id_, result={}))

    def _forward(self, query_str: str, sub, id_) -> None:
        from ..core.events import encode_event_data

        while self._alive and not sub.canceled.is_set():
            try:
                msg = sub.out.get(timeout=0.5)
            except Exception:
                continue
            self.send_json(
                _rpc_response(
                    id_,
                    result={
                        "query": query_str,
                        "data": encode_event_data(msg.data),
                        "events": msg.events,
                    },
                )
            )

    def _unsubscribe(self, id_, query_str: str) -> None:
        pair = self._subs.pop(query_str, None)
        if pair is None:
            raise RPCError(f"not subscribed to {query_str!r}")
        q, _sub = pair
        self.env.event_bus.unsubscribe(self.id, q)
        self.send_json(_rpc_response(id_, result={}))

    def _unsub_all(self) -> None:
        if self._subs:
            try:
                self.env.event_bus.unsubscribe_all(self.id)
            except Exception:  # cometlint: disable=CLNT006 -- cleanup of a
                # dying websocket: the subscriber may already be gone from
                # the bus (unsubscribed server-side); nothing to report
                pass
            self._subs.clear()

    def cleanup(self) -> None:
        self._alive = False
        self._unsub_all()


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 64


class RPCServer(BaseService):
    """HTTP JSON-RPC server bound to config.rpc.laddr."""

    def __init__(self, env, laddr: str, logger=None, routes=None):
        super().__init__("rpc-server")
        self.env = env
        self.laddr = laddr
        self.logger = logger
        # Optional route-table override (the light proxy serves the same
        # JSON-RPC protocol over verified closures instead of core ROUTES).
        self.routes = routes
        self._httpd: _Server | None = None
        self._thread: threading.Thread | None = None

    @property
    def bound_addr(self) -> str:
        if self._httpd is None:
            return ""
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def on_start(self) -> None:
        host, port = _parse_laddr(self.laddr)
        attrs = {"env": self.env}
        if self.routes is not None:
            attrs["routes"] = self.routes
        handler = type("BoundHandler", (_Handler,), attrs)
        self._httpd = _Server((host, port), handler)
        self._httpd.logger = self.logger
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rpc-http", daemon=True
        )
        self._thread.start()

    def on_stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)


def _parse_laddr(laddr: str) -> tuple[str, int]:
    addr = laddr
    for prefix in ("tcp://", "http://"):
        if addr.startswith(prefix):
            addr = addr[len(prefix):]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)
