"""RPC JSON encoding of the data model.

Follows the reference's JSON conventions (rpc/core responses via
cometbft/libs/json): integers that can exceed 2^53 are strings, hashes and
addresses are upper-hex, raw byte blobs (txs, app data, signatures,
pubkeys) are base64, times are RFC3339 with nanoseconds.
"""

from __future__ import annotations

import base64
from datetime import datetime, timezone


def hex_bytes(b: bytes | None) -> str:
    return (b or b"").hex().upper()


def b64(b: bytes | None) -> str:
    return base64.b64encode(b or b"").decode()


def b64_decode(s: str) -> bytes:
    return base64.b64decode(s)


def rfc3339(ns: int) -> str:
    dt = datetime.fromtimestamp(ns / 1e9, tz=timezone.utc)
    frac = ns % 1_000_000_000
    return dt.strftime("%Y-%m-%dT%H:%M:%S") + f".{frac:09d}Z"


def enc_block_id(bid) -> dict:
    return {
        "hash": hex_bytes(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": hex_bytes(bid.part_set_header.hash),
        },
    }


def enc_header(h) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": rfc3339(h.time_ns),
        "last_block_id": enc_block_id(h.last_block_id),
        "last_commit_hash": hex_bytes(h.last_commit_hash),
        "data_hash": hex_bytes(h.data_hash),
        "validators_hash": hex_bytes(h.validators_hash),
        "next_validators_hash": hex_bytes(h.next_validators_hash),
        "consensus_hash": hex_bytes(h.consensus_hash),
        "app_hash": hex_bytes(h.app_hash),
        "last_results_hash": hex_bytes(h.last_results_hash),
        "evidence_hash": hex_bytes(h.evidence_hash),
        "proposer_address": hex_bytes(h.proposer_address),
    }


def enc_commit_sig(cs) -> dict:
    return {
        "block_id_flag": cs.block_id_flag,
        "validator_address": hex_bytes(cs.validator_address),
        "timestamp": rfc3339(cs.timestamp_ns) if cs.timestamp_ns else "",
        "signature": b64(cs.signature) if cs.signature else None,
    }


def enc_commit(c) -> dict:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": enc_block_id(c.block_id),
        "signatures": [enc_commit_sig(s) for s in c.signatures],
    }


def enc_block(b) -> dict:
    return {
        "header": enc_header(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": []},
        "last_commit": enc_commit(b.last_commit) if b.last_commit else None,
    }


def enc_block_meta(m) -> dict:
    return {
        "block_id": enc_block_id(m.block_id),
        "block_size": str(m.block_size),
        "header": enc_header(m.header),
        "num_txs": str(m.num_txs),
    }


def enc_validator(v) -> dict:
    return {
        "address": hex_bytes(v.address),
        "pub_key": {
            "type": "tendermint/PubKeyEd25519",
            "value": b64(v.pub_key.bytes()),
        },
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def enc_events(events) -> list:
    out = []
    for ev in events or []:
        out.append(
            {
                "type": ev.type,
                "attributes": [
                    {"key": a.key, "value": a.value, "index": a.index}
                    for a in ev.attributes
                ],
            }
        )
    return out


def enc_exec_tx_result(r) -> dict:
    return {
        "code": r.code,
        "data": b64(r.data) if r.data else None,
        "log": r.log,
        "info": getattr(r, "info", ""),
        "gas_wanted": str(getattr(r, "gas_wanted", 0)),
        "gas_used": str(getattr(r, "gas_used", 0)),
        "events": enc_events(getattr(r, "events", [])),
        "codespace": getattr(r, "codespace", ""),
    }
