"""RPC JSON encoding of the data model.

Follows the reference's JSON conventions (rpc/core responses via
cometbft/libs/json): integers that can exceed 2^53 are strings, hashes and
addresses are upper-hex, raw byte blobs (txs, app data, signatures,
pubkeys) are base64, times are RFC3339 with nanoseconds.
"""

from __future__ import annotations

import base64
from datetime import datetime, timezone


def hex_bytes(b: bytes | None) -> str:
    return (b or b"").hex().upper()


def b64(b: bytes | None) -> str:
    return base64.b64encode(b or b"").decode()


def b64_decode(s: str) -> bytes:
    return base64.b64decode(s)


def rfc3339(ns: int) -> str:
    # Integer split: float ns/1e9 rounds fractions near 1s up to the
    # next second while the digits stay, producing a string 1s off —
    # which would break the decode round-trip the light proxy's
    # content-hash verification depends on.
    secs, frac = divmod(ns, 1_000_000_000)
    dt = datetime.fromtimestamp(secs, tz=timezone.utc)
    # manual format: strftime("%Y") does not zero-pad year 1 (Go's zero
    # time) on glibc, producing "1-01-01…" instead of "0001-01-01…"
    return (
        f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}"
        f"T{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}.{frac:09d}Z"
    )


def enc_block_id(bid) -> dict:
    return {
        "hash": hex_bytes(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": hex_bytes(bid.part_set_header.hash),
        },
    }


def enc_header(h) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": rfc3339(h.time_ns),
        "last_block_id": enc_block_id(h.last_block_id),
        "last_commit_hash": hex_bytes(h.last_commit_hash),
        "data_hash": hex_bytes(h.data_hash),
        "validators_hash": hex_bytes(h.validators_hash),
        "next_validators_hash": hex_bytes(h.next_validators_hash),
        "consensus_hash": hex_bytes(h.consensus_hash),
        "app_hash": hex_bytes(h.app_hash),
        "last_results_hash": hex_bytes(h.last_results_hash),
        "evidence_hash": hex_bytes(h.evidence_hash),
        "proposer_address": hex_bytes(h.proposer_address),
    }


def enc_commit_sig(cs) -> dict:
    return {
        "block_id_flag": cs.block_id_flag,
        "validator_address": hex_bytes(cs.validator_address),
        "timestamp": rfc3339(cs.timestamp_ns) if cs.timestamp_ns else "",
        "signature": b64(cs.signature) if cs.signature else None,
    }


def enc_commit(c) -> dict:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": enc_block_id(c.block_id),
        "signatures": [enc_commit_sig(s) for s in c.signatures],
    }


def enc_vote(v) -> dict:
    return {
        "type": v.msg_type,
        "height": str(v.height),
        "round": v.round,
        "block_id": enc_block_id(v.block_id),
        "timestamp": rfc3339(v.timestamp_ns),
        "validator_address": hex_bytes(v.validator_address),
        "validator_index": v.validator_index,
        "signature": b64(v.signature) if v.signature else None,
    }


def enc_evidence(ev) -> dict:
    """Registry-wrapped evidence JSON (the reference wraps each evidence
    item in a {"type","value"} envelope via libs/json; types/evidence.go)."""
    from ..types.evidence import (
        DuplicateVoteEvidence,
        LightClientAttackEvidence,
    )

    if isinstance(ev, DuplicateVoteEvidence):
        return {
            "type": "tendermint/DuplicateVoteEvidence",
            "value": {
                "vote_a": enc_vote(ev.vote_a),
                "vote_b": enc_vote(ev.vote_b),
                "total_voting_power": str(ev.total_voting_power),
                "validator_power": str(ev.validator_power),
                "timestamp": rfc3339(ev.timestamp_ns),
            },
        }
    if isinstance(ev, LightClientAttackEvidence):
        sh = ev.conflicting_block.signed_header
        return {
            "type": "tendermint/LightClientAttackEvidence",
            "value": {
                "conflicting_block": {
                    "signed_header": {
                        "header": enc_header(sh.header),
                        "commit": enc_commit(sh.commit),
                    },
                    "validator_set": {
                        "validators": [
                            enc_validator(v)
                            for v in ev.conflicting_block.validator_set.validators
                        ],
                    },
                },
                "common_height": str(ev.common_height),
                "byzantine_validators": [
                    enc_validator(v) for v in ev.byzantine_validators
                ],
                "total_voting_power": str(ev.total_voting_power),
                "timestamp": rfc3339(ev.timestamp_ns),
            },
        }
    raise ValueError(f"unsupported evidence type {type(ev).__name__}")


def enc_block(b) -> dict:
    return {
        "header": enc_header(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {
            "evidence": [enc_evidence(ev) for ev in b.evidence]
        },
        "last_commit": enc_commit(b.last_commit) if b.last_commit else None,
    }


# -- decoders (JSON → data model) -----------------------------------------
#
# The light proxy must re-verify primary-supplied blocks from CONTENT
# (light/rpc/client.go:319-340 recomputes res.Block.Hash()), so it needs
# the inverse of the encoders above.


def parse_rfc3339(s: str) -> int:
    """RFC3339 (with up to nanosecond fraction) → unix ns."""
    if not s:
        return 0
    base, _, rest = s.partition(".")
    if rest:
        frac = rest.rstrip("Z")
        ns = int(frac.ljust(9, "0")[:9])
    else:
        base = base.rstrip("Z")
        ns = 0
    base = base.rstrip("Z")
    # tolerate unpadded years (older encoders emitted "1-01-01…" for
    # Go's zero time)
    ymd, _, hms = base.partition("T")
    y, m, d = ymd.split("-")
    dt = datetime.strptime(
        f"{int(y):04d}-{m}-{d}T{hms}", "%Y-%m-%dT%H:%M:%S"
    ).replace(tzinfo=timezone.utc)
    # integer seconds-since-epoch (float timestamp() loses precision at
    # year-1 magnitudes used by Go's zero time)
    delta = dt - datetime(1970, 1, 1, tzinfo=timezone.utc)
    secs = delta.days * 86400 + delta.seconds
    return secs * 1_000_000_000 + ns


def dec_hex(s: str | None) -> bytes:
    return bytes.fromhex(s) if s else b""


def dec_block_id(d: dict):
    from ..types.block import BlockID, PartSetHeader

    parts = d.get("parts") or {}
    return BlockID(
        hash=dec_hex(d.get("hash")),
        part_set_header=PartSetHeader(
            total=int(parts.get("total", 0)), hash=dec_hex(parts.get("hash"))
        ),
    )


def dec_header(d: dict):
    from ..types.block import Header, Version

    v = d.get("version") or {}
    return Header(
        version=Version(
            block=int(v.get("block", 0)), app=int(v.get("app", 0))
        ),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time_ns=parse_rfc3339(d["time"]),
        last_block_id=dec_block_id(d.get("last_block_id") or {}),
        last_commit_hash=dec_hex(d.get("last_commit_hash")),
        data_hash=dec_hex(d.get("data_hash")),
        validators_hash=dec_hex(d.get("validators_hash")),
        next_validators_hash=dec_hex(d.get("next_validators_hash")),
        consensus_hash=dec_hex(d.get("consensus_hash")),
        app_hash=dec_hex(d.get("app_hash")),
        last_results_hash=dec_hex(d.get("last_results_hash")),
        evidence_hash=dec_hex(d.get("evidence_hash")),
        proposer_address=dec_hex(d.get("proposer_address")),
    )


def dec_commit_sig(d: dict):
    from ..types.block import CommitSig

    sig = d.get("signature")
    return CommitSig(
        block_id_flag=int(d["block_id_flag"]),
        validator_address=dec_hex(d.get("validator_address")),
        timestamp_ns=parse_rfc3339(d.get("timestamp") or ""),
        signature=base64.b64decode(sig) if sig else b"",
    )


def dec_commit(d: dict):
    from ..types.block import Commit

    return Commit(
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=dec_block_id(d.get("block_id") or {}),
        signatures=[dec_commit_sig(s) for s in d.get("signatures") or []],
    )


def dec_vote(d: dict):
    from ..types.vote import Vote

    sig = d.get("signature")
    return Vote(
        msg_type=int(d["type"]),
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=dec_block_id(d.get("block_id") or {}),
        timestamp_ns=parse_rfc3339(d.get("timestamp") or ""),
        validator_address=dec_hex(d.get("validator_address")),
        validator_index=int(d.get("validator_index", 0)),
        signature=base64.b64decode(sig) if sig else b"",
    )


def dec_validator(d: dict):
    from ..crypto.keys import PUBKEY_TYPES, register_extra_key_types
    from ..types.validator_set import Validator

    pk = d.get("pub_key") or {}
    type_name = pk.get("type", "tendermint/PubKeyEd25519")
    key_type = {
        "tendermint/PubKeyEd25519": "ed25519",
        "tendermint/PubKeySecp256k1": "secp256k1",
        "tendermint/PubKeySr25519": "sr25519",
    }.get(type_name)
    if key_type is None:
        raise ValueError(f"unknown pubkey type {type_name!r}")
    register_extra_key_types()
    pub_key = PUBKEY_TYPES[key_type](base64.b64decode(pk.get("value", "")))
    return Validator(
        pub_key=pub_key,
        voting_power=int(d.get("voting_power", 0)),
        proposer_priority=int(d.get("proposer_priority", 0)),
    )


def dec_evidence(d: dict):
    from ..types.evidence import (
        DuplicateVoteEvidence,
        LightClientAttackEvidence,
    )
    from ..types.light_block import LightBlock, SignedHeader
    from ..types.validator_set import ValidatorSet

    t, v = d.get("type"), d.get("value") or {}
    if t == "tendermint/DuplicateVoteEvidence":
        return DuplicateVoteEvidence(
            vote_a=dec_vote(v["vote_a"]),
            vote_b=dec_vote(v["vote_b"]),
            total_voting_power=int(v.get("total_voting_power", 0)),
            validator_power=int(v.get("validator_power", 0)),
            timestamp_ns=parse_rfc3339(v.get("timestamp") or ""),
        )
    if t == "tendermint/LightClientAttackEvidence":
        cb = v.get("conflicting_block") or {}
        sh = cb.get("signed_header") or {}
        return LightClientAttackEvidence(
            conflicting_block=LightBlock(
                signed_header=SignedHeader(
                    header=dec_header(sh["header"]),
                    commit=dec_commit(sh["commit"]),
                ),
                validator_set=ValidatorSet(
                    [
                        dec_validator(x)
                        for x in (cb.get("validator_set") or {}).get(
                            "validators"
                        )
                        or []
                    ]
                ),
            ),
            common_height=int(v.get("common_height", 0)),
            byzantine_validators=[
                dec_validator(x)
                for x in v.get("byzantine_validators") or []
            ],
            total_voting_power=int(v.get("total_voting_power", 0)),
            timestamp_ns=parse_rfc3339(v.get("timestamp") or ""),
        )
    raise ValueError(f"unknown evidence type {t!r}")


def dec_block(d: dict):
    from ..types.block import Block, Data

    lc = d.get("last_commit")
    return Block(
        header=dec_header(d["header"]),
        data=Data(
            txs=[
                base64.b64decode(t)
                for t in (d.get("data") or {}).get("txs") or []
            ]
        ),
        evidence=[
            dec_evidence(e)
            for e in (d.get("evidence") or {}).get("evidence") or []
        ],
        last_commit=dec_commit(lc) if lc and lc.get("signatures") else None,
    )


def enc_block_meta(m) -> dict:
    return {
        "block_id": enc_block_id(m.block_id),
        "block_size": str(m.block_size),
        "header": enc_header(m.header),
        "num_txs": str(m.num_txs),
    }


def enc_validator(v) -> dict:
    return {
        "address": hex_bytes(v.address),
        "pub_key": {
            "type": "tendermint/PubKeyEd25519",
            "value": b64(v.pub_key.bytes()),
        },
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def enc_events(events) -> list:
    out = []
    for ev in events or []:
        out.append(
            {
                "type": ev.type,
                "attributes": [
                    {"key": a.key, "value": a.value, "index": a.index}
                    for a in ev.attributes
                ],
            }
        )
    return out


def enc_exec_tx_result(r) -> dict:
    return {
        "code": r.code,
        "data": b64(r.data) if r.data else None,
        "log": r.log,
        "info": getattr(r, "info", ""),
        "gas_wanted": str(getattr(r, "gas_wanted", 0)),
        "gas_used": str(getattr(r, "gas_used", 0)),
        "events": enc_events(getattr(r, "events", [])),
        "codespace": getattr(r, "codespace", ""),
    }
