"""RPC Environment: handles to every service the routes read.

Reference: rpc/core/env.go:199 — one struct threaded to all handlers
instead of globals.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Environment:
    # storage
    block_store: object = None
    state_store: object = None
    # services
    consensus: object = None  # ConsensusState
    consensus_reactor: object = None
    mempool: object = None
    evidence_pool: object = None
    switch: object = None  # p2p switch (peers, listeners)
    proxy_app_query: object = None  # ABCI query connection
    event_bus: object = None
    tx_indexer: object = None
    block_indexer: object = None
    # static info
    genesis: object = None
    node_info: object = None
    priv_validator_pub_key: object = None
    config: object = None
    # extra route tables merged in by the node (e.g. statesync)
    extra: dict = field(default_factory=dict)

    def latest_height(self) -> int:
        return self.block_store.height() if self.block_store else 0

    def chain_id(self) -> str:
        return self.genesis.chain_id if self.genesis else ""
