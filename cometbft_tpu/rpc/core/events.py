"""Event payload encoding for RPC subscriptions.

Maps event-bus dataclasses to the reference's tagged JSON envelope
(types/events.go TMEventData registrations): {"type": "tendermint/event/X",
"value": {...}}.
"""

from __future__ import annotations

from ...types import event_bus as eb
from .. import encoding as enc


def encode_event_data(data) -> dict:
    if isinstance(data, eb.EventDataNewBlock):
        return {
            "type": "tendermint/event/NewBlock",
            "value": {
                "block": enc.enc_block(data.block),
                "block_id": enc.enc_block_id(data.block_id)
                if getattr(data, "block_id", None)
                else None,
            },
        }
    if isinstance(data, eb.EventDataNewBlockHeader):
        return {
            "type": "tendermint/event/NewBlockHeader",
            "value": {"header": enc.enc_header(data.header)},
        }
    if isinstance(data, eb.EventDataTx):
        return {
            "type": "tendermint/event/Tx",
            "value": {
                "TxResult": {
                    "height": str(data.height),
                    "index": data.index,
                    "tx": enc.b64(data.tx),
                    "result": enc.enc_exec_tx_result(data.result),
                }
            },
        }
    if isinstance(data, eb.EventDataRoundState):
        return {
            "type": "tendermint/event/RoundState",
            "value": {
                "height": str(data.height),
                "round": data.round,
                "step": str(data.step),
            },
        }
    if isinstance(data, eb.EventDataVote):
        v = data.vote
        return {
            "type": "tendermint/event/Vote",
            "value": {
                "Vote": {
                    "type": v.msg_type,
                    "height": str(v.height),
                    "round": v.round,
                    "validator_address": enc.hex_bytes(v.validator_address),
                    "validator_index": v.validator_index,
                }
            },
        }
    # generic fallback: dataclass fields best-effort
    return {"type": f"tendermint/event/{type(data).__name__}", "value": {}}
