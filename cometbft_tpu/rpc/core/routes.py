"""RPC route handlers (reference: rpc/core/routes.go:12-56 + per-file
implementations under rpc/core/).

Every handler takes (env, **params) and returns a JSON-encodable dict.
Param coercion (heights arrive as strings from JSON-RPC) happens here.
Errors raise RPCError with reference-style messages.
"""

from __future__ import annotations


from ...abci import types as abci
from ...mempool.clist_mempool import MempoolFullError, TxInCacheError
from .. import encoding as enc


class RPCError(Exception):
    def __init__(self, message: str, code: int = -32603, data: str = ""):
        super().__init__(message)
        self.code = code
        self.data = data


def _int(v, name: str, default=None) -> int | None:
    if v is None or v == "":
        if default is not None:
            return default
        return None
    try:
        return int(v)
    except (TypeError, ValueError):
        raise RPCError(f"invalid {name}: {v!r}", code=-32602)


def _height_or_latest(env, height) -> int:
    h = _int(height, "height")
    latest = env.latest_height()
    if h is None or h == 0:
        return latest
    if h <= 0:
        raise RPCError("height must be greater than 0")
    if h > latest:
        raise RPCError(
            f"height {h} must be less than or equal to the current "
            f"blockchain height {latest}"
        )
    return h


def _tx_bytes(tx) -> bytes:
    if isinstance(tx, (bytes, bytearray)):
        return bytes(tx)
    if isinstance(tx, str):
        return enc.b64_decode(tx)
    raise RPCError("tx must be base64 string", code=-32602)


# ---------------------------------------------------------------------------
# info routes (rpc/core/status.go, net.go, blocks.go, consensus.go)
# ---------------------------------------------------------------------------


def health(env) -> dict:
    return {}


def status(env) -> dict:
    latest = env.latest_height()
    meta = env.block_store.load_block_meta(latest) if latest else None
    earliest = env.block_store.base() if hasattr(env.block_store, "base") else 1
    emeta = env.block_store.load_block_meta(earliest) if latest else None
    val_info = {}
    if env.priv_validator_pub_key is not None:
        pk = env.priv_validator_pub_key
        power = 0
        if env.state_store is not None:
            st = env.state_store.load()
            if st is not None:
                idx, val = st.validators.get_by_address(bytes(pk.address()))
                if idx >= 0:
                    power = val.voting_power
        val_info = {
            "address": enc.hex_bytes(bytes(pk.address())),
            "pub_key": {
                "type": "tendermint/PubKeyEd25519",
                "value": enc.b64(pk.bytes()),
            },
            "voting_power": str(power),
        }
    catching_up = False
    if env.consensus_reactor is not None:
        catching_up = bool(getattr(env.consensus_reactor, "wait_sync", False))
    return {
        "node_info": _node_info_json(env),
        "sync_info": {
            "latest_block_hash": enc.hex_bytes(
                meta.block_id.hash if meta else b""
            ),
            "latest_app_hash": enc.hex_bytes(
                meta.header.app_hash if meta else b""
            ),
            "latest_block_height": str(latest),
            "latest_block_time": enc.rfc3339(meta.header.time_ns)
            if meta
            else enc.rfc3339(0),
            "earliest_block_hash": enc.hex_bytes(
                emeta.block_id.hash if emeta else b""
            ),
            "earliest_block_height": str(earliest if latest else 0),
            "catching_up": catching_up,
        },
        "validator_info": val_info,
    }


def _node_info_json(env) -> dict:
    ni = env.node_info
    if ni is None:
        return {}
    return {
        "id": ni.node_id,
        "listen_addr": ni.listen_addr,
        "network": ni.network,
        "version": ni.version,
        "moniker": ni.moniker,
        "channels": enc.hex_bytes(bytes(ni.channels or [])),
    }


def net_info(env) -> dict:
    peers = env.switch.peers() if env.switch else []
    return {
        "listening": bool(env.switch and env.switch.is_running()),
        "listeners": [env.node_info.listen_addr] if env.node_info else [],
        "n_peers": str(len(peers)),
        "peers": [
            {
                "node_info": {
                    "id": p.id,
                    "moniker": getattr(p.node_info, "moniker", ""),
                    "network": getattr(p.node_info, "network", ""),
                },
                "is_outbound": p.outbound,
                "remote_ip": getattr(p, "socket_addr", ""),
            }
            for p in peers
        ],
    }


def genesis(env) -> dict:
    import json as _json

    return {"genesis": _json.loads(env.genesis.to_json())}


GENESIS_CHUNK_SIZE = 16 * 1024 * 1024  # net.go:16 genesisChunkSize


def genesis_chunked(env, chunk=None) -> dict:
    """Large genesis docs fetched in 16 MB base64 chunks
    (rpc/core/net.go GenesisChunked)."""
    import base64 as _b64

    # serialize once per process (env.go InitGenesisChunks caches too):
    # the route exists precisely because the doc can be huge
    doc = env.extra.get("_genesis_encoded")
    if doc is None:
        doc = env.genesis.to_json().encode()
        env.extra["_genesis_encoded"] = doc
    total = max(1, (len(doc) + GENESIS_CHUNK_SIZE - 1) // GENESIS_CHUNK_SIZE)
    idx = _int(chunk, "chunk", 0) or 0
    if not 0 <= idx < total:
        raise RPCError(
            f"chunk {idx} out of range (0..{total - 1})", code=-32602
        )
    piece = doc[idx * GENESIS_CHUNK_SIZE : (idx + 1) * GENESIS_CHUNK_SIZE]
    return {
        "chunk": idx,
        "total": total,
        "data": _b64.b64encode(piece).decode(),
    }


def header_by_hash(env, hash=None) -> dict:  # noqa: A002
    if not hash:
        raise RPCError("hash is required", code=-32602)
    raw = bytes.fromhex(hash) if isinstance(hash, str) else bytes(hash)
    meta = env.block_store.load_block_meta_by_hash(raw)
    if meta is None:
        raise RPCError(f"header with hash {hash} not found")
    return {"header": enc.enc_header(meta.header)}


def block(env, height=None) -> dict:
    h = _height_or_latest(env, height)
    blk = env.block_store.load_block(h)
    meta = env.block_store.load_block_meta(h)
    if blk is None or meta is None:
        raise RPCError(f"block at height {h} not found")
    return {
        "block_id": enc.enc_block_id(meta.block_id),
        "block": enc.enc_block(blk),
    }


def block_by_hash(env, hash=None) -> dict:  # noqa: A002
    if not hash:
        raise RPCError("hash is required", code=-32602)
    raw = bytes.fromhex(hash) if isinstance(hash, str) else bytes(hash)
    blk = env.block_store.load_block_by_hash(raw)
    if blk is None:
        raise RPCError(f"block with hash {hash} not found")
    meta = env.block_store.load_block_meta(blk.header.height)
    return {
        "block_id": enc.enc_block_id(meta.block_id),
        "block": enc.enc_block(blk),
    }


def header(env, height=None) -> dict:
    h = _height_or_latest(env, height)
    meta = env.block_store.load_block_meta(h)
    if meta is None:
        raise RPCError(f"header at height {h} not found")
    return {"header": enc.enc_header(meta.header)}


def blockchain(env, min_height=None, max_height=None) -> dict:
    """Block metas in [min, max], newest first, max 20
    (rpc/core/blocks.go BlockchainInfo)."""
    latest = env.latest_height()
    maxh = min(_int(max_height, "max_height", latest) or latest, latest)
    minh = max(_int(min_height, "min_height", 1) or 1, 1)
    minh = max(minh, maxh - 20 + 1)
    if minh > maxh:
        raise RPCError(
            f"min height {minh} can't be greater than max height {maxh}"
        )
    metas = []
    for h in range(maxh, minh - 1, -1):
        m = env.block_store.load_block_meta(h)
        if m is not None:
            metas.append(enc.enc_block_meta(m))
    return {"last_height": str(latest), "block_metas": metas}


def commit(env, height=None) -> dict:
    h = _height_or_latest(env, height)
    meta = env.block_store.load_block_meta(h)
    if meta is None:
        raise RPCError(f"block at height {h} not found")
    c = env.block_store.load_block_commit(h)
    canonical = True
    if c is None and h == env.latest_height():
        c = env.block_store.load_seen_commit()
        canonical = False
    if c is None:
        raise RPCError(f"commit for height {h} not found")
    return {
        "signed_header": {
            "header": enc.enc_header(meta.header),
            "commit": enc.enc_commit(c),
        },
        "canonical": canonical,
    }


def validators(env, height=None, page=None, per_page=None) -> dict:
    h = _height_or_latest(env, height)
    vals = env.state_store.load_validators(h)
    if vals is None:
        raise RPCError(f"validators at height {h} not found")
    page_n = _int(page, "page", 1) or 1
    per = min(_int(per_page, "per_page", 30) or 30, 100)
    total = len(vals.validators)
    start = (page_n - 1) * per
    if start > total or page_n < 1:
        raise RPCError(f"page should be within [1, {max(1,(total+per-1)//per)}] range")
    subset = vals.validators[start : start + per]
    return {
        "block_height": str(h),
        "validators": [enc.enc_validator(v) for v in subset],
        "count": str(len(subset)),
        "total": str(total),
    }


def consensus_params(env, height=None) -> dict:
    h = _height_or_latest(env, height)
    st = env.state_store.load()
    if st is None:
        raise RPCError("no state")
    p = st.consensus_params
    return {
        "block_height": str(h),
        "consensus_params": {
            "block": {
                "max_bytes": str(p.block.max_bytes),
                "max_gas": str(p.block.max_gas),
            },
            "evidence": {
                "max_age_num_blocks": str(p.evidence.max_age_num_blocks),
                "max_age_duration": str(p.evidence.max_age_duration_ns),
                "max_bytes": str(p.evidence.max_bytes),
            },
            "validator": {"pub_key_types": list(p.validator.pub_key_types)},
            "abci": {
                "vote_extensions_enable_height": str(
                    p.abci.vote_extensions_enable_height
                ),
            },
        },
    }


def consensus_state(env) -> dict:
    rs = env.consensus.get_round_state()
    return {
        "round_state": {
            "height/round/step": f"{rs.height}/{rs.round}/{int(rs.step)}",
            "start_time": enc.rfc3339(rs.start_time_ns),
            "proposal_block_hash": enc.hex_bytes(
                rs.proposal_block.hash() if rs.proposal_block else b""
            ),
            "locked_block_hash": enc.hex_bytes(
                rs.locked_block.hash() if rs.locked_block else b""
            ),
            "valid_block_hash": enc.hex_bytes(
                rs.valid_block.hash() if rs.valid_block else b""
            ),
        }
    }


def dump_consensus_state(env) -> dict:
    rs = env.consensus.get_round_state()
    votes = []
    if rs.votes is not None:
        for r in range(rs.round + 1):
            pv = rs.votes.prevotes(r)
            pc = rs.votes.precommits(r)
            votes.append(
                {
                    "round": r,
                    "prevotes_bit_array": str(pv.bit_array()) if pv else "",
                    "precommits_bit_array": str(pc.bit_array()) if pc else "",
                }
            )
    out = consensus_state(env)
    out["round_state"]["height_vote_set"] = votes
    peers = env.switch.peers() if env.switch else []
    out["peers"] = [{"node_address": p.id} for p in peers]
    return out


def unconfirmed_txs(env, limit=None) -> dict:
    lim = min(_int(limit, "limit", 30) or 30, 100)
    txs = env.mempool.reap_max_txs(lim)
    return {
        "n_txs": str(len(txs)),
        "total": str(env.mempool.size()),
        "total_bytes": str(env.mempool.size_bytes()),
        "txs": [enc.b64(tx) for tx in txs],
    }


def num_unconfirmed_txs(env) -> dict:
    return {
        "n_txs": str(env.mempool.size()),
        "total": str(env.mempool.size()),
        "total_bytes": str(env.mempool.size_bytes()),
        "txs": None,
    }


# ---------------------------------------------------------------------------
# ABCI passthrough (rpc/core/abci.go)
# ---------------------------------------------------------------------------


def abci_info(env) -> dict:
    res = env.proxy_app_query.info(abci.RequestInfo())
    return {
        "response": {
            "data": res.data,
            "version": res.version,
            "app_version": str(res.app_version),
            "last_block_height": str(res.last_block_height),
            "last_block_app_hash": enc.b64(res.last_block_app_hash),
        }
    }


def abci_query(env, path="", data="", height=None, prove=False) -> dict:
    raw = bytes.fromhex(data) if isinstance(data, str) else bytes(data or b"")
    res = env.proxy_app_query.query(
        abci.RequestQuery(
            data=raw,
            path=path,
            height=_int(height, "height", 0) or 0,
            prove=bool(prove),
        )
    )
    return {
        "response": {
            "code": res.code,
            "log": res.log,
            "info": res.info,
            "index": str(res.index),
            "key": enc.b64(res.key),
            "value": enc.b64(res.value),
            "height": str(res.height),
            "codespace": res.codespace,
        }
    }


# ---------------------------------------------------------------------------
# tx ingress (rpc/core/mempool.go)
# ---------------------------------------------------------------------------


def _check_tx_sync(env, tx: bytes):
    """CheckTx and wait for the result (BroadcastTxSync semantics)."""
    import threading

    done = threading.Event()
    box = {}

    def cb(res):
        box["res"] = res
        done.set()

    try:
        env.mempool.check_tx(tx, cb=cb)
    except TxInCacheError:
        raise RPCError("tx already exists in cache")
    except MempoolFullError as e:
        raise RPCError(str(e))
    if not done.wait(timeout=10):
        raise RPCError("timed out waiting for tx to be included in mempool")
    return box["res"]


def broadcast_tx_async(env, tx=None) -> dict:
    raw = _tx_bytes(tx)
    try:
        env.mempool.check_tx(raw)
    except TxInCacheError:
        raise RPCError("tx already exists in cache")
    except MempoolFullError as e:
        raise RPCError(str(e))
    from ...crypto import tmhash

    return {"code": 0, "data": "", "log": "", "hash": enc.hex_bytes(tmhash.sum(raw))}


def broadcast_tx_sync(env, tx=None) -> dict:
    raw = _tx_bytes(tx)
    res = _check_tx_sync(env, raw)
    from ...crypto import tmhash

    return {
        "code": res.code,
        "data": enc.b64(res.data),
        "log": res.log,
        "codespace": res.codespace,
        "hash": enc.hex_bytes(tmhash.sum(raw)),
    }


def broadcast_tx_commit(env, tx=None) -> dict:
    """CheckTx, then wait for the tx to land in a committed block
    (rpc/core/mempool.go:104 BroadcastTxCommit) via an event-bus
    subscription."""
    import queue as _q

    from ...crypto import tmhash
    from ...libs import pubsub
    from ...types.event_bus import EVENT_TYPE_KEY

    raw = _tx_bytes(tx)
    tx_hash = tmhash.sum(raw)
    if env.event_bus is None:
        raise RPCError("event bus unavailable")
    subscriber = f"broadcast_tx_commit:{tx_hash.hex()}"
    query = pubsub.Query.parse(
        f"{EVENT_TYPE_KEY} = 'Tx' AND tx.hash = '{tx_hash.hex().upper()}'"
    )
    sub = env.event_bus.subscribe(subscriber, query, capacity=1)
    try:
        check_res = _check_tx_sync(env, raw)
        result = {
            "check_tx": {
                "code": check_res.code,
                "data": enc.b64(check_res.data),
                "log": check_res.log,
            },
            "hash": enc.hex_bytes(tx_hash),
        }
        if check_res.code != abci.OK:
            result["tx_result"] = {"code": check_res.code}
            result["height"] = "0"
            return result
        try:
            msg = sub.out.get(timeout=30.0)
        except _q.Empty:
            raise RPCError("timed out waiting for tx to be included in a block")
        data = msg.data  # EventDataTx
        result["tx_result"] = enc.enc_exec_tx_result(data.result)
        result["height"] = str(data.height)
        return result
    finally:
        try:
            env.event_bus.unsubscribe_all(subscriber)
        except Exception:
            pass


def tx_trace(env, key=None) -> dict:
    """'Where is my transaction' over RPC: the sampled tx-lifecycle
    plane's (libs/txtrace) view of one tx key — in-flight stage stamps
    or the completed submit->commit decomposition.  ``key`` is the tx
    key (SHA-256 of the tx) in hex; a prefix of the retained 16 chars
    works, a full 64-char key hex is truncated.  An unsampled key
    returns empty row lists with ``sampled: false`` so a client can
    tell "not sampled" from "not seen"."""
    from ...libs import txtrace as libtxtrace

    if key is None or not str(key).strip():
        raise RPCError("missing key param", code=-32602)
    return libtxtrace.lookup(str(key))


def check_tx(env, tx=None) -> dict:
    """Run CheckTx against the app WITHOUT adding to the mempool
    (rpc/core/mempool.go CheckTx)."""
    raw = _tx_bytes(tx)
    res = env.proxy_app_query.check_tx(abci.RequestCheckTx(tx=raw))
    return {
        "code": res.code,
        "data": enc.b64(res.data),
        "log": res.log,
        "gas_wanted": str(res.gas_wanted),
        "gas_used": str(res.gas_used),
    }


# ---------------------------------------------------------------------------
# block results / tx lookup (need stores + indexer)
# ---------------------------------------------------------------------------


def block_results(env, height=None) -> dict:
    h = _height_or_latest(env, height)
    resp = env.state_store.load_finalize_block_response(h)
    if resp is None:
        raise RPCError(f"results for height {h} not available")
    return {
        "height": str(h),
        "txs_results": [
            enc.enc_exec_tx_result(r) for r in (resp.tx_results or [])
        ],
        "finalize_block_events": enc.enc_events(resp.events),
        "validator_updates": [
            {
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": enc.b64(vu.pub_key.bytes()),
                },
                "power": str(vu.power),
            }
            for vu in (resp.validator_updates or [])
        ],
        "app_hash": enc.hex_bytes(resp.app_hash),
    }


def tx(env, hash=None, prove=False) -> dict:  # noqa: A002
    if env.tx_indexer is None:
        raise RPCError("transaction indexing is disabled")
    if not hash:
        raise RPCError("hash is required", code=-32602)
    raw = bytes.fromhex(hash) if isinstance(hash, str) else bytes(hash)
    res = env.tx_indexer.get(raw)
    if res is None:
        raise RPCError(f"tx ({hash}) not found")
    return _enc_tx_result(res, prove, env)


def _page_window(page, per_page, total) -> tuple[int, int]:
    page_n = _int(page, "page", 1) or 1
    per = _int(per_page, "per_page", 30) or 30
    if per < 1:
        raise RPCError("per_page must be at least 1", code=-32602)
    per = min(per, 100)
    pages = max(1, (total + per - 1) // per)
    if page_n < 1 or page_n > pages:
        raise RPCError(
            f"page should be within [1, {pages}] range", code=-32602
        )
    return (page_n - 1) * per, per


def _enc_tx_result(res, prove, env, proof_cache=None) -> dict:
    out = {
        "hash": enc.hex_bytes(res.tx_hash),
        "height": str(res.height),
        "index": res.index,
        "tx_result": enc.enc_exec_tx_result(res.result),
        "tx": enc.b64(res.tx),
    }
    if prove:
        cached = (proof_cache or {}).get(res.height)
        if cached is None:
            blk = env.block_store.load_block(res.height)
            if blk is None:
                return out
            from ...crypto import merkle

            cached = merkle.proofs_from_byte_slices(list(blk.data.txs))
            if proof_cache is not None:
                proof_cache[res.height] = cached
        root, proofs = cached
        pr = proofs[res.index]
        out["proof"] = {
            "root_hash": enc.hex_bytes(root),
            "data": enc.b64(res.tx),
            "proof": {
                "total": str(pr.total),
                "index": str(pr.index),
                "leaf_hash": enc.b64(pr.leaf_hash),
                "aunts": [enc.b64(a) for a in pr.aunts],
            },
        }
    return out


def tx_search(env, query=None, prove=False, page=None, per_page=None,
              order_by=None) -> dict:
    if env.tx_indexer is None:
        raise RPCError("transaction indexing is disabled")
    if not query:
        raise RPCError("query is required", code=-32602)
    results = env.tx_indexer.search(query)
    if (order_by or "asc") == "desc":
        results = list(reversed(results))
    start, per = _page_window(page, per_page, len(results))
    subset = results[start : start + per]
    proof_cache: dict = {}
    return {
        "txs": [
            _enc_tx_result(r, prove, env, proof_cache) for r in subset
        ],
        "total_count": str(len(results)),
    }


def block_search(env, query=None, page=None, per_page=None, order_by=None) -> dict:
    if env.block_indexer is None:
        raise RPCError("block indexing is disabled")
    if not query:
        raise RPCError("query is required", code=-32602)
    heights = env.block_indexer.search(query)
    if (order_by or "asc") == "desc":
        heights = list(reversed(heights))
    start, per = _page_window(page, per_page, len(heights))
    subset = heights[start : start + per]
    blocks = []
    for h in subset:
        m = env.block_store.load_block_meta(h)
        b = env.block_store.load_block(h)
        if m and b:
            blocks.append(
                {"block_id": enc.enc_block_id(m.block_id), "block": enc.enc_block(b)}
            )
    return {"blocks": blocks, "total_count": str(len(heights))}


def broadcast_evidence(env, evidence=None) -> dict:
    """Submit evidence (base64 of the canonical serialization) to the
    pool — the light client's detector reports attacks through this
    (rpc/core/evidence.go BroadcastEvidence)."""
    import base64 as _b64

    if not evidence:
        raise RPCError("evidence is required", code=-32602)
    if env.evidence_pool is None:
        raise RPCError("this node has no evidence pool")
    from ...types import serialization as ser

    try:
        ev = ser.loads(_b64.b64decode(evidence))
    except Exception as e:
        raise RPCError(f"undecodable evidence: {e}", code=-32602)
    try:
        env.evidence_pool.add_evidence(ev)
    except Exception as e:
        raise RPCError(f"evidence rejected: {e}")
    return {"hash": ev.hash().hex().upper()}


# ---------------------------------------------------------------------------
# light-client proof service (light/service.py LightService)
# ---------------------------------------------------------------------------


def _light_service(env):
    svc = env.extra.get("light_service")
    if svc is None:
        raise RPCError(
            "light service is disabled (set COMETBFT_TPU_LIGHT=1)",
            code=-32601,
        )
    return svc


def light_verify(
    env, height=None, trust_height=None, trust_hash=None, deadline=None
) -> dict:
    """Skipping-verification proof: verify the block at ``height``
    relative to ``trust_height`` (the service's own root when omitted)
    and return its verified identity + bisection trace. Backpressure
    and deadline rejections map to distinct JSON-RPC error codes so
    clients can tell "retry later" (-32005) from "took too long"
    (-32004) from "bad request / failed verification"."""
    from ...light import service as light_service_mod

    svc = _light_service(env)
    h = _int(height, "height")
    if h is None or h <= 0:
        raise RPCError("height must be a positive integer", code=-32602)
    th = _int(trust_height, "trust_height")
    raw_hash = None
    if trust_hash is not None and trust_hash != "":
        # hex string only: bytes(<int>) would silently mint a zeroed
        # root and anything else belongs in a -32602, not a TypeError
        if not isinstance(trust_hash, str):
            raise RPCError("trust_hash must be a hex string", code=-32602)
        try:
            raw_hash = bytes.fromhex(trust_hash)
        except ValueError:
            raise RPCError("invalid trust_hash hex", code=-32602)
    dl = None
    if deadline is not None and deadline != "":
        try:
            dl = float(deadline)
        except (TypeError, ValueError):
            raise RPCError(f"invalid deadline: {deadline!r}", code=-32602)
    try:
        result = svc.verify_at_height(
            h, trust_height=th, trust_hash=raw_hash, deadline_s=dl
        )
    except light_service_mod.DeadlineExceededError as e:
        raise RPCError(str(e), code=-32004)
    except (
        light_service_mod.ServiceBusyError,
        light_service_mod.ServiceStoppedError,
    ) as e:
        raise RPCError(str(e), code=-32005)
    except Exception as e:
        raise RPCError(f"light verification failed: {e}")
    result["verified_heights"] = [
        str(x) for x in result.get("verified_heights", [])
    ]
    return result


def light_status(env) -> dict:
    """Observability surface of the light proof service: admission
    counters, cache occupancy/hit tallies, coalescer window counts."""
    svc = _light_service(env)
    return svc.status()


def unsafe_flush_mempool(env) -> dict:
    """Drop every pending tx (rpc/core/mempool.go UnsafeFlushMempool;
    registered only with unsafe routes enabled)."""
    env.mempool.flush()
    return {}


def unsafe_dial_seeds(env, seeds=None) -> dict:
    """Crawl the given seeds immediately (rpc/core/net.go UnsafeDialSeeds)."""
    if not seeds or not isinstance(seeds, (list, tuple)):
        raise RPCError("seeds must be a non-empty list", code=-32602)
    if env.switch is None:
        raise RPCError("p2p switch unavailable")
    # best-effort book insert so PEX keeps the addresses, but the dial
    # itself needs only the switch (net.go UnsafeDialSeeds works with
    # PEX disabled)
    pex = env.extra.get("pex_reactor")
    book = getattr(pex, "book", None) if pex is not None else None
    if book is not None:
        for addr in seeds:
            try:
                book.add_address(addr, src="rpc")
            except Exception:
                pass
    env.switch.dial_peers_async(list(seeds))
    return {}


def unsafe_dial_peers(env, peers=None, persistent=False) -> dict:
    """Dial peers directly (rpc/core/net.go UnsafeDialPeers). The
    ``persistent`` flag is accepted for API parity; persistence is
    decided by the switch's configured persistent set."""
    if not peers or not isinstance(peers, (list, tuple)):
        raise RPCError("peers must be a non-empty list", code=-32602)
    if env.switch is None:
        raise RPCError("p2p switch unavailable")
    env.switch.dial_peers_async(list(peers))
    return {}


# ---------------------------------------------------------------------------
# route table (rpc/core/routes.go:12-56)
# ---------------------------------------------------------------------------

ROUTES = {
    "health": health,
    "status": status,
    "net_info": net_info,
    "genesis": genesis,
    "blockchain": blockchain,
    "block": block,
    "block_by_hash": block_by_hash,
    "block_results": block_results,
    "header": header,
    "commit": commit,
    "validators": validators,
    "consensus_state": consensus_state,
    "dump_consensus_state": dump_consensus_state,
    "consensus_params": consensus_params,
    "unconfirmed_txs": unconfirmed_txs,
    "num_unconfirmed_txs": num_unconfirmed_txs,
    "abci_info": abci_info,
    "abci_query": abci_query,
    "broadcast_tx_async": broadcast_tx_async,
    "broadcast_tx_sync": broadcast_tx_sync,
    "broadcast_tx_commit": broadcast_tx_commit,
    "check_tx": check_tx,
    "tx": tx,
    "tx_search": tx_search,
    "block_search": block_search,
    "broadcast_evidence": broadcast_evidence,
    "genesis_chunked": genesis_chunked,
    "header_by_hash": header_by_hash,
    "light_verify": light_verify,
    "light_status": light_status,
    "tx_trace": tx_trace,
}

# Operator-only routes, merged in when config.rpc.unsafe is set
# (rpc/core/routes.go AddUnsafeRoutes).
UNSAFE_ROUTES = {
    "unsafe_flush_mempool": unsafe_flush_mempool,
    "dial_seeds": unsafe_dial_seeds,
    "dial_peers": unsafe_dial_peers,
}
