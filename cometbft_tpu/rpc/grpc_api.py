"""Legacy gRPC broadcast API (reference: rpc/grpc/api.go — the
deprecated-but-shipped BroadcastAPI service with Ping and BroadcastTx;
kept for operator/tool parity alongside the JSON-RPC surface).

Same transport approach as the ABCI gRPC boundary (abci/grpc.py): real
gRPC/HTTP-2 via generic method handlers; payloads are plain JSON (the
service carries only strings and flat response dicts).
"""

from __future__ import annotations

import concurrent.futures
import json

import grpc

from ..libs.service import BaseService

_SERVICE = "cometbft.rpc.BroadcastAPI"


def _ser(msg) -> bytes:
    # plain JSON: the BroadcastAPI payloads are strings and flat dicts
    # (the tagged dataclass codec is for typed message sets)
    return json.dumps(msg, separators=(",", ":")).encode()


def _de(data: bytes):
    return json.loads(data)


class BroadcastAPIServer(BaseService):
    """Ping + BroadcastTx over gRPC (rpc/grpc/api.go)."""

    def __init__(self, addr: str, env, max_workers: int = 4):
        super().__init__("rpc-grpc-broadcast")
        for scheme in ("grpc://", "tcp://"):
            if addr.startswith(scheme):
                addr = addr[len(scheme) :]
        self.addr = addr
        self.env = env  # rpc.core Environment (mempool + stores)
        self._max_workers = max_workers
        self._server = None

    def on_start(self) -> None:
        from .core.routes import broadcast_tx_sync

        env = self.env

        def ping(request, context):
            return {}

        def broadcast_tx(request, context):
            # request: base64 tx string, same shape as the JSON-RPC param
            res = broadcast_tx_sync(env, tx=request)
            return {
                "check_tx": {
                    "code": int(res["code"]),
                    "data": res.get("data", ""),
                    "log": res.get("log", ""),
                },
                "hash": res.get("hash", ""),
            }

        handlers = {
            "ping": grpc.unary_unary_rpc_method_handler(
                ping, request_deserializer=_de, response_serializer=_ser
            ),
            "broadcast_tx": grpc.unary_unary_rpc_method_handler(
                broadcast_tx,
                request_deserializer=_de,
                response_serializer=_ser,
            ),
        }
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(
                max_workers=self._max_workers,
                thread_name_prefix="rpc-grpc",
            )
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        bound = self._server.add_insecure_port(self.addr)
        if bound == 0:
            raise OSError(f"cannot bind BroadcastAPI at {self.addr}")
        self.bound_port = bound
        self._server.start()

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait(2.0)


class BroadcastAPIClient:
    """Client for the BroadcastAPI service (rpc/grpc/client.go)."""

    def __init__(self, addr: str, timeout: float = 10.0):
        for scheme in ("grpc://", "tcp://"):
            if addr.startswith(scheme):
                addr = addr[len(scheme) :]
        self.timeout = timeout
        self._channel = grpc.insecure_channel(addr)
        grpc.channel_ready_future(self._channel).result(timeout=timeout)
        self._ping = self._channel.unary_unary(
            f"/{_SERVICE}/ping",
            request_serializer=_ser,
            response_deserializer=_de,
        )
        self._btx = self._channel.unary_unary(
            f"/{_SERVICE}/broadcast_tx",
            request_serializer=_ser,
            response_deserializer=_de,
        )

    def ping(self) -> dict:
        return self._ping("", timeout=self.timeout)

    def broadcast_tx(self, tx: bytes) -> dict:
        import base64

        return self._btx(
            base64.b64encode(tx).decode(), timeout=self.timeout
        )

    def close(self) -> None:
        self._channel.close()
