"""Decode RPC JSON back into data-model types (inverse of encoding.py).

Used by RPC clients that need typed results — most importantly the light
client's RPC provider (reference: rpc/client http + light/provider/http),
which must reconstruct byte-exact headers/commits so hashes and signature
checks reproduce.
"""

from __future__ import annotations

import base64
from datetime import datetime, timezone

from ..crypto.keys import Ed25519PubKey
from ..types.block import (
    BLOCK_ID_FLAG_ABSENT,
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
    Version,
)
from ..types.validator_set import Validator, ValidatorSet


def from_hex(s: str) -> bytes:
    return bytes.fromhex(s) if s else b""


def from_b64(s) -> bytes:
    return base64.b64decode(s) if s else b""


def parse_rfc3339(s: str) -> int:
    """RFC3339 with nanosecond fraction -> ns since epoch."""
    if not s:
        return 0
    base, _, frac_z = s.partition(".")
    dt = datetime.strptime(base, "%Y-%m-%dT%H:%M:%S").replace(
        tzinfo=timezone.utc
    )
    ns = int(dt.timestamp()) * 1_000_000_000
    if frac_z:
        frac = frac_z.rstrip("Z")
        ns += int(frac.ljust(9, "0")[:9])
    return ns


def dec_block_id(d: dict) -> BlockID:
    parts = d.get("parts") or {}
    return BlockID(
        hash=from_hex(d.get("hash", "")),
        part_set_header=PartSetHeader(
            total=int(parts.get("total", 0)),
            hash=from_hex(parts.get("hash", "")),
        ),
    )


def dec_header(d: dict) -> Header:
    v = d.get("version") or {}
    return Header(
        version=Version(block=int(v.get("block", 0)), app=int(v.get("app", 0))),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time_ns=parse_rfc3339(d["time"]),
        last_block_id=dec_block_id(d.get("last_block_id") or {}),
        last_commit_hash=from_hex(d.get("last_commit_hash", "")),
        data_hash=from_hex(d.get("data_hash", "")),
        validators_hash=from_hex(d.get("validators_hash", "")),
        next_validators_hash=from_hex(d.get("next_validators_hash", "")),
        consensus_hash=from_hex(d.get("consensus_hash", "")),
        app_hash=from_hex(d.get("app_hash", "")),
        last_results_hash=from_hex(d.get("last_results_hash", "")),
        evidence_hash=from_hex(d.get("evidence_hash", "")),
        proposer_address=from_hex(d.get("proposer_address", "")),
    )


def dec_commit_sig(d: dict) -> CommitSig:
    return CommitSig(
        block_id_flag=int(d.get("block_id_flag", BLOCK_ID_FLAG_ABSENT)),
        validator_address=from_hex(d.get("validator_address", "")),
        timestamp_ns=parse_rfc3339(d.get("timestamp", "")),
        signature=from_b64(d.get("signature")),
    )


def dec_commit(d: dict) -> Commit:
    return Commit(
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=dec_block_id(d["block_id"]),
        signatures=[dec_commit_sig(s) for s in d.get("signatures", [])],
    )


def dec_validator(d: dict) -> Validator:
    pk = d.get("pub_key") or {}
    return Validator(
        address=from_hex(d["address"]),
        pub_key=Ed25519PubKey(from_b64(pk.get("value"))),
        voting_power=int(d["voting_power"]),
        proposer_priority=int(d.get("proposer_priority", 0)),
    )


def dec_validator_set(vals: list[dict]) -> ValidatorSet:
    return ValidatorSet([dec_validator(v) for v in vals])
