"""RPC layer: JSON-RPC 2.0 over HTTP + WebSocket subscriptions.

Reference: /root/reference/rpc/ (jsonrpc server, ~40 core routes, http and
local clients).
"""

from .client import HTTPClient, LocalClient, Subscription, WSClient
from .core.env import Environment
from .core.routes import ROUTES, RPCError
from .jsonrpc.server import RPCServer

__all__ = [
    "Environment",
    "HTTPClient",
    "LocalClient",
    "ROUTES",
    "RPCError",
    "RPCServer",
    "Subscription",
    "WSClient",
]
