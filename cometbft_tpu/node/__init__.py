"""L8 node assembly (reference: node/)."""

from .node import (  # noqa: F401
    Node,
    default_new_node,
    init_files,
    load_genesis,
)
