"""Node assembly (reference: node/node.go:138 NewNode, node/setup.go).

Wiring order mirrors the reference: DBs → state → proxy app (4 conns) →
event bus → handshake (app replay) → mempool → consensus → RPC/p2p (as
those layers land). ``Node.start`` boots services in dependency order;
``stop`` unwinds them.
"""

from __future__ import annotations

import json
import os
import threading

from .. import proxy
from ..abci.kvstore import KVStoreApplication
from ..blocksync import BlocksyncReactor
from ..config import Config
from ..consensus import ConsensusState
from ..consensus.reactor import ConsensusReactor
from ..consensus.replay import Handshaker
from ..consensus.wal import WAL
from ..evidence import EvidencePool, EvidenceReactor
from ..libs import db as dbm
from ..libs.service import BaseService
from ..mempool import CListMempool
from ..mempool.reactor import MempoolReactor
from ..p2p import MultiplexTransport, NodeInfo, NodeKey, Switch
from ..p2p.conn.connection import MConnConfig
from ..privval import FilePV
from ..state import BlockExecutor, Store, make_genesis_state
from ..store import BlockStore
from ..types import GenesisDoc
from ..types.event_bus import EventBus


def init_files(config: Config) -> dict:
    """``cometbft init`` (cmd/cometbft/commands/init.go): write config dir,
    node key, validator key, and a single-validator genesis if absent."""
    home = os.path.expanduser(config.base.home)
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)

    pv_key_file = config.base.resolve(config.base.priv_validator_key_file)
    pv_state_file = config.base.resolve(config.base.priv_validator_state_file)
    pv = FilePV.load_or_generate(pv_key_file, pv_state_file)

    # durable config (config/toml.go WriteConfigFile): written once so
    # operators edit a file, not code
    from ..config_file import save_toml

    toml_path = config.base.resolve("config/config.toml")
    if not os.path.exists(toml_path):
        save_toml(config, toml_path)

    genesis_file = config.base.resolve(config.base.genesis_file)
    created_genesis = False
    if not os.path.exists(genesis_file):
        from ..types import GenesisValidator

        doc = GenesisDoc(
            chain_id=f"test-chain-{os.urandom(3).hex()}",
            validators=[
                GenesisValidator(pub_key=pv.get_pub_key(), power=10)
            ],
        )
        doc.validate_and_complete()
        with open(genesis_file, "w") as f:
            f.write(doc.to_json())
        created_genesis = True
    return {
        "pv": pv,
        "genesis_file": genesis_file,
        "created_genesis": created_genesis,
    }


def load_genesis(config: Config) -> GenesisDoc:
    with open(config.base.resolve(config.base.genesis_file)) as f:
        return GenesisDoc.from_json(f.read())


def _make_db(config: Config, name: str) -> dbm.DB:
    if config.base.db_backend == "mem":
        return dbm.MemDB()
    data_dir = config.base.resolve("data")
    path = os.path.join(data_dir, f"{name}.db")
    if config.base.db_backend == "native":
        # C++ engine (the cgo-backend tier of cometbft-db). An unusable
        # backend is FATAL, not a fallback: silently writing FileDB
        # format under a db_backend=native config would poison every
        # offline tool that later trusts the config (compacting a
        # foreign-format file erases it). Reference behavior: the node
        # refuses to start when the configured backend can't open.
        from ..libs.db_native import NativeDB

        return NativeDB(path)
    return dbm.FileDB(path)


def _app_client_creator(config: Config, app_db: dbm.DB):
    """proxy/client.go DefaultClientCreator."""
    pa = config.base.proxy_app
    if pa in ("kvstore", "persistent_kvstore"):
        return proxy.local_client_creator(KVStoreApplication(app_db)), True
    if pa == "noop":
        from ..abci.application import BaseApplication

        return proxy.local_client_creator(BaseApplication()), True
    if pa.startswith("grpc://"):
        return proxy.grpc_client_creator(pa), False
    if pa.startswith(("tcp://", "unix://")):
        return proxy.socket_client_creator(pa), False
    raise ValueError(f"unknown proxy_app {pa!r}")


class Node(BaseService):
    def __init__(self, config: Config, genesis: GenesisDoc, priv_validator):
        super().__init__("node")
        self.config = config
        self.genesis = genesis

        # 0. Observability floor: leveled structured logging + metrics
        # (reference: libs/log + per-package prometheus metrics).
        from ..libs import log as liblog
        from ..libs import metrics as libmetrics

        self.logger = liblog.Logger(
            level=liblog.parse_level(config.base.log_level)
        ).with_fields(chain=genesis.chain_id[:16])
        self.metrics = libmetrics.NodeMetrics()
        libmetrics.push_node_metrics(self.metrics)

        # 1. DBs (setup.go initDBs:107)
        self.app_db = _make_db(config, "app")
        self.block_db = _make_db(config, "blockstore")
        self.state_db = _make_db(config, "state")
        self.block_store = BlockStore(self.block_db)
        self.state_store = Store(self.state_db)

        # 2. State from DB or genesis (setup.go:537)
        state = self.state_store.load()
        if state is None:
            state = make_genesis_state(genesis)
            self.state_store.save(state)

        # 3. Proxy app — 4 connections (setup.go:123)
        creator, _in_process = _app_client_creator(config, self.app_db)
        self.proxy_app = proxy.AppConns(
            creator, on_error=self._on_app_error
        )
        self.proxy_app.start()

        # 4. EventBus (setup.go:132)
        self.event_bus = EventBus()
        self.event_bus.start()

        # 5. Handshake: sync app to store (setup.go:169 doHandshake)
        executor_for_replay = BlockExecutor(
            self.state_store, self.proxy_app.consensus,
            block_store=self.block_store,
        )
        handshaker = Handshaker(
            self.state_store, state, self.block_store, genesis,
            block_exec=executor_for_replay,
        )
        handshaker.handshake(self.proxy_app)
        state = handshaker.state

        # 6. Mempool (setup.go:223)
        self.mempool = CListMempool(
            config.mempool,
            self.proxy_app.mempool,
            height=state.last_block_height,
        )
        if config.consensus.create_empty_blocks is False:
            self.mempool.enable_txs_available()

        # 7. Evidence pool (setup.go:254)
        self.evidence_db = _make_db(config, "evidence")
        self.evidence_pool = EvidencePool(
            self.evidence_db, self.state_store, self.block_store
        )

        # 8. Block executor + consensus (setup.go:254-292)
        self.block_exec = BlockExecutor(
            self.state_store,
            self.proxy_app.consensus,
            mempool=self.mempool,
            evidence_pool=self.evidence_pool,
            block_store=self.block_store,
            event_bus=self.event_bus,
        )
        wal_path = config.base.resolve(config.consensus.wal_file)
        os.makedirs(os.path.dirname(wal_path), exist_ok=True)
        self.consensus = ConsensusState(
            config.consensus,
            state,
            self.block_exec,
            self.block_store,
            tx_notifier=self.mempool,
            evidence_pool=self.evidence_pool,
            event_bus=self.event_bus,
            wal=WAL(wal_path),
        )
        if priv_validator is not None:
            self.consensus.set_priv_validator(priv_validator)
        self.consensus.logger = self.logger.with_module("consensus")
        self.state = state
        self._txs_available_thread: threading.Thread | None = None
        self._last_commit_time = 0.0
        self.consensus.add_block_committed_hook(self._on_block_committed)
        # Commit-chain failures fail-stop the whole node (the reference
        # panics in finalizeCommit) — same posture as _on_app_error.
        self.consensus.on_fatal = self._on_app_error

        # 8b. Pipelined heights (consensus/pipeline.py): speculative
        # execution + ordered commit-writer behind a durability barrier.
        # Knob-gated (COMETBFT_TPU_PIPELINE / COMETBFT_TPU_SPEC_EXEC);
        # the commit-writer fsyncs through the consensus WAL, so it must
        # be wired to the SAME instance the FSM logs to.
        from ..consensus.pipeline import CommitPipeline, pipeline_mode, spec_mode

        pipe = CommitPipeline(
            self.block_exec, self.consensus.wal, on_fatal=self._on_app_error
        )
        pmode = pipeline_mode()
        pipe.enabled = pmode in ("auto", "on", "inline")
        pipe.inline = pmode == "inline"
        smode = spec_mode()
        pipe.spec_enabled = smode == "on" or (
            smode == "auto"
            and getattr(
                self.proxy_app.consensus, "supports_speculation", lambda: False
            )()
        )
        pipe.note_base(state.last_block_height)
        self.block_exec.prune_gate = pipe.durable_height
        self.consensus.pipeline = pipe

        # 9. P2P: transport + switch + reactors (setup.go:325,394)
        self.node_key = NodeKey.load_or_generate(
            config.base.resolve(config.base.node_key_file)
        )
        # Flight-ring origin: every row the consensus receive routine
        # records carries this node's id prefix, so per-node timelines
        # decode even when several nodes share one process (the same
        # prefix the netstats peer label uses on the remote side).
        from ..libs import health as libhealth

        self.consensus.health_origin = libhealth.register_origin(
            self.node_key.node_id[:10]
        )
        # the commit-writer/spec workers record ring rows for the same
        # node as the receive routine
        pipe.health_origin = self.consensus.health_origin
        # Blocksync only when it can help: enabled in config and we're not
        # the sole validator (node.go onlyValidatorIsUs check).
        only_us = (
            priv_validator is not None
            and len(state.validators) == 1
            and state.validators.has_address(
                bytes(priv_validator.get_pub_key().address())
            )
        )
        # Statesync only makes sense for an empty node (node.go:377).
        self.statesync_enabled = (
            config.statesync.enable and state.last_block_height == 0
        )
        run_blocksync = config.base.block_sync and not only_us
        self.consensus_reactor = ConsensusReactor(
            self.consensus, wait_sync=run_blocksync or self.statesync_enabled
        )
        self.blocksync_reactor = BlocksyncReactor(
            state,
            self.block_exec,
            self.block_store,
            # during statesync, blocksync stays parked until the snapshot
            # restore hands it a state (switch_to_block_sync)
            run_blocksync and not self.statesync_enabled,
            consensus_reactor=self.consensus_reactor,
            min_recv_rate=config.blocksync.min_recv_rate,
        )
        if self.statesync_enabled:
            # parked-for-statesync is NOT synced: the constructor pre-sets
            # the event for plain non-blocksync nodes only
            self.blocksync_reactor.synced.clear()
        self.mempool_reactor = MempoolReactor(config.mempool, self.mempool)
        # Advertised software version; env-overridable so the e2e upgrade
        # perturbation (restart under a bumped version — the reference's
        # docker-image swap, runner/perturb.go:16-31) is observable over
        # RPC/p2p while staying protocol-compatible.
        from ..state.state import SOFTWARE_VERSION

        from ..libs import netstats as libnetstats

        self.node_info = NodeInfo(
            node_id=self.node_key.node_id,
            listen_addr="",
            network=genesis.chain_id,
            moniker=config.base.moniker,
            version=os.environ.get(
                "COMETBFT_TPU_SOFTWARE_VERSION", SOFTWARE_VERSION
            ),
            # advertise the provenance-stamp capability: messages are
            # stamped only toward peers that advertise it back, so an
            # unstamped peer sees byte-identical wire traffic
            # (COMETBFT_TPU_NET_STAMP=0 withdraws the advertisement)
            other=(
                {libnetstats.NODEINFO_STAMP_KEY: 1}
                if libnetstats.stamping_wanted()
                else {}
            ),
        )
        self.transport = MultiplexTransport(
            self.node_key,
            self.node_info,
            handshake_timeout=config.p2p.handshake_timeout_ns / 1e9,
            dial_timeout=config.p2p.dial_timeout_ns / 1e9,
        )
        self.switch = Switch(
            self.transport,
            mconn_config=MConnConfig(
                send_rate=config.p2p.send_rate,
                recv_rate=config.p2p.recv_rate,
                flush_throttle=config.p2p.flush_throttle_timeout_ns / 1e9,
            ),
            max_inbound=config.p2p.max_num_inbound_peers,
            max_outbound=config.p2p.max_num_outbound_peers,
        )
        self.evidence_reactor = EvidenceReactor(self.evidence_pool)
        # 9c. Statesync reactor: every node serves snapshots; a syncing
        # node also runs the Syncer (setup.go:476 startStateSync)
        from ..statesync import StatesyncReactor, Syncer

        self.statesync_reactor = StatesyncReactor(self.proxy_app.snapshot)
        self.syncer = None
        if self.statesync_enabled:
            sp = self._make_state_provider()
            self.syncer = Syncer(
                self.proxy_app.snapshot,
                self.proxy_app.query,
                sp,
                self.statesync_reactor.request_chunk,
                chunk_timeout=config.statesync.chunk_request_timeout_ns / 1e9,
                discovery_time=config.statesync.discovery_time_ns / 1e9,
            )
            self.statesync_reactor.syncer = self.syncer

        self.switch.add_reactor("MEMPOOL", self.mempool_reactor)
        self.switch.add_reactor("BLOCKSYNC", self.blocksync_reactor)
        self.switch.add_reactor("EVIDENCE", self.evidence_reactor)
        self.switch.add_reactor("CONSENSUS", self.consensus_reactor)
        self.switch.add_reactor("STATESYNC", self.statesync_reactor)

        # 9d. PEX + address book (setup.go:427,454)
        from ..p2p.pex import AddrBook, PexReactor

        self.addr_book = AddrBook(
            config.base.resolve("config/addrbook.json")
        )
        self.addr_book.add_our_address(self.node_key.node_id)
        self.pex_reactor = None
        if config.p2p.pex:
            self.pex_reactor = PexReactor(
                self.addr_book,
                seed_mode=config.p2p.seed_mode,
                max_outbound=config.p2p.max_num_outbound_peers,
            )
            self.switch.add_reactor("PEX", self.pex_reactor)
        self.node_info.channels = self.switch.channel_ids()

        # 9b. Indexers (setup.go:141 createAndStartIndexerService)
        from ..state.indexer import (
            IndexerService,
            KVBlockIndexer,
            KVTxIndexer,
        )

        if config.tx_index.indexer == "kv":
            self.indexer_db = _make_db(config, "tx_index")
            self.tx_indexer = KVTxIndexer(self.indexer_db)
            self.block_indexer = KVBlockIndexer(self.indexer_db)
        elif config.tx_index.indexer == "sqlite":
            # external-DB sink (the reference's psql-sink tier,
            # state/indexer/sink/psql/psql.go:250): relational event
            # storage, SQL-translated search
            from ..state.sink import (
                SQLiteBlockIndexer,
                SQLiteEventSink,
                SQLiteTxIndexer,
            )

            self.indexer_db = None
            self.event_sink = SQLiteEventSink(
                os.path.join(config.base.resolve("data"), "events.sqlite")
            )
            self.tx_indexer = SQLiteTxIndexer(self.event_sink)
            self.block_indexer = SQLiteBlockIndexer(self.event_sink)
        else:
            self.indexer_db = None
            self.tx_indexer = None
            self.block_indexer = None
        if self.tx_indexer is not None:
            self.indexer_service = IndexerService(
                self.tx_indexer, self.block_indexer, self.event_bus
            )
            self.indexer_service.start()
        else:
            self.indexer_service = None

        # 10. RPC environment + server (node.go:536 startRPC)
        from ..rpc import Environment, RPCServer

        self.rpc_env = Environment(
            block_store=self.block_store,
            state_store=self.state_store,
            consensus=self.consensus,
            consensus_reactor=self.consensus_reactor,
            mempool=self.mempool,
            evidence_pool=self.evidence_pool,
            switch=self.switch,
            proxy_app_query=self.proxy_app.query,
            event_bus=self.event_bus,
            genesis=genesis,
            node_info=self.node_info,
            tx_indexer=self.tx_indexer,
            block_indexer=self.block_indexer,
            priv_validator_pub_key=(
                priv_validator.get_pub_key()
                if priv_validator is not None
                else None
            ),
            config=config,
        )
        self.rpc_env.extra["metrics"] = self.metrics
        self.rpc_env.extra["refresh_metrics"] = self._refresh_metrics
        self.rpc_env.extra["pex_reactor"] = self.pex_reactor
        rpc_routes = None
        if getattr(config.rpc, "unsafe", False):
            from ..rpc.core.routes import ROUTES, UNSAFE_ROUTES

            rpc_routes = {**ROUTES, **UNSAFE_ROUTES}
        self.rpc_server = (
            RPCServer(
                self.rpc_env,
                config.rpc.laddr,
                logger=self.logger.with_module("rpc"),
                routes=rpc_routes,
            )
            if config.rpc.laddr
            else None
        )
        # pprof/JAX-profiler server (node/node.go:651 startPprofServer)
        self.pprof_server = None
        if getattr(config.rpc, "pprof_laddr", ""):
            from ..libs.pprof import PprofServer

            self.pprof_server = PprofServer(
                config.rpc.pprof_laddr,
                logger=self.logger.with_module("pprof"),
            )
        # Dedicated Prometheus scrape listener (the reference's
        # Instrumentation server, node/node.go:630 + config/config.go
        # prometheus_listen_addr). COMETBFT_TPU_PROM_ADDR overrides the
        # config section; starting it also enables libs/devstats so the
        # XLA compile/device-memory/transfer families carry real data.
        from ..libs import devstats as libdevstats

        prom_addr = libdevstats.prometheus_addr(config)
        self.prometheus_server = None
        if prom_addr:
            self.prometheus_server = libdevstats.PrometheusServer(
                prom_addr,
                self.metrics.registry,
                refresh=self._refresh_metrics,
                logger=self.logger.with_module("prometheus"),
            )
        # Cross-caller verify coalescer (crypto/coalesce.py): the
        # steady-state vote path's feeder for the device kernel.
        # COMETBFT_TPU_COALESCE gates it; the decision is deferred to
        # on_start because in "auto" mode it probes the jax backend —
        # constructing a Node must stay free of backend init.
        self.verify_coalescer = None
        # Cross-caller hash plane (crypto/hashplane.py): coalesced
        # SHA-256 for mempool tx keys, PartSet leaves and merkle
        # levels. COMETBFT_TPU_HASH gates it; same deferred-probe boot
        # as the verify coalescer.
        self.hash_plane = None
        # Health monitor (libs/health): started in _finish_start — the
        # always-on flight recorder + SLO watchdogs + black-box dumps.
        self.health_monitor = None
        # Peer-health suspicion scorer (p2p/suspicion): started in
        # _finish_start behind COMETBFT_TPU_SUSPICION — evicts gray
        # (slow-but-alive) peers off the netstats signals.
        self.suspicion_scorer = None
        # Light-client proof service (light/service.py): serves
        # light_verify/light_status over the RPC server, funnelling
        # thousands of clients' skipping-verification commit checks
        # through the shared verifiers (and the coalescer, when one is
        # routed). Knob-gated (COMETBFT_TPU_LIGHT); started LAST in
        # _finish_start with leak-safe unwind like the health monitor.
        self.light_service = None
        self.switch.logger = self.logger.with_module("p2p")
        self.blocksync_reactor.logger = self.logger.with_module("blocksync")
        self.statesync_reactor.logger = self.logger.with_module("statesync")

    def _on_block_committed(self, height: int) -> None:
        """Metrics + the per-commit log line (consensus/metrics.go)."""
        import time as _time

        meta = self.block_store.load_block_meta(height)
        now = _time.monotonic()
        self.metrics.height.set(height)
        if self._last_commit_time:
            self.metrics.block_interval.observe(now - self._last_commit_time)
        self._last_commit_time = now
        if meta is not None:
            self.metrics.block_txs.set(meta.num_txs)
            self.metrics.block_size.set(meta.block_size)
            self.metrics.total_txs.inc(meta.num_txs)
            self.logger.with_module("consensus").info(
                "finalized block",
                height=height,
                num_txs=meta.num_txs,
                app_hash=meta.header.app_hash,
            )
        # Absent signers of the block's own seen commit
        # (consensus/metrics.go MissingValidators{,Power}).
        try:
            commit = self.block_store.load_seen_commit()
            if commit is not None and commit.height == height:
                from ..types.block import BLOCK_ID_FLAG_ABSENT

                # the set that SIGNED height h is the per-height persisted
                # one — node.state is the boot-time snapshot and goes
                # stale immediately (review finding)
                vals = self.state_store.load_validators(height)
                if vals is None:
                    return
                missing = missing_power = 0
                for idx, cs in enumerate(commit.signatures):
                    if cs.block_id_flag == BLOCK_ID_FLAG_ABSENT:
                        missing += 1
                        val = vals.get_by_index(idx)
                        if val is not None:
                            missing_power += val.voting_power
                self.metrics.missing_validators.set(missing)
                self.metrics.missing_validators_power.set(missing_power)
        except Exception:
            pass  # metrics must never break the commit path

    def _refresh_metrics(self) -> None:
        """Pull-time gauges (collector pattern): cheap reads at scrape —
        nothing here may touch the consensus commit path or disk."""
        from ..libs import devstats as libdevstats

        # device memory + arena occupancy into THIS node's registry
        # (no-op unless devstats is on; never initializes a jax backend
        # from the scrape path)
        libdevstats.sample(self.metrics)
        # health SLIs + composite score from the flight recorder (lock-
        # free ring reads; never touches an engine mutex)
        from ..libs import health as libhealth

        libhealth.sample(self.metrics)
        # network-plane gauges: per-channel queue depth/high-watermark,
        # top-K peer rates (lock-free connection snapshot)
        from ..libs import netstats as libnetstats

        libnetstats.sample(self.metrics)
        out, inb = self.switch.num_peers()
        self.metrics.peers.set(out + inb)
        self.metrics.mempool_size.set(self.mempool.size())
        with self.consensus._mtx:
            vals = self.consensus.rs.validators
        if vals is not None:
            self.metrics.validators.set(len(vals))
            self.metrics.validators_power.set(vals.total_voting_power())
        if self.evidence_pool is not None:
            try:
                offenders = set()
                # walk the gossip clist directly: pending_evidence()
                # serializes every item for its byte cap — too heavy for
                # the scrape path
                for el in self.evidence_pool.evidence_list:
                    ev = el.value
                    if hasattr(ev, "vote_a"):  # DuplicateVoteEvidence
                        offenders.add(bytes(ev.vote_a.validator_address))
                    for v in getattr(ev, "byzantine_validators", []):
                        offenders.add(bytes(v.address))
                self.metrics.byzantine_validators.set(len(offenders))
            except Exception:
                pass

    def _make_state_provider(self):
        """Light-client state provider from config.state_sync
        (stateprovider.go:29: needs witnesses, so >=2 RPC servers)."""
        from ..light import TrustOptions
        from ..light.rpc_provider import RPCProvider
        from ..statesync import StateProvider

        ss = self.config.statesync
        if not ss.rpc_servers:
            raise ValueError("statesync requires state_sync.rpc_servers")
        providers = [
            RPCProvider(addr, self.genesis.chain_id)
            for addr in ss.rpc_servers
        ]
        return StateProvider(
            self.genesis.chain_id,
            self.genesis,
            providers,
            TrustOptions(
                period_ns=ss.trust_period_ns,
                height=ss.trust_height,
                hash=bytes.fromhex(ss.trust_hash),
            ),
            initial_height=self.genesis.initial_height,
        )

    def _statesync_routine(self) -> None:
        """Background restore; on success bootstrap stores and hand off to
        blocksync (node.go startStateSync + statesync completion path)."""
        slog = self.logger.with_module("statesync")
        slog.info("discovering snapshots")
        try:
            state, commit = self.syncer.sync_any(deadline=120.0)
        except Exception:
            # Any failure path (SyncError, light-client errors, RPC down)
            # must not leave the node parked forever...
            import traceback

            traceback.print_exc()
            if self.syncer.applied_any:
                # ...but once ANY chunk was applied the app is no longer at
                # genesis: block-syncing from height 1 would replay against
                # mutated app state and fork on the first app hash.
                # Fail-stop like the reference (syncer.go verifyApp panic).
                import sys

                print(
                    "statesync failed after chunks were applied; "
                    "the data dir needs a reset — stopping node",
                    file=sys.stderr,
                )
                try:
                    self.stop()
                except Exception:
                    pass
                return
            # nothing applied: safe to block-sync the chain from genesis
            slog.error("statesync failed; falling back to blocksync")
            self.blocksync_reactor.switch_to_block_sync(self.state)
            return
        self.state_store.bootstrap(state)
        self.block_store.save_seen_commit(commit)
        self.state = state
        slog.info(
            "snapshot restored", height=state.last_block_height,
            app_hash=state.app_hash,
        )
        self.blocksync_reactor.switch_to_block_sync(state)

    def _on_app_error(self, err: Exception) -> None:
        # Fail-stop: the app is the source of truth (multi_app_conn.go:129).
        if self.is_running():
            try:
                self.stop()
            except Exception:
                os._exit(1)

    # -- lifecycle (node.go:364 OnStart) -----------------------------------

    def on_start(self) -> None:
        # boot order (node.go:364): pprof → RPC → transport listen → switch
        # (starts reactors, which start consensus) → dial persistent peers
        #
        # Network-plane telemetry first (refcounted like devstats /
        # health; COMETBFT_TPU_NET=0 pins it off): it must be live
        # before the switch accepts the first connection, and the boot
        # unwind below releases it on any failure.
        from ..libs import devledger as libdevledger
        from ..libs import lockprof as liblockprof
        from ..libs import netstats as libnetstats
        from ..libs import profile as libprofile
        from ..libs import txtrace as libtxtrace

        libnetstats.acquire()
        # the device-time ledger rides the same lifecycle: per-caller
        # attribution is on exactly while a node runs (kill switch
        # COMETBFT_TPU_LEDGER=0), released on any boot failure below
        libdevledger.acquire()
        # the tx-lifecycle plane too (kill switch COMETBFT_TPU_TX=0):
        # sampled stage stamps start with the first admitted tx, and
        # this node's mempool joins the oldest-age probe the
        # tx_starved watchdog and mempool_oldest_age_seconds read
        libtxtrace.acquire()
        # lock-contention profiler (kill switch COMETBFT_TPU_LOCKPROF=0):
        # per-lock wait/hold columns record exactly while a node runs,
        # feeding lock_wait_seconds{lock}, /debug/contention and the
        # lock_contended watchdog
        liblockprof.acquire()
        # sampling profiler (kill switch COMETBFT_TPU_PROF=0): the
        # prof-sampler thread walks stacks at ~67 Hz exactly while a
        # node runs, feeding /debug/pprof/profile, the profile.json
        # bundle artifact and the cpu:<subsystem> critical-path gate
        libprofile.acquire()
        libtxtrace.register_mempool(self.mempool)
        try:
            if self.pprof_server is not None:
                self.pprof_server.start()
                self.logger.with_module("pprof").info(
                    "pprof server listening",
                    port=self.pprof_server.bound_port,
                )
            if self.rpc_server is not None:
                self.rpc_server.start()
                self.logger.with_module("rpc").info(
                    "RPC server listening", addr=self.rpc_server.bound_addr
                )
            self.transport.listen(self.config.p2p.laddr)
            self.logger.with_module("p2p").info(
                "p2p transport listening", addr=self.transport.listen_addr
            )
            self.node_info.listen_addr = self.transport.listen_addr
            # The verify coalescer starts after every other fallible boot
            # step but before the switch (which starts consensus), so the
            # very first admitted votes coalesce and an earlier boot
            # failure — pprof/RPC/listen — can't leak a routed coalescer
            # that Node.stop() (NotStartedError) would never unwind. "auto"
            # starts one only when an accelerator backend is live, so
            # host-only deployments keep their unrouted paths untouched.
            from ..crypto import coalesce as crypto_coalesce

            if crypto_coalesce.node_wants_coalescer():
                self.verify_coalescer = crypto_coalesce.VerifyCoalescer(
                    logger=self.logger.with_module("coalesce")
                )
                self.verify_coalescer.start()
                crypto_coalesce.push_active(self.verify_coalescer)
            # The hash plane rides the same boot slot and the same
            # leak-safety rules as the verify coalescer: started before
            # the switch so the first CheckTx keys / PartSet leaves
            # coalesce, unwound on ANY later boot failure. "auto"
            # starts one only on accelerator backends — host-only
            # deployments keep plain hashlib with zero round trips.
            from ..crypto import hashplane as crypto_hashplane

            try:
                if crypto_hashplane.node_wants_hashplane():
                    self.hash_plane = crypto_hashplane.HashCoalescer(
                        logger=self.logger.with_module("hashplane")
                    )
                    self.hash_plane.start()
                    crypto_hashplane.push_active(self.hash_plane)
            except BaseException:
                if self.verify_coalescer is not None:
                    crypto_coalesce.pop_active(self.verify_coalescer)
                    self.verify_coalescer.stop()
                    self.verify_coalescer = None
                raise
            try:
                self._finish_start()
            except BaseException:
                # a failed boot leaves _started unset, so Node.stop() would
                # raise NotStartedError and on_stop would never unroute the
                # coalescer — unwind it here or the orphan stays atop the
                # process-wide routing stack with its executor running
                if self.hash_plane is not None:
                    crypto_hashplane.pop_active(self.hash_plane)
                    self.hash_plane.stop()
                    self.hash_plane = None
                if self.verify_coalescer is not None:
                    crypto_coalesce.pop_active(self.verify_coalescer)
                    self.verify_coalescer.stop()
                    self.verify_coalescer = None
                raise
        except BaseException:
            # ANY boot failure: release the netstats + ledger + tx-plane
            # + lockprof + profiler acquires (on_stop never runs on a
            # half-booted node)
            libtxtrace.deregister_mempool(self.mempool)
            libprofile.release()
            liblockprof.release()
            libtxtrace.release()
            libdevledger.release()
            libnetstats.release()
            raise

    def _finish_start(self) -> None:
        """Boot steps after the verify coalescer is routed: the switch
        (which starts consensus), peer dialing, background routines and
        the Prometheus exporter. Split out so on_start can unwind the
        coalescer if ANY of them fails."""
        self.switch.start()
        persistent = [
            a.strip()
            for a in self.config.p2p.persistent_peers.split(",")
            if a.strip()
        ]
        if persistent:
            self.switch.set_persistent_peers(persistent)
            self.switch.dial_peers_async(persistent)
        # seeds prime the address book; PEX's ensure-peers loop dials them
        seeds = [
            a.strip()
            for a in self.config.p2p.seeds.split(",")
            if a.strip()
        ]
        for seed in seeds:
            self.addr_book.add_address(seed, src="seed-config")
        if self.statesync_enabled:
            threading.Thread(
                target=self._statesync_routine, name="statesync", daemon=True
            ).start()
        if self.mempool.txs_available() is not None:
            self._txs_available_thread = threading.Thread(
                target=self._forward_txs_available, daemon=True
            )
            self._txs_available_thread.start()
        # Prometheus exporter LAST: device telemetry lives exactly as
        # long as someone can scrape it (acquired here, released in
        # on_stop, refcounted across in-process nodes), and starting it
        # after every fallible boot step means a failed boot — where
        # stop() raises NotStartedError and on_stop never runs — cannot
        # leak the acquire.
        if self.prometheus_server is not None:
            from ..libs import devstats as libdevstats

            libdevstats.acquire()
            try:
                self.prometheus_server.start()
            except BaseException:
                libdevstats.release()
                raise
            self.logger.with_module("prometheus").info(
                "prometheus exporter listening",
                port=self.prometheus_server.bound_port,
            )
        # Health monitor LAST for the same leak-safety reason as the
        # exporter: its on_start acquires the flight recorder
        # (refcounted like devstats), so it must start only after every
        # fallible boot step. COMETBFT_TPU_HEALTH=0 is the kill switch;
        # the stall window scales off this node's own consensus
        # timeouts (one commit+propose cycle is the longest a healthy
        # node idles between step transitions).
        from ..libs import health as libhealth

        if libhealth.monitor_enabled():
            self.health_monitor = libhealth.HealthMonitor(
                metrics=self.metrics,
                stall_base_s=(
                    self.config.consensus.commit_timeout()
                    + self.config.consensus.propose_timeout(0)
                ),
                bundle_dir=self.config.base.resolve("data/health"),
                # legitimate silences on THIS node: still block-syncing
                # (consensus parked behind the sync reactors), or
                # intentionally waiting for transactions — a quiet
                # chain with create_empty_blocks=false is live, not
                # stalled, and must not page the operator
                idle_ok=lambda: (
                    not self.blocksync_reactor.synced.is_set()
                    or (
                        not self.config.consensus.create_empty_blocks
                        and self.mempool.size() == 0
                    )
                ),
                # slow-disk watchdog signal: this node's own WAL fsync
                # EWMA state (consensus/wal.py disk_degraded)
                disk_degraded_fn=self.consensus.wal.disk_degraded,
                logger=self.logger.with_module("health"),
            )
            try:
                self.health_monitor.start()
            except BaseException:
                # the exporter was already up: a failed boot here would
                # otherwise leak its devstats acquire (stop() raises
                # NotStartedError on a half-booted node, so on_stop
                # never runs)
                self.health_monitor = None
                self._unwind_late_services()
                raise
        # Peer-health suspicion scorer (p2p/suspicion): acts on the
        # netstats gray-failure signals by evicting suspect peers
        # through the switch. Same late-boot posture — nothing below
        # depends on it, and a failure unwinds the monitor + exporter.
        from ..p2p import suspicion as p2p_suspicion

        if p2p_suspicion.enabled():
            try:
                self.suspicion_scorer = p2p_suspicion.SuspicionScorer(
                    self.switch,
                    metrics=self.metrics,
                    logger=self.logger.with_module("suspicion"),
                )
                self.suspicion_scorer.start()
            except BaseException:
                self.suspicion_scorer = None
                self._unwind_late_services()
                raise
        # Light-client proof service LAST, same leak-safety posture:
        # everything it depends on (stores, RPC env, metrics, the
        # routed coalescer) is already up, and a failure here unwinds
        # the health monitor + exporter acquires that on_stop would
        # never release on a half-booted node.
        from ..light import service as light_service_mod

        if light_service_mod.node_wants_light_service():
            from ..light.provider import StoreBackedProvider

            try:
                self.light_service = light_service_mod.LightService(
                    provider=StoreBackedProvider(
                        self.block_store, self.state_store,
                        self.genesis.chain_id,
                    ),
                    chain_id=self.genesis.chain_id,
                    logger=self.logger.with_module("light"),
                )
                self.light_service.start()
            except BaseException:
                self.light_service = None
                self._unwind_late_services()
                raise
            self.rpc_env.extra["light_service"] = self.light_service
            self.logger.with_module("light").info(
                "light proof service serving light_verify/light_status"
            )

    def _unwind_late_services(self) -> None:
        """Stop every late-boot service started so far (reverse boot
        order) and release the exporter acquire — the ONE failure path
        of the _finish_start late-service ladder, so adding a new late
        service cannot silently miss an earlier one's teardown.  The
        caller Nones the service whose start just failed before calling
        (a half-started BaseService raises from stop())."""
        for attr in (
            "light_service", "suspicion_scorer", "health_monitor",
        ):
            svc = getattr(self, attr)
            if svc is not None:
                try:
                    if svc.is_running():
                        svc.stop()
                except Exception:
                    pass
                setattr(self, attr, None)
        self._unwind_late_boot()

    def _unwind_late_boot(self) -> None:
        """Release the Prometheus exporter's devstats acquire after a
        late _finish_start failure (a half-booted node never runs
        on_stop, so the unwind must happen at the failure site)."""
        if self.prometheus_server is not None:
            from ..libs import devstats as libdevstats

            try:
                if self.prometheus_server.is_running():
                    self.prometheus_server.stop()
            except Exception:
                pass
            libdevstats.release()

    def _forward_txs_available(self) -> None:
        ev = self.mempool.txs_available()
        while not self.quit_event().is_set():
            if ev.wait(timeout=0.2):
                ev.clear()
                self.consensus.handle_txs_available()

    def on_stop(self) -> None:
        from ..libs import metrics as libmetrics

        # pop THIS node's registry; an in-process peer node pushed later
        # keeps the top slot, an earlier one is restored (libs/metrics
        # node-stack semantics)
        libmetrics.pop_node_metrics(self.metrics)
        # Remote-signer endpoint (default_new_node attaches it): release
        # the listening socket + ping thread or a same-process restart on
        # the same laddr fails with EADDRINUSE.
        endpoint = getattr(self, "_privval_endpoint", None)
        if endpoint is not None:
            try:
                endpoint.stop()
            except Exception:
                pass
        if self.indexer_service is not None:
            try:
                self.indexer_service.stop()
            except Exception:
                pass
        if self.rpc_server is not None and self.rpc_server.is_running():
            try:
                self.rpc_server.stop()
            except Exception:
                pass
        # Light service right after the RPC listener: no new requests
        # can arrive, queued waiters are rejected, and stop() drains
        # every in-flight verification before the verifiers below it
        # (coalescer, stores) unwind.
        if getattr(self, "light_service", None) is not None:
            try:
                if self.light_service.is_running():
                    self.light_service.stop()
            except Exception:
                pass
        if self.pprof_server is not None and self.pprof_server.is_running():
            try:
                self.pprof_server.stop()
            except Exception:
                pass
        if self.prometheus_server is not None:
            from ..libs import devstats as libdevstats

            if self.prometheus_server.is_running():
                try:
                    self.prometheus_server.stop()
                except Exception:
                    pass
            libdevstats.release()
        if self.suspicion_scorer is not None:
            try:
                if self.suspicion_scorer.is_running():
                    self.suspicion_scorer.stop()
            except Exception:
                pass
        if self.health_monitor is not None:
            try:
                if self.health_monitor.is_running():
                    self.health_monitor.stop()
            except Exception:
                pass
        for svc in (self.switch, self.event_bus, self.proxy_app):
            try:
                if svc.is_running():
                    svc.stop()
            except Exception:
                pass
        # after the switch (its peers deregister their stats blocks on
        # connection stop): release this node's netstats + device-time
        # ledger + tx-plane + lock-profiler + sampling-profiler acquires
        from ..libs import devledger as libdevledger
        from ..libs import lockprof as liblockprof
        from ..libs import netstats as libnetstats
        from ..libs import profile as libprofile
        from ..libs import txtrace as libtxtrace

        libtxtrace.deregister_mempool(self.mempool)
        libprofile.release()
        liblockprof.release()
        libtxtrace.release()
        libnetstats.release()
        libdevledger.release()
        # Coalescer after consensus is down: unroute first (new callers
        # fall back to host instantly), then drain — stop() resolves
        # every pending ticket, so no verifier thread is left hanging.
        if getattr(self, "verify_coalescer", None) is not None:
            from ..crypto import coalesce as crypto_coalesce

            crypto_coalesce.pop_active(self.verify_coalescer)
            try:
                if self.verify_coalescer.is_running():
                    self.verify_coalescer.stop()
            except Exception:
                pass
        # Hash plane with the same unroute-then-drain discipline: new
        # hashers fall back to hashlib instantly, stop() resolves every
        # pending digest ticket.
        if getattr(self, "hash_plane", None) is not None:
            from ..crypto import hashplane as crypto_hashplane

            crypto_hashplane.pop_active(self.hash_plane)
            try:
                if self.hash_plane.is_running():
                    self.hash_plane.stop()
            except Exception:
                pass
        try:
            self.consensus.wal.close()
        except Exception:
            pass
        for db in (
            self.app_db, self.block_db, self.state_db, self.evidence_db,
            self.indexer_db, getattr(self, "event_sink", None),
        ):
            if db is None:
                continue
            try:
                db.close()
            except Exception:
                pass


def default_new_node(config: Config) -> Node:
    """node/setup.go:64 DefaultNewNode.

    With ``priv_validator_laddr`` set the node listens for a remote
    signer and signs through it (setup.go:595
    createAndStartPrivValidatorSocketClient); otherwise the file PV.
    """
    genesis = load_genesis(config)
    if config.base.priv_validator_laddr:
        from ..privval.signer import (
            RetrySignerClient,
            SignerClient,
            SignerListenerEndpoint,
        )

        endpoint = SignerListenerEndpoint(config.base.priv_validator_laddr)
        endpoint.start()
        try:
            pv = RetrySignerClient(SignerClient(endpoint, genesis.chain_id))
            node = Node(config, genesis, pv)
        except Exception:
            endpoint.stop()
            raise
        node._privval_endpoint = endpoint
        return node
    pv = FilePV.load_or_generate(
        config.base.resolve(config.base.priv_validator_key_file),
        config.base.resolve(config.base.priv_validator_state_file),
    )
    return Node(config, genesis, pv)
