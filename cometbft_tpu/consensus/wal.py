"""Consensus write-ahead log (reference: consensus/wal.go:59-435).

Every message the consensus loop consumes (peer msgs, own msgs, timeouts)
is written BEFORE processing; own messages are fsynced (state.go:805) so a
crash cannot double-sign. Records are CRC-framed over a rotating autofile
``Group``; ``EndHeightMessage`` marks height boundaries for
``search_for_end_height`` (replay start discovery, wal.go:232).

Record frame: ``crc32(payload) u32 | len u32 | payload`` where payload is
tagged JSON of one of the message dataclasses.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import time
from array import array

from ..libs import sync as libsync
import zlib

from ..libs import autofile
from ..libs import fail as libfail
from ..libs import health as libhealth
from ..libs import trace as libtrace
from ..libs.jsoncodec import Codec
from ..types import serialization as ser

_FRAME = struct.Struct("<II")
MAX_MSG_BYTES = 1 << 20  # wal.go maxMsgSizeBytes

# -- slow-disk degradation (gray-failure defense) -----------------------
#
# A disk that is slow-but-alive is invisible to liveness checks: fsyncs
# still return, the node still votes — just late enough that every
# propose timeout it owns expires and rounds spin. The WAL tracks an
# EWMA of its own fsync latency; when the EWMA crosses the degradation
# threshold the node enters a `disk_degraded` state that (a) widens its
# propose timeouts (consensus/state.py) so the chain slows instead of
# spinning rounds, and (b) trips the `slow_disk` health watchdog
# (libs/health) for a black-box bundle. Hysteresis: the state clears
# only once the EWMA falls below half the threshold, so a latency
# hovering at the edge cannot flap timeouts every other height.
_ENV_DISK_EWMA = "COMETBFT_TPU_HEALTH_DISK_EWMA"
_ENV_DISK_MS = "COMETBFT_TPU_HEALTH_DISK_MS"
DEFAULT_DISK_EWMA_WINDOW = 8  # EWMA alpha = 2 / (window + 1)
DEFAULT_DISK_DEGRADED_MS = 50.0


def _disk_ewma_alpha() -> float:
    window = libhealth._env_float(
        _ENV_DISK_EWMA, DEFAULT_DISK_EWMA_WINDOW
    )
    return 2.0 / (max(1.0, window) + 1.0)


def _disk_degraded_ns() -> float:
    ms = libhealth._env_float(_ENV_DISK_MS, DEFAULT_DISK_DEGRADED_MS)
    return max(0.1, ms) * 1e6


@dataclasses.dataclass(slots=True)
class EndHeightMessage:
    """Marks that ``height`` is fully committed (wal.go:38)."""

    height: int


@dataclasses.dataclass(slots=True)
class MsgInfo:
    """A consensus message + where it came from ("" = internal)."""

    msg: object
    peer_id: str = ""


@dataclasses.dataclass(slots=True)
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int  # RoundStep value


# WAL codec shares the types codec so Vote/Proposal/Block payloads nest.
wal_codec: Codec = ser.codec
wal_codec.register(EndHeightMessage, MsgInfo, TimeoutInfo)


class WALError(Exception):
    pass


class WAL:
    """BaseWAL (wal.go:77): framed records over an autofile Group."""

    def __init__(self, path: str, head_size_limit: int | None = None):
        kwargs = {}
        if head_size_limit is not None:
            kwargs["head_size_limit"] = head_size_limit
        self.group = autofile.Group(path, **kwargs)
        self._mtx = libsync.Mutex("consensus.wal._mtx")
        self._msgs_since_sync = 0
        # slow-disk state: [fsync EWMA ns, degraded flag] — preallocated
        # scalar slots, written under the fsync path's own timing branch
        self._disk = array("d", [0.0, 0.0])
        self._disk_alpha = _disk_ewma_alpha()
        self._disk_threshold_ns = _disk_degraded_ns()
        # Seed a brand-new WAL with #ENDHEIGHT 0 so replay can always find
        # a marker (wal.go OnStart); absence later = corruption.
        if self.group.max_index() < 0 and os.path.getsize(path) == 0:
            self.write_end_height(0)

    # -- write -------------------------------------------------------------

    def write(self, msg) -> None:
        payload = wal_codec.dumps(msg)
        if len(payload) > MAX_MSG_BYTES:
            raise WALError(f"msg of {len(payload)}B exceeds WAL limit")
        frame = _FRAME.pack(zlib.crc32(payload), len(payload)) + payload
        with self._mtx:
            self.group.write(frame)
            self.group.flush()

    def write_sync(self, msg, overlapped: bool = False) -> None:
        """fsync before returning — required before signing own msgs.
        ``overlapped=True`` marks an fsync that runs OFF the FSM critical
        section (the pipelined commit-writer): the flight-recorder row is
        flagged so the budget plane credits it outside the serial span."""
        self.write(msg)
        timed = libtrace.enabled() or libhealth.enabled()
        t0 = time.perf_counter() if timed else 0.0
        libfail.delay_point("wal-fsync")
        with self._mtx:  # cometlint: disable=CLNT009 -- the WAL mutex serializes frame write+fsync (wal.go WriteSync)
            self.group.flush_and_sync()
        if timed:
            dur_ns = int((time.perf_counter() - t0) * 1e9)
            self._note_fsync(dur_ns)
            libhealth.record(
                libhealth.EV_FSYNC, a=dur_ns, b=1 if overlapped else 0
            )
            if libtrace.enabled():
                libtrace.event("wal.fsync", dur_ns=dur_ns)

    def flush_and_sync(self) -> None:
        timed = libtrace.enabled() or libhealth.enabled()
        t0 = time.perf_counter() if timed else 0.0
        libfail.delay_point("wal-fsync")
        with self._mtx:  # cometlint: disable=CLNT009 -- flush_and_sync is the caller-requested fsync point
            self.group.flush_and_sync()
        if timed:
            dur_ns = int((time.perf_counter() - t0) * 1e9)
            self._note_fsync(dur_ns)
            libhealth.record(libhealth.EV_FSYNC, a=dur_ns)
            if libtrace.enabled():
                libtrace.event("wal.fsync", dur_ns=dur_ns)

    # -- slow-disk state (see the module-level notes) -------------------

    def _note_fsync(self, dur_ns: int) -> None:
        """Fold one measured fsync into the EWMA + hysteresis state.
        Lock-free scalar stores; the writers already serialize on the
        WAL mutex for the fsync itself."""
        d = self._disk
        ewma = d[0]
        ewma = dur_ns if ewma == 0.0 else (
            self._disk_alpha * dur_ns + (1.0 - self._disk_alpha) * ewma
        )
        d[0] = ewma
        if d[1] == 0.0:
            if ewma > self._disk_threshold_ns:
                d[1] = 1.0
        elif ewma < 0.5 * self._disk_threshold_ns:
            d[1] = 0.0

    def fsync_ewma_s(self) -> float:
        """Smoothed fsync latency (seconds; 0.0 before any sample)."""
        return self._disk[0] / 1e9

    def disk_degraded(self) -> bool:
        """Whether this WAL's disk is in the degraded (slow) state."""
        return self._disk[1] != 0.0

    def write_end_height(self, height: int, overlapped: bool = False) -> None:
        self.write_sync(EndHeightMessage(height), overlapped=overlapped)
        self.group.check_head_size_limit()

    # -- read --------------------------------------------------------------

    def iter_messages(self):
        """Yield every decodable message in order; stops at the first torn
        or corrupt record (crash tail)."""
        reader = autofile.GroupReader(self.group)
        try:
            while True:
                hdr = reader.read(_FRAME.size)
                if len(hdr) < _FRAME.size:
                    return
                crc, length = _FRAME.unpack(hdr)
                if length > MAX_MSG_BYTES:
                    return
                payload = reader.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    return
                try:
                    yield wal_codec.loads(payload)
                except Exception:
                    return
        finally:
            reader.close()

    def search_for_end_height(self, height: int) -> list | None:
        """Messages AFTER ``EndHeightMessage(height)``, or None if that
        marker never appears (wal.go SearchForEndHeight:232)."""
        found = False
        out: list = []
        for msg in self.iter_messages():
            if isinstance(msg, EndHeightMessage):
                if msg.height == height:
                    found = True
                    out = []
                continue
            if found:
                out.append(msg)
        return out if found else None

    def close(self) -> None:
        self.group.close()


class NopWAL:
    """WAL that drops everything (wal.go nilWAL — used by tools/tests)."""

    def fsync_ewma_s(self) -> float:
        return 0.0

    def disk_degraded(self) -> bool:
        return False

    def write(self, msg) -> None:
        pass

    def write_sync(self, msg, overlapped: bool = False) -> None:
        pass

    def flush_and_sync(self) -> None:
        pass

    def write_end_height(self, height: int, overlapped: bool = False) -> None:
        pass

    def iter_messages(self):
        return iter(())

    def search_for_end_height(self, height: int):
        return None

    def close(self) -> None:
        pass
