"""The Tendermint consensus state machine (reference: consensus/state.go).

Single-writer core: one ``_receive_routine`` thread owns ALL round state
(state.go:750) and consumes a merged queue of peer messages, own messages,
and timeouts. Every message is WAL-logged before processing; own messages
are fsynced so a crash cannot double-sign (state.go:797-805).

Step functions mirror the reference: ``enter_new_round:1018``,
``enter_propose:1105``, ``enter_prevote`` (defaultDoPrevote:1313),
``enter_precommit:1489``, ``enter_commit:1624``, ``try_finalize_commit:1687``,
``finalize_commit:1715``; vote ingest ``try_add_vote:2086``/``add_vote:2137``;
own-vote signing ``sign_vote:2355``/``sign_add_vote:2426``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import queue
import threading

from ..libs import sync as libsync
import time

from ..config import ConsensusConfig
from ..crypto import batch as crypto_batch
from ..libs import health as libhealth
from ..libs import metrics as libmetrics
from ..libs import trace as libtrace
from ..libs.events import EventSwitch
from ..libs.service import BaseService
from ..types import BlockID, PartSet, canonical
from ..types.block import Block
from ..types.event_bus import (
    EventDataCompleteProposal,
    EventDataNewRound,
    EventDataRoundState,
    EventDataVote,
    NopEventBus,
)
from ..types.part_set import PartSetError
from ..types.vote import Proposal, Vote
from ..types.vote_set import ConflictingVoteError, VoteSet
from ..types import serialization as ser
from .height_vote_set import HeightVoteSet
from .messages import BlockPartMessage, ProposalMessage, VoteMessage
from .round_state import RoundState, RoundStep
from .ticker import TimeoutTicker
from .wal import MsgInfo, NopWAL, TimeoutInfo

# evsw event names the reactor listens on (consensus/events.go)
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_VALID_BLOCK = "ValidBlock"
EVENT_VOTE = "Vote"
EVENT_PROPOSAL_BLOCK_PART = "ProposalBlockPart"


class ConsensusError(Exception):
    pass


class FatalConsensusError(ConsensusError):
    """A failure inside the commit chain (save → ApplyBlock → advance).

    The reference PANICS here (state.go finalizeCommit): past +2/3
    precommits the node must either fully apply the block or stop —
    continuing with a half-applied height (block saved, state not)
    operates on inconsistent state. Never absorbed by vote-admission
    error handling; propagates to the receive loop, which fail-stops
    the node.
    """


def commit_to_vote_set(chain_id: str, commit, validators) -> VoteSet:
    """Rebuild the precommit VoteSet a commit came from
    (types/block.go CommitToVoteSet / Commit.ToVoteSet:1088)."""
    vs = VoteSet(
        chain_id, commit.height, commit.round, canonical.PRECOMMIT_TYPE,
        validators,
    )
    from ..types.block import BLOCK_ID_FLAG_ABSENT

    votes = []
    for idx, cs in enumerate(commit.signatures):
        if cs.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            continue
        votes.append(
            Vote(
                msg_type=canonical.PRECOMMIT_TYPE,
                height=commit.height,
                round=commit.round,
                block_id=cs.block_id(commit.block_id),
                timestamp_ns=cs.timestamp_ns,
                validator_address=cs.validator_address,
                validator_index=idx,
                signature=cs.signature,
            )
        )
    oks, errs = vs.add_votes_batch(votes)  # one batched verify (TPU path)
    if not all(oks):
        cause = next((e for e in errs if e is not None), None)
        raise ConsensusError(
            f"failed to reconstruct seen-commit votes: {cause}"
        )
    return vs


def extended_commit_to_vote_set(chain_id: str, ec, validators) -> VoteSet:
    """Rebuild the precommit VoteSet — with vote extensions — from a stored
    ExtendedCommit (types/block.go ToExtendedVoteSet / reference
    votesFromExtendedCommit). Used after restart when extensions are
    enabled so the next proposal's ExtendedCommitInfo isn't empty."""
    vs = VoteSet(
        chain_id, ec.height, ec.round, canonical.PRECOMMIT_TYPE,
        validators, extensions_enabled=True,
    )
    from ..types.block import BLOCK_ID_FLAG_ABSENT

    votes = []
    for idx, es in enumerate(ec.extended_signatures):
        cs = es.commit_sig
        if cs.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            continue
        votes.append(
            Vote(
                msg_type=canonical.PRECOMMIT_TYPE,
                height=ec.height,
                round=ec.round,
                block_id=cs.block_id(ec.block_id),
                timestamp_ns=cs.timestamp_ns,
                validator_address=cs.validator_address,
                validator_index=idx,
                signature=cs.signature,
                extension=es.extension,
                extension_signature=es.extension_signature,
            )
        )
    oks, errs = vs.add_votes_batch(votes)
    if not all(oks):
        cause = next((e for e in errs if e is not None), None)
        raise ConsensusError(
            f"failed to reconstruct extended-commit votes: {cause}"
        )
    return vs


class ConsensusState(BaseService):
    def __init__(
        self,
        config: ConsensusConfig,
        state,  # sm.State
        block_exec,
        block_store,
        tx_notifier=None,  # mempool (TxsAvailable signal)
        evidence_pool=None,
        event_bus=None,
        wal=None,
        options=None,
        clock=None,
    ):
        super().__init__("consensus")
        self.config = config
        self.block_exec = block_exec
        self.block_store = block_store
        self.tx_notifier = tx_notifier
        self.evidence_pool = evidence_pool
        self.event_bus = event_bus if event_bus is not None else NopEventBus()
        # lockfree: handle is swapped only inside the single-threaded startup replay (under the mutex); steady-state it is an immutable reference and the WAL's own group lock serializes writes
        self.wal = wal if wal is not None else NopWAL()
        self.evsw = EventSwitch()

        self.priv_validator = None
        self.priv_validator_pub_key = None

        self.rs = RoundState()
        self.state = None  # sm.State, set by update_to_state
        # guards rs reads from other threads; libs.sync so the deadlock
        # tier (COMETBFT_TPU_DEADLOCK=1) instruments the consensus mutex
        self._mtx = libsync.RLock("consensus.state")

        # Time source. Every wall/monotonic read the FSM makes goes
        # through this seam so the simnet plane (cometbft_tpu/simnet)
        # can substitute its virtual clock — the determinism guarantee
        # ("same (seed, scenario) => same heights/rounds/events") needs
        # round-0 sleeps, timeouts and commit latencies derived from
        # simulated time, not from however long the host took. A ctor
        # parameter (not a post-hoc setattr) because update_to_state —
        # called below — already stamps _height_started from it.
        self._clock = clock if clock is not None else time
        # True when a simnet driver owns this FSM: on_start skips the
        # receive/ticker-forwarder threads and the driver pumps the
        # inbox via process_pending() from its scheduler thread.
        self.sim_driven = False
        # flight-ring origin id (libs/health.register_origin) the
        # receive routine declares for its thread; node/node.py sets it
        # to the node-id prefix so ring rows are node-attributed
        self.health_origin = 0

        # merged inbox: ("peer"|"internal"|"timeout", payload)
        self._queue: queue.Queue = queue.Queue(maxsize=1000)
        self._preverify_warned_types: set[str] = set()
        self.ticker = TimeoutTicker()
        self._n_started = 0
        # lockfree: True only during the single-threaded startup replay, before any routine exists; steady-state constant False
        self.replay_mode = False
        self.do_wal_catchup = True
        self._on_block_committed = []  # test/metrics hooks: f(height)
        # Fail-stop hook for FatalConsensusError (node wires this to a
        # full node stop; None → os._exit, never a silent dead thread).
        self.on_fatal = None
        # Pipelined-heights engine (consensus/pipeline.CommitPipeline):
        # speculative execution + ordered commit-writer + durability
        # barrier. None => the fully serial reference commit chain.
        # lockfree: wired once at node boot before any routine starts; steady-state an immutable reference (the pipeline has its own mutex)
        self.pipeline = None

        # libs/trace spans for the current height/round/step. Manual
        # (begin/end) because the FSM is event-driven — the intervals
        # do not nest lexically. All three are touched only with the
        # state mutex held (FSM thread + init/replay), ended eagerly on
        # each transition; None whenever tracing was off at the last
        # transition.
        self._tr_height = None
        self._tr_round = None
        self._tr_step = None

        # Event-delivery deferral (cometlint CLNT009/CLNT010): while the
        # receive loop is inside its critical section this collects
        # (publish_fn, args) pairs; delivery happens after the mutex is
        # released so subscriber callbacks — the reactor's evsw
        # re-broadcast does peer sends, pubsub touches its own lock —
        # never run while 'consensus.state' is held. None => immediate
        # delivery (replay, init wiring, direct test calls).
        self._pending_events: list | None = None

        # Construction is single-threaded, but update_to_state mutates
        # the same FSM fields the live commit chain does — taking the
        # (reentrant, uncontended) mutex here keeps one machine-checked
        # invariant: every post-construction write to FSM state holds
        # 'consensus.state'. cometlint's guarded-field pass (CLNT011/012)
        # infers guards as the intersection over write sites, so an
        # unlocked wiring-phase write would erase the guard. Event
        # delivery is deferred past the release for the same reason
        # _locked_dispatch defers it: 'consensus.state' must never be
        # held while a subscriber callback runs, and the runtime
        # lock-order sanitizer checks exactly that.
        with self._deferred_events():
            with self._mtx:
                self.update_to_state(state)
                self.reconstruct_last_commit_if_needed(state)

    def add_block_committed_hook(self, fn) -> None:
        self._on_block_committed.append(fn)

    # ------------------------------------------------------------------
    # public surface
    # ------------------------------------------------------------------

    def set_priv_validator(self, pv) -> None:
        with self._mtx:
            self.priv_validator = pv
            if pv is not None:
                self.priv_validator_pub_key = pv.get_pub_key()

    def get_round_state(self) -> RoundState:
        """Shallow snapshot — never the live object (state.go GetRoundState
        returns rs.Copy(); field-by-field mutation would tear readers)."""
        with self._mtx:
            return dataclasses.replace(self.rs)

    def height(self) -> int:
        with self._mtx:
            return self.rs.height

    # -- message entry points (thread-safe) --------------------------------

    def add_vote_from_peer(self, vote: Vote, peer_id: str) -> None:
        self._queue.put(("peer", MsgInfo(VoteMessage(vote), peer_id)))

    def set_proposal_from_peer(self, proposal: Proposal, peer_id: str) -> None:
        self._queue.put(("peer", MsgInfo(ProposalMessage(proposal), peer_id)))

    def add_block_part_from_peer(
        self, height: int, round_: int, part, peer_id: str
    ) -> None:
        self._queue.put(
            ("peer", MsgInfo(BlockPartMessage(height, round_, part), peer_id))
        )

    def _send_internal(self, msg) -> None:
        """Never block the receive thread on its own queue
        (state.go sendInternalMessage's select/default + goroutine)."""
        item = ("internal", MsgInfo(msg, ""))
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            threading.Thread(
                target=self._queue.put, args=(item,), daemon=True
            ).start()

    def handle_txs_available(self) -> None:
        """Mempool signal (state.go:981) — used with create_empty_blocks=False."""
        self._queue.put(("txs_available", None))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def on_start(self) -> None:
        # the flag read holds the mutex for the same reason __init__
        # takes it: the writer (switch_to_consensus, on the blocksync
        # routine) writes it under the mutex, and uniform discipline is
        # what keeps the inferred guard machine-checkable. Replay
        # handlers publish; deferral delivers after release.
        with self._deferred_events():
            # cometlint: disable=CLNT009,CLNT010 -- single-threaded startup: replay I/O and event delivery run before any routine exists to contend for the mutex
            with self._mtx:
                if self.do_wal_catchup and not isinstance(self.wal, NopWAL):
                    self._catchup_replay()
        self.ticker.start()
        if self.sim_driven:
            # the simnet scheduler pumps the inbox (process_pending) and
            # its SimTicker enqueues tocks directly — no threads
            self._schedule_round0()
            return
        threading.Thread(
            target=self._tock_forwarder, name="cs-tock", daemon=True
        ).start()
        # lockfree: start/stop lifecycle handle — written once by the thread that calls start(); on_stop reads it via getattr after the queue handshake
        self._receive_thread = threading.Thread(
            target=self._receive_routine, name="cs-receive", daemon=True
        )
        self._receive_thread.start()
        self._schedule_round0()

    def on_stop(self) -> None:
        if self.ticker.is_running():
            self.ticker.stop()
        self._queue.put(("quit", None))
        # Drain the loop before the WAL can be closed under it. (Skipped
        # when stop() is reached FROM the receive thread — the fail-stop
        # path after FatalConsensusError — joining yourself raises.)
        rt = getattr(self, "_receive_thread", None)
        if rt is not None and rt is not threading.current_thread():
            rt.join(timeout=5)
        # In-flight prestage builds dying mid-device-call at interpreter
        # teardown can abort the process; give each a bounded drain.
        for pt in getattr(self, "_prestage_threads", []):
            pt.join(timeout=2)
        # Drain the commit-writer BEFORE the WAL can be closed under it:
        # pending jobs fsync through self.wal.
        if self.pipeline is not None:
            self.pipeline.stop()
        self.wal.flush_and_sync()
        # close any open trace spans so a stopped node's trace has no
        # dangling intervals
        for attr in ("_tr_step", "_tr_round", "_tr_height"):
            sp = getattr(self, attr, None)
            if sp is not None:
                sp.end()
                setattr(self, attr, None)

    def _tock_forwarder(self) -> None:
        while not self.quit_event().is_set():
            try:
                ti = self.ticker.tock_queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._queue.put(("timeout", ti))

    def _schedule_round0(self) -> None:
        sleep_s = max(
            0.0, (self.rs.start_time_ns - self._clock.time_ns()) / 1e9
        )
        self._schedule_timeout(
            sleep_s, self.rs.height, 0, RoundStep.NEW_HEIGHT
        )

    def _schedule_timeout(
        self, duration_s: float, height: int, round_: int, step: RoundStep
    ) -> None:
        self.ticker.schedule_timeout(
            TimeoutInfo(duration_s, height, round_, int(step))
        )

    def _propose_timeout(self, round_: int) -> float:
        """Propose timeout, widened while OUR disk is degraded: a
        slow-but-alive WAL eats into every propose window this node
        waits out (the proposer's own fsyncs delay its proposal by the
        same amount), so stretching the window by a few smoothed fsyncs
        — capped at one extra base timeout — turns spun rounds into a
        slower-but-committing chain (consensus/wal.py disk_degraded).

        Never widened for a sim-driven FSM: the EWMA measures WALL
        fsync time, and feeding wall measurements into virtual-time
        timeout scheduling would break the simnet's bit-reproducibility
        (the sim injects slow disks at the message plane instead)."""
        base = self.config.propose_timeout(round_)
        wal = self.wal
        if not self.sim_driven and wal is not None and wal.disk_degraded():
            base += min(base, 4.0 * wal.fsync_ewma_s())
        return base

    # ------------------------------------------------------------------
    # the single-writer loop
    # ------------------------------------------------------------------

    # Max items drained per micro-batch window. Bounds the per-launch batch
    # and keeps timeouts responsive; 1024 covers a full prevote round of a
    # 1000-validator set arriving at once.
    _DRAIN_WINDOW = 1024

    def _receive_routine(self) -> None:
        # this thread owns the FSM: every flight-ring row it records
        # (steps, proposals, votes, commits, fsyncs) belongs to the
        # node that built this state — declare it once so in-process
        # multi-node harnesses decode per-node timelines (0 = default)
        libhealth.set_thread_origin(self.health_origin)
        while True:
            items = [self._queue.get()]
            # Micro-batch window (SURVEY §7(d)): drain whatever is ALREADY
            # queued — no waiting, so rounds never stall — and preverify all
            # drained vote signatures in one batched launch. Items are then
            # processed strictly in arrival order through the unchanged
            # per-vote state machine, which hits the signature memo instead
            # of verifying one-by-one.
            try:
                while len(items) < self._DRAIN_WINDOW:
                    items.append(self._queue.get_nowait())
            except queue.Empty:
                pass
            if self._process_batch(items):
                return

    def process_pending(self, max_batches: int = 64) -> int:
        """Drain queued inbox items WITHOUT blocking — the simnet
        driver's pump (one call per scheduler event, on the scheduler
        thread).  Internal messages a batch generates are picked up by
        the next batch in the same call; ``max_batches`` bounds a
        pathological self-feeding loop.  Returns items processed."""
        done = 0
        for _ in range(max_batches):
            items: list = []
            try:
                while len(items) < self._DRAIN_WINDOW:
                    items.append(self._queue.get_nowait())
            except queue.Empty:
                pass
            if not items:
                break
            done += len(items)
            if self._process_batch(items):
                break
        return done

    def _process_batch(self, items: list) -> bool:
        """WAL-log + dispatch one drained batch (the single-writer body
        shared by the receive thread and the simnet pump).  Returns True
        on the quit sentinel."""
        memo = None
        try:
            memo = self._preverify_queued_votes(items)
        except Exception as e:
            # Preverification is an optimization only — votes fall back
            # to per-signature host verification — but a persistent
            # failure here erases the batching win, so surface it once
            # per distinct failure type (a one-shot flag would let a
            # transient relay hiccup permanently mask a later bug).
            if type(e).__name__ not in self._preverify_warned_types:
                self._preverify_warned_types.add(type(e).__name__)
                import traceback

                traceback.print_exc()
        try:
            for kind, payload in items:
                if kind == "quit":
                    return True
                try:
                    if kind == "peer":
                        self.wal.write(payload)
                    elif kind == "internal":
                        self.wal.write_sync(payload)
                    elif kind == "timeout":
                        self.wal.write(payload)
                    self._locked_dispatch(kind, payload)
                except FatalConsensusError as e:
                    # Fail-stop (state.go finalizeCommit panics): the
                    # node must not keep running on a half-applied
                    # height. The on_fatal hook (node wiring) stops
                    # the whole node; without one, kill the process —
                    # a dead consensus thread with a live node would
                    # be the silent wedge this guards against.
                    import traceback

                    traceback.print_exc()
                    if self.on_fatal is not None:
                        self.on_fatal(e)
                        return True
                    os._exit(1)
                except Exception:
                    if self.replay_mode:
                        raise
                    import traceback

                    traceback.print_exc()
        finally:
            if memo:
                # Memo entries are scoped to THIS drain window: votes
                # dropped before reaching signature verification (bad
                # rounds, failed pre-checks) must not let peer-
                # controlled entries accumulate for the height.
                memo.clear()
        return False

    @contextlib.contextmanager
    def _deferred_events(self):
        """Collect _publish deliveries while the body runs; drain them
        only after it exits. Wrapped around every ``with self._mtx:``
        region that can reach a publish, so subscriber callbacks never
        run while 'consensus.state' is held (the runtime lock-order
        sanitizer observes acquisition edges and checks exactly this).
        Nests: an inner region feeds the buffer already live, and only
        the outermost exit — past every mutex release — delivers."""
        if self._pending_events is not None:
            yield
            return
        pending: list = []
        # lockfree: FSM-owner plane — exactly one thread drives the FSM at any moment (init wiring -> on_start replay -> blocksync switch_to_consensus -> receive routine), and ownership hand-offs carry happens-before edges (Thread.start, the start/stop queue handshake), so the buffer is never installed or drained concurrently
        self._pending_events = pending
        try:
            yield
        finally:
            # lockfree: same FSM-owner plane as the install above; the reset runs on the same thread that installed the buffer
            self._pending_events = None
            for fn, args in pending:
                try:
                    fn(*args)
                except Exception:
                    # a dead subscriber must not take down the FSM loop;
                    # the traceback still reaches the logs
                    import traceback

                    traceback.print_exc()

    def _locked_dispatch(self, kind: str, payload) -> None:
        """One FSM step under the state mutex, with event delivery
        deferred to AFTER release.

        Holding 'consensus.state' across subscriber callbacks is exactly
        the blocking-under-lock regime the lock-order pass flags: the
        reactor's evsw listener re-broadcasts round steps to every peer
        (socket sends) and the pubsub bus takes its own mutex. Events
        are *constructed* eagerly at the publish site (the payload is a
        snapshot), only delivery moves out of the critical section, so
        RPC/reactor observers see the same data marginally later —
        ordering among events is preserved.
        """
        with self._deferred_events():
            with self._mtx:
                libsync.lockset_note("ConsensusState.state")
                if kind == "timeout":
                    self._handle_timeout(payload)
                elif kind == "txs_available":
                    self._handle_txs_available()
                else:
                    self._handle_msg(payload)

    def _publish(self, fn, *args) -> None:
        """Route one event through the deferral buffer (or deliver
        immediately outside the receive loop — replay, init, tests)."""
        if self._pending_events is not None:
            self._pending_events.append((fn, args))
        else:
            fn(*args)

    def _preverify_queued_votes(self, items) -> dict | None:
        """One batched signature launch for all drained current-height votes.

        Results land in the HeightVoteSet's signature memo keyed by the
        exact (pubkey, sign bytes, signature) triple; admission later pops
        them. Mirrors vote_set.go:216-231's per-vote verify with the
        device-batched layout of SURVEY §7(d). Never changes consensus
        state — a memo miss just falls back to the per-vote host verify.
        """
        from ..crypto import coalesce as crypto_coalesce

        votes: list[Vote] = []
        for kind, payload in items:
            if kind == "peer" and isinstance(payload.msg, VoteMessage):
                votes.append(payload.msg.vote)
        # A lone drained vote is worth pre-verifying only when a
        # coalescer is routed: the batch verifier then submits it as a
        # coalescer lane that merges with concurrent callers' windows
        # (the whole point of the steady-state path); without one, a
        # single-lane "batch" is just the per-vote host verify done
        # earlier, so skip straight to admission.
        min_lanes = 1 if crypto_coalesce.active() is not None else 2
        if len(votes) < min_lanes:
            return None
        with self._mtx:
            rs = self.rs
            height = rs.height
            val_set = rs.validators
            memo = rs.votes.sig_memo
            chain_id = self.state.chain_id
        triples: list[tuple] = []
        for vote in votes:
            if vote.height != height:
                continue
            val = val_set.get_by_index(vote.validator_index)
            if val is None:
                continue
            triples.append(
                (val.pub_key, vote.sign_bytes(chain_id), vote.signature)
            )
            if (
                rs.votes.extensions_enabled
                and vote.msg_type == canonical.PRECOMMIT_TYPE
                and not vote.block_id.is_nil()
                and vote.extension_signature
            ):
                triples.append(
                    (
                        val.pub_key,
                        vote.extension_sign_bytes(chain_id),
                        vote.extension_signature,
                    )
                )
        if len(triples) < min_lanes:
            return None
        try:
            # Keyed off the SET: a heterogeneous ed25519+sr25519 valset
            # pre-verifies through MixedBatchVerifier (one launch)
            # instead of losing batching to a foreign-key TypeError.
            from ..libs import devledger

            verifier = crypto_batch.create_commit_batch_verifier(val_set)
            for pub_key, sign_bytes, sig in triples:
                verifier.add(pub_key, sign_bytes, sig)
            with devledger.caller_class("consensus-vote"):
                _, bits = verifier.verify()
        except (ValueError, TypeError):
            # no batch backend for some key type (e.g. secp256k1):
            # skip pre-verification — admission falls back to per-vote
            # verify, never crashes the receive loop
            return None
        for (pub_key, sign_bytes, sig), ok in zip(triples, bits):
            memo[(pub_key.bytes(), sign_bytes, sig)] = bool(ok)
        if libtrace.enabled():
            libtrace.event(
                "consensus.preverify",
                height=height,
                lanes=len(triples),
                ok=sum(1 for b in bits if b),
            )
        return memo

    def _handle_msg(self, mi: MsgInfo) -> None:
        msg, peer_id = mi.msg, mi.peer_id
        if isinstance(msg, ProposalMessage):
            try:
                self._set_proposal(msg.proposal)
            except ConsensusError:
                libmetrics.node_metrics().proposals.labels("rejected").inc()
                libhealth.record(
                    libhealth.EV_PROPOSAL,
                    msg.proposal.height, msg.proposal.round, 0,
                )
                raise
        elif isinstance(msg, BlockPartMessage):
            self._add_proposal_block_part(msg, peer_id)
        elif isinstance(msg, VoteMessage):
            self._try_add_vote(msg.vote, peer_id)

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < int(rs.step)
        ):
            return  # stale
        step = RoundStep(ti.step)
        if step == RoundStep.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif step == RoundStep.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif step == RoundStep.PROPOSE:
            self._publish(
                self.event_bus.publish_timeout_propose,
                EventDataRoundState(**rs.event_fields()),
            )
            self._enter_prevote(ti.height, ti.round)
        elif step == RoundStep.PREVOTE_WAIT:
            self._publish(
                self.event_bus.publish_timeout_wait,
                EventDataRoundState(**rs.event_fields()),
            )
            self._enter_precommit(ti.height, ti.round)
        elif step == RoundStep.PRECOMMIT_WAIT:
            self._publish(
                self.event_bus.publish_timeout_wait,
                EventDataRoundState(**rs.event_fields()),
            )
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        elif step == RoundStep.COMMIT:
            # timeout_commit elapsed → next height round 0
            self._enter_new_round(ti.height, 0)

    def _handle_txs_available(self) -> None:
        """state.go:981 handleTxsAvailable — round 0 only."""
        rs = self.rs
        if rs.round != 0:
            return
        if rs.step == RoundStep.NEW_HEIGHT:
            # Still inside the timeout_commit window: arm a NEW_ROUND
            # timeout for when it expires instead of dropping the signal.
            remaining = max(
                0.001, (rs.start_time_ns - self._clock.time_ns()) / 1e9 + 0.001
            )
            self._schedule_timeout(
                remaining, rs.height, 0, RoundStep.NEW_ROUND
            )
        elif rs.step == RoundStep.NEW_ROUND:
            self._enter_propose(rs.height, 0)

    # ------------------------------------------------------------------
    # state transitions
    # ------------------------------------------------------------------

    def update_to_state(self, state) -> None:
        """state.go:593 updateToState — prep RoundState for the next height."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and state is not None:
            if rs.height != state.last_block_height:
                raise ConsensusError(
                    f"updateToState at height {rs.height} but state is at "
                    f"{state.last_block_height}"
                )
        if (
            self.state is not None
            and state.last_block_height <= self.state.last_block_height
        ):
            return  # stale state (blocksync overlap)

        # Extract last_commit from this height's precommits.
        last_commit = None
        if rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if precommits is None or not precommits.has_two_thirds_majority():
                raise ConsensusError("updateToState without +2/3 precommits")
            last_commit = precommits

        height = (
            state.initial_height
            if state.last_block_height == 0
            else state.last_block_height + 1
        )

        rs.height = height
        # flight-recorder anchor for the per-height commit-latency SLI
        self._height_started = self._clock.monotonic()
        if libtrace.enabled():
            for attr in ("_tr_step", "_tr_round", "_tr_height"):
                sp = getattr(self, attr, None)
                if sp is not None:
                    sp.end()
            self._tr_round = self._tr_step = None
            self._tr_height = libtrace.begin("consensus.height",
                                             height=height)
        else:
            # see _set_step: no stale spans across a disabled window
            self._tr_height = self._tr_round = self._tr_step = None
        if rs.commit_time_ns == 0:
            rs.start_time_ns = (
                state.last_block_time_ns
                + int(self.config.commit_timeout() * 1e9)
            )
        else:
            rs.start_time_ns = rs.commit_time_ns + int(
                self.config.commit_timeout() * 1e9
            )
        rs.round = 0
        self._set_step(rs, RoundStep.NEW_HEIGHT)
        rs.validators = state.validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(
            state.chain_id,
            height,
            state.validators,
            extensions_enabled=state.consensus_params.vote_extensions_enabled(
                height
            ),
        )
        rs.commit_round = -1
        rs.last_commit = last_commit
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = state
        self._new_step()

    def reconstruct_last_commit_if_needed(self, state) -> None:
        """After restart: rebuild rs.last_commit (state.go
        reconstructLastCommit). When vote extensions were enabled at the
        last height, reconstruct from the stored ExtendedCommit so the next
        proposal's ExtendedCommitInfo carries the extensions (reference
        votesFromExtendedCommit); otherwise from the plain seen commit."""
        if state.last_block_height == 0 or self.rs.last_commit is not None:
            return
        if self.block_store is None:
            return
        if state.consensus_params.vote_extensions_enabled(
            state.last_block_height
        ):
            ec = self.block_store.load_block_extended_commit(
                state.last_block_height
            )
            if ec is None:
                raise ConsensusError(
                    "vote extensions enabled but no extended commit stored "
                    f"for height {state.last_block_height}"
                )
            self.rs.last_commit = extended_commit_to_vote_set(
                state.chain_id, ec, state.last_validators
            )
            return
        seen = self.block_store.load_seen_commit()
        if seen is None or seen.height != state.last_block_height:
            return
        self.rs.last_commit = commit_to_vote_set(
            state.chain_id, seen, state.last_validators
        )

    def _new_step(self) -> None:
        rs = self.rs
        ev = EventDataRoundState(**rs.event_fields())
        self._publish(self.event_bus.publish_new_round_step, ev)
        # shallow snapshot: delivery is deferred past further FSM
        # mutations of rs, and the reactor must broadcast the step
        # that PUBLISHED the event, not whatever rs ends up at
        self._publish(
            self.evsw.fire_event, EVENT_NEW_ROUND_STEP,
            dataclasses.replace(rs),
        )

    # -- NewRound (state.go:1018) ------------------------------------------

    def _set_step(self, rs, step) -> None:
        """Step transition + per-step timing
        (consensus/metrics.go StepDurationSeconds)."""
        now = self._clock.monotonic()
        started = getattr(self, "_step_started", None)
        if started is not None:
            libmetrics.node_metrics().step_duration.labels(
                rs.step.name
            ).observe(now - started)
        self._step_started = now
        if libtrace.enabled():
            sp = getattr(self, "_tr_step", None)
            if sp is not None:
                sp.end()
            self._tr_step = libtrace.begin(
                "consensus.step",
                parent=getattr(self, "_tr_round", None),
                height=rs.height,
                round=rs.round,
                step=step.name,
            )
        else:
            # tracing turned off mid-run: drop the stale span so a
            # later re-enable doesn't end it with a duration covering
            # the whole disabled window
            self._tr_step = None
        rs.step = step
        # always-on flight recorder: the stall watchdog keys off this
        # transition's timestamp (libs/health; allocation- and lock-free)
        libhealth.record(
            libhealth.EV_STEP, rs.height, rs.round, int(step)
        )

    def _enter_new_round(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT
        ):
            return
        m = libmetrics.node_metrics()
        now_mono = self._clock.monotonic()
        if getattr(self, "_round_started", None) is not None:
            m.round_duration.observe(now_mono - self._round_started)
        self._round_started = now_mono
        m.rounds.set(round_)
        if libtrace.enabled():
            sp = getattr(self, "_tr_round", None)
            if sp is not None:
                sp.end()
            self._tr_round = libtrace.begin(
                "consensus.round",
                parent=getattr(self, "_tr_height", None),
                height=height,
                round=round_,
            )
        else:
            self._tr_round = None  # see _set_step: no stale spans
        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy_increment_proposer_priority(
                round_ - rs.round
            )
        rs.round = round_
        self._set_step(rs, RoundStep.NEW_ROUND)
        rs.validators = validators
        if round_ != 0:
            # round 0 keeps proposal from NEW_HEIGHT reset
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.triggered_timeout_precommit = False
        rs.votes.set_round(round_ + 1)
        # Pre-stage the validator set's expanded-pubkey tables device-side
        # so this round's vote/commit verifies ship only R|S|k (zero
        # builder launches in steady state). Fingerprinted by valset hash:
        # rounds without churn are a dict no-op.
        # Off the FSM thread: on accelerator backends a valset change
        # costs a full builder device round trip, which must not delay
        # publish_new_round. Tables are a pure function of the key and
        # the cache is thread-safe, so a racing verify at worst builds
        # the same tables itself.
        vhash = validators.hash()
        if vhash != getattr(self, "_prestaged_valset", None) and vhash != getattr(
            self, "_prestage_inflight", None
        ):
            # Mark staged only when the warm-up RETURNS (a thread that
            # dies must not permanently skip this valset); the inflight
            # marker stops churn rounds spawning duplicate warm-ups.
            # Both attributes are touched only on the FSM thread except
            # the success store, which is idempotent.
            # lockfree: FSM-thread-only writes plus an idempotent clear from the warm-up thread; a stale read only costs one duplicate (cached) prestage
            self._prestage_inflight = vhash

            def _warm(vs=validators, h=vhash):
                try:
                    crypto_batch.prestage_validators(vs)
                    self._prestaged_valset = h
                finally:
                    # only clear OUR marker: a newer valset's warm-up may
                    # have replaced it while we ran
                    if getattr(self, "_prestage_inflight", None) == h:
                        self._prestage_inflight = None

            threads = [
                t
                for t in getattr(self, "_prestage_threads", [])
                if t.is_alive()
            ]
            t = threading.Thread(
                target=_warm, name="prestage-valset", daemon=True
            )
            t.start()
            threads.append(t)
            self._prestage_threads = threads
        self._publish(
            self.event_bus.publish_new_round,
            EventDataNewRound(
                height=height,
                round=round_,
                step=rs.step.short,
                proposer_address=validators.get_proposer().address,
            )
        )
        wait_for_txs = (
            not self.config.create_empty_blocks and round_ == 0
        )
        if wait_for_txs:
            if self.config.create_empty_blocks_interval_ns > 0:
                self._schedule_timeout(
                    self.config.create_empty_blocks_interval_ns / 1e9,
                    height, round_, RoundStep.NEW_ROUND,
                )
            # else wait for handle_txs_available
        else:
            self._enter_propose(height, round_)

    # -- Propose (state.go:1105) -------------------------------------------

    def _enter_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PROPOSE
        ):
            return
        rs.round = round_
        self._set_step(rs, RoundStep.PROPOSE)
        self._new_step()
        self._schedule_timeout(
            self._propose_timeout(round_), height, round_,
            RoundStep.PROPOSE,
        )
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            # Not a validator — just wait for the proposal.
            if rs.proposal_complete():
                self._enter_prevote(height, round_)
            return
        addr = bytes(self.priv_validator_pub_key.address())
        if not rs.validators.has_address(addr):
            if rs.proposal_complete():
                self._enter_prevote(height, round_)
            return
        if rs.validators.get_proposer().address == addr:
            self._decide_proposal(height, round_)
        if rs.proposal_complete():
            self._enter_prevote(height, round_)

    def _wait_pipeline_durable(self, height: int) -> None:
        """The durability barrier (docs/perf.md "Pipelined heights"):
        block until every height <= ``height`` is fsynced + applied by
        the commit-writer.  The FSM may PROCESS H+1 messages while H's
        durable suffix drains, but it must not SIGN for H+1 (a crash
        would forget votes the network already saw — double-sign risk)
        nor feed the app H+1 proposals before Commit(H) landed.  Called
        holding 'consensus.state' by design — not advancing is the
        point; the writer never takes the FSM mutex, so this cannot
        deadlock, and the wait is bounded (a wedged writer fail-stops
        the node, same as any commit-chain failure)."""
        pipe = self.pipeline
        if (
            pipe is None
            or not pipe.enabled
            or self.replay_mode
            or height <= 0
        ):
            return
        try:
            pipe.wait_durable(height)
        except Exception as e:
            raise FatalConsensusError(
                f"durability barrier failed waiting for height "
                f"{height}: {e!r}"
            ) from e

    def _decide_proposal(self, height: int, round_: int) -> None:
        """state.go:1244 defaultDecideProposal."""
        # barrier: the proposal for H reaps the mempool and builds on
        # state(H-1) — both must reflect a durable H-1
        self._wait_pipeline_durable(height - 1)
        rs = self.rs
        if rs.valid_block is not None:
            block, parts = rs.valid_block, rs.valid_block_parts
        else:
            block = self._create_proposal_block(height)
            if block is None:
                return
            parts = PartSet.from_data(ser.dumps(block))
        block_id = BlockID(block.hash(), parts.header)
        proposal = Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=block_id,
            timestamp_ns=self._clock.time_ns(),
        )
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception:
            # Expected during WAL replay: FilePV refuses to re-sign an
            # already-signed HRS with different data (state.go:1217 logs
            # only outside replay mode).
            return
        self._send_internal(ProposalMessage(proposal))
        for i in range(parts.header.total):
            self._send_internal(
                BlockPartMessage(height, round_, parts.get_part(i))
            )

    def _create_proposal_block(self, height: int) -> Block | None:
        rs = self.rs
        if height == self.state.initial_height:
            last_ext_commit = None
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            last_ext_commit = rs.last_commit.make_extended_commit()
        else:
            return None  # don't have the commit for the last block
        proposer = bytes(self.priv_validator_pub_key.address())
        return self.block_exec.create_proposal_block(
            height, self.state, last_ext_commit, proposer,
            time_ns=self._clock.time_ns(),
        )

    # -- proposal ingest ---------------------------------------------------

    def _set_proposal(self, proposal: Proposal) -> None:
        """state.go setProposal / defaultSetProposal."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round
        ):
            raise ConsensusError("invalid POL round in proposal")
        proposer = rs.validators.get_proposer()
        sign_bytes = proposal.sign_bytes(self.state.chain_id)
        # Routed through the cross-caller coalescer when one is active:
        # the proposal check then shares a device micro-batch with the
        # votes draining around it (identical verdict; clean host
        # fallback inside crypto/coalesce.verify_signature).
        from ..crypto import coalesce as crypto_coalesce
        from ..libs import devledger

        with devledger.caller_class("proposal"):
            sig_ok = crypto_coalesce.verify_signature(
                proposer.pub_key, sign_bytes, proposal.signature
            )
        if not sig_ok:
            raise ConsensusError("invalid proposal signature")
        rs.proposal = proposal
        libmetrics.node_metrics().proposals.labels("accepted").inc()
        libhealth.record(libhealth.EV_PROPOSAL, rs.height, rs.round, 1)
        # tx-lifecycle proposal stamp: ONE per accepted proposal, not
        # per tx — the proposal message does not name its txs, so the
        # per-tx join happens at commit (CListMempool.update), where
        # the committed keys are already derived, against this
        # height's stamp (libs/txtrace.note_proposal docstring)
        from ..libs import txtrace as libtxtrace

        libtxtrace.note_proposal(rs.height, rs.round)
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(
                proposal.block_id.part_set_header
            )

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> None:
        """state.go addProposalBlockPart."""
        rs = self.rs
        if msg.height != rs.height:
            return
        if rs.proposal_block_parts is None:
            return  # no proposal yet; parts are re-gossiped
        try:
            added = rs.proposal_block_parts.add_part(msg.part)
        except PartSetError:
            if peer_id:
                return  # bad peer part; ignore (reactor may punish)
            raise
        if not added:
            return
        self._publish(self.evsw.fire_event, EVENT_PROPOSAL_BLOCK_PART, msg)
        if not rs.proposal_block_parts.is_complete():
            return
        block = ser.loads(rs.proposal_block_parts.assemble())
        rs.proposal_block = block
        self._publish(
            self.event_bus.publish_complete_proposal,
            EventDataCompleteProposal(
                height=rs.height,
                round=rs.round,
                step=rs.step.short,
                block_id=BlockID(block.hash(), rs.proposal_block_parts.header),
            )
        )
        prevotes = rs.votes.prevotes(rs.round)
        maj23 = prevotes.two_thirds_majority() if prevotes else None
        if maj23 is not None and not maj23.is_nil() and rs.valid_round < rs.round:
            if block.hash() == maj23.hash:
                rs.valid_round = rs.round
                rs.valid_block = block
                rs.valid_block_parts = rs.proposal_block_parts
        if rs.step <= RoundStep.PROPOSE and rs.proposal_complete():
            self._enter_prevote(rs.height, rs.round)
        elif rs.step == RoundStep.COMMIT:
            self._try_finalize_commit(rs.height)

    # -- Prevote (state.go:1264,1313) --------------------------------------

    def _enter_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE
        ):
            return
        rs.round = round_
        self._set_step(rs, RoundStep.PREVOTE)
        self._new_step()
        self._do_prevote(height, round_)

    def _do_prevote(self, height: int, round_: int) -> None:
        """defaultDoPrevote (state.go:1313-1452, 0.39 semantics).

        There is no unlocking: a validator locked on a block prevotes nil for
        anything else unless the proposal carries a POL (Proposal.pol_round)
        at or after its locked round — the algorithm's line-28 rule.  The old
        prevote-the-lock shortcut had documented liveness defects.
        """
        rs = self.rs
        if rs.proposal_block is None or rs.proposal is None:
            self._sign_add_vote(canonical.PREVOTE_TYPE, b"", None)
            return
        # barrier: ProcessProposal below consults the app, which must
        # already hold Commit(H-1) — never show it H's proposal while
        # H-1's commit is still draining on the writer
        self._wait_pipeline_durable(height - 1)
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except Exception:
            # Invalid from consensus' perspective → prevote nil.
            self._sign_add_vote(canonical.PREVOTE_TYPE, b"", None)
            return

        def prevote_proposal() -> None:
            # Every prevote-the-block path funnels through here, always
            # AFTER validate_block above — start executing it
            # speculatively so a precommit win finds FinalizeBlock
            # already memoized (consensus/pipeline.py).
            pipe = self.pipeline
            if (
                pipe is not None
                and pipe.spec_enabled
                and not self.replay_mode
            ):
                blk, st, be = rs.proposal_block, self.state, self.block_exec
                try:
                    pipe.submit_speculation(
                        height,
                        blk.hash(),
                        lambda: be.speculate_block(st, blk),
                    )
                except Exception as e:
                    # Only the cs-spec-exec CRASH SEAM escapes an inline
                    # submit (real speculation failures are absorbed
                    # inside the pipeline and degrade to a serial
                    # commit) — treat it like any simulated process
                    # death: fail-stop the node.
                    raise FatalConsensusError(
                        f"crash seam in speculative execution: {e!r}"
                    ) from e
            self._sign_add_vote(
                canonical.PREVOTE_TYPE,
                rs.proposal_block.hash(),
                rs.proposal_block_parts.header,
            )

        if rs.proposal.pol_round == -1:
            # Fresh proposal, never had a +2/3 majority (line 22-26).
            if rs.locked_round == -1:
                if (
                    rs.valid_round != -1
                    and rs.valid_block is not None
                    and rs.proposal_block.hash() == rs.valid_block.hash()
                ):
                    # Matches our valid block: app-validity already attested
                    # by a correct node; no ProcessProposal round trip.
                    prevote_proposal()
                    return
                try:
                    accepted = self.block_exec.process_proposal(
                        rs.proposal_block, self.state
                    )
                except Exception:
                    accepted = False
                if accepted:
                    prevote_proposal()
                else:
                    self._sign_add_vote(canonical.PREVOTE_TYPE, b"", None)
                return
            if rs.proposal_block.hash() == rs.locked_block.hash():
                prevote_proposal()
                return
            self._sign_add_vote(canonical.PREVOTE_TYPE, b"", None)
            return

        # Re-proposal carrying a POL round (line 28-32): prevote it iff a
        # +2/3 prevote majority for this block exists at pol_round and our
        # lock is not more recent (or matches the block). ProcessProposal is
        # intentionally NOT called here — the +2/3 prevotes at pol_round mean
        # at least one correct node already app-validated it
        # (state.go:1413-1431's "we don't need to query the application").
        pol_prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        maj23 = pol_prevotes.two_thirds_majority() if pol_prevotes else None
        if (
            maj23 is not None
            and not maj23.is_nil()
            and rs.proposal_block.hash() == maj23.hash
            and 0 <= rs.proposal.pol_round < rs.round
        ):
            if rs.locked_round <= rs.proposal.pol_round:
                prevote_proposal()
                return
            if rs.proposal_block.hash() == rs.locked_block.hash():
                prevote_proposal()
                return
        self._sign_add_vote(canonical.PREVOTE_TYPE, b"", None)

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise ConsensusError("enterPrevoteWait without any +2/3 prevotes")
        rs.round = round_
        self._set_step(rs, RoundStep.PREVOTE_WAIT)
        self._new_step()
        self._schedule_timeout(
            self.config.prevote_timeout(round_), height, round_,
            RoundStep.PREVOTE_WAIT,
        )

    # -- Precommit (state.go:1489) -----------------------------------------

    def _enter_precommit(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PRECOMMIT
        ):
            return
        rs.round = round_
        self._set_step(rs, RoundStep.PRECOMMIT)
        self._new_step()
        prevotes = rs.votes.prevotes(round_)
        maj23 = prevotes.two_thirds_majority() if prevotes else None

        if maj23 is None:
            # No polka → precommit nil.
            self._sign_add_vote(canonical.PRECOMMIT_TYPE, b"", None)
            return

        self._publish(
            self.event_bus.publish_polka,
            EventDataRoundState(**rs.event_fields()),
        )

        pol_round, _ = rs.votes.pol_info()
        if pol_round < round_:
            raise ConsensusError("POL round inconsistent with +2/3 prevotes")

        if maj23.is_nil():
            # +2/3 prevoted nil → precommit nil.  The lock is NOT cleared:
            # 0.39 removed all unlocking (state.go:1534-1539).
            self._sign_add_vote(canonical.PRECOMMIT_TYPE, b"", None)
            return

        if rs.locked_block is not None and rs.locked_block.hash() == maj23.hash:
            # Relock.
            rs.locked_round = round_
            self._publish(
                self.event_bus.publish_relock,
                EventDataRoundState(**rs.event_fields()),
            )
            self._sign_add_vote(
                canonical.PRECOMMIT_TYPE, maj23.hash, maj23.part_set_header
            )
            return

        if rs.proposal_block is not None and rs.proposal_block.hash() == maj23.hash:
            # Lock the proposal block (validate first — must never lock an
            # invalid block).
            self.block_exec.validate_block(self.state, rs.proposal_block)
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            self._publish(
                self.event_bus.publish_lock,
                EventDataRoundState(**rs.event_fields()),
            )
            self._sign_add_vote(
                canonical.PRECOMMIT_TYPE, maj23.hash, maj23.part_set_header
            )
            return

        # +2/3 prevoted a block we don't have → fetch it and precommit nil,
        # keeping any existing lock (state.go:1580-1589).
        if (
            rs.proposal_block_parts is None
            or rs.proposal_block_parts.header != maj23.part_set_header
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet(maj23.part_set_header)
        self._sign_add_vote(canonical.PRECOMMIT_TYPE, b"", None)

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise ConsensusError("enterPrecommitWait without +2/3 precommits")
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(
            self.config.precommit_timeout(round_), height, round_,
            RoundStep.PRECOMMIT_WAIT,
        )

    # -- Commit (state.go:1624) --------------------------------------------

    def _enter_commit(self, height: int, commit_round: int) -> None:
        rs = self.rs
        if rs.height != height or rs.step >= RoundStep.COMMIT:
            return
        precommits = rs.votes.precommits(commit_round)
        maj23 = precommits.two_thirds_majority()
        if maj23 is None or maj23.is_nil():
            raise ConsensusError("enterCommit without +2/3 for a block")
        self._set_step(rs, RoundStep.COMMIT)
        rs.commit_round = commit_round
        rs.commit_time_ns = self._clock.time_ns()
        self._new_step()

        if rs.locked_block is not None and rs.locked_block.hash() == maj23.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or rs.proposal_block.hash() != maj23.hash:
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet(maj23.part_set_header)
            self._publish(
                self.evsw.fire_event, EVENT_VALID_BLOCK,
                dataclasses.replace(rs),
            )
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        maj23 = precommits.two_thirds_majority() if precommits else None
        if maj23 is None or maj23.is_nil():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != maj23.hash:
            return  # still waiting for block parts
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """state.go:1715 — save, apply, advance."""
        rs = self.rs
        if rs.height != height or rs.step != RoundStep.COMMIT:
            return
        try:
            self._finalize_commit_locked(height)
        except FatalConsensusError:
            raise
        except Exception as e:
            raise FatalConsensusError(
                f"failure finalizing height {height}: {e!r}"
            ) from e

    def _finalize_commit_locked(self, height: int) -> None:
        rs = self.rs
        precommits = rs.votes.precommits(rs.commit_round)
        block_id = precommits.two_thirds_majority()
        block, parts = rs.proposal_block, rs.proposal_block_parts
        block.validate_basic()

        from ..libs.fail import fail_point

        # Claim the speculative FinalizeBlock if we executed this exact
        # block at prevote time (records hit/miss/abort either way). A
        # hit skips re-validation: speculation is only ever submitted
        # from _do_prevote AFTER validate_block passed on this block.
        pipe = self.pipeline
        spec = None
        if pipe is not None and not self.replay_mode:
            spec = pipe.consume_speculation(
                height, rs.commit_round, block.hash()
            )
        if spec is None:
            self.block_exec.validate_block(self.state, block)

        pipelined = (
            pipe is not None and pipe.enabled and not self.replay_mode
        )
        if pipelined:
            # Pipelined commit (docs/perf.md "Pipelined heights"): the
            # FSM runs only the in-memory half — FinalizeBlock (or the
            # memoized speculation) and the State(H+1) derivation — and
            # hands the ENTIRE durable suffix to the ordered
            # commit-writer in the exact serial order, so every crash
            # window maps onto the reference recovery matrix and the
            # app is never durably ahead of the block store
            # (consensus/replay.py's handshake invariant).  The FSM
            # then advances to H+1 immediately; _wait_pipeline_durable
            # fences signing until this job completes.  WAL note: H+1
            # peer/timeout records may land BEFORE the worker's
            # EndHeight(H) marker and so are invisible to replay —
            # harmless, they are re-gossiped/re-armed; own messages
            # cannot, because signing waits on the barrier.
            spec_resp, spec_post = spec if spec is not None else (None, None)
            new_state, resp = self.block_exec.begin_apply(
                self.state, block_id, block, spec_resp=spec_resp
            )
            extended = self.state.consensus_params.vote_extensions_enabled(
                height
            )
            seen_commit = None if extended else precommits.make_commit()
            ext_commit = (
                precommits.make_extended_commit(True) if extended else None
            )
            store, wal, block_exec = self.block_store, self.wal, self.block_exec

            def _durable_suffix():
                fail_point("cs-pipeline-save")
                fail_point("cs-before-save-block")
                if store.height() < block.header.height:
                    if ext_commit is not None:
                        store.save_block_with_extended_commit(
                            block, parts, ext_commit
                        )
                    else:
                        store.save_block(block, parts, seen_commit)
                fail_point("cs-after-save-block")
                # crash window between the durable block and its fsynced
                # EndHeight marker — recovered by the handshake replay
                # of the stored-but-unapplied tip
                fail_point("cs-pipeline-fsync")
                wal.write_end_height(height, overlapped=True)
                fail_point("cs-after-end-height")
                block_exec.complete_apply(
                    new_state, block_id, block, resp, spec_token=spec_post
                )
                fail_point("cs-after-apply-block")

            pipe.enqueue_commit(height, _durable_suffix)
            # warm H+1's device windows while the suffix drains
            pipe.prestage_next(new_state.validators)
        else:
            fail_point("cs-before-save-block")
            if self.block_store.height() < block.header.height:
                seen_commit = precommits.make_commit()
                if self.state.consensus_params.vote_extensions_enabled(height):
                    self.block_store.save_block_with_extended_commit(
                        block, parts, precommits.make_extended_commit(True)
                    )
                else:
                    self.block_store.save_block(block, parts, seen_commit)

            fail_point("cs-after-save-block")
            # EndHeight AFTER the block is saved, BEFORE ApplyBlock: a crash
            # in between recovers via the ABCI handshake replay, not the WAL
            # (state.go:1753-1820 fail points).
            self.wal.write_end_height(height)
            fail_point("cs-after-end-height")

            if spec is None:
                new_state = self.block_exec.apply_block(
                    self.state, block_id, block
                )
            else:
                # serial durable order, speculative execution result:
                # same chain, minus the redundant FinalizeBlock
                spec_resp, spec_post = spec
                t0 = time.perf_counter()
                new_state, resp = self.block_exec.begin_apply(
                    self.state, block_id, block, spec_resp=spec_resp
                )
                self.block_exec.complete_apply(
                    new_state, block_id, block, resp,
                    spec_token=spec_post, t0=t0,
                )
            fail_point("cs-after-apply-block")
            if pipe is not None:
                # a serially-committed height (WAL catchup replay, the
                # pipeline knob off) is durable HERE — advance the mark
                # so the barrier, the prune gate and the lag gauge
                # never wait on a debt the writer was never handed
                pipe.note_base(height)

        # per-height commit latency into the flight recorder (the
        # health engine's commit SLI; commit_round+1 = rounds needed;
        # b = tx count, so timelines and SLIs can correlate commit
        # latency with block fullness)
        libhealth.record(
            libhealth.EV_COMMIT, height, rs.commit_round,
            int(
                (
                    self._clock.monotonic()
                    - getattr(
                        self, "_height_started", self._clock.monotonic()
                    )
                ) * 1e9
            ),
            len(block.data.txs),
        )

        for hook in self._on_block_committed:
            hook(height)

        # Next height.
        rs.commit_time_ns = self._clock.time_ns()
        self.update_to_state(new_state)
        self._schedule_round0()

    # ------------------------------------------------------------------
    # votes
    # ------------------------------------------------------------------

    def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """state.go:2086."""
        try:
            return self._add_vote(vote, peer_id)
        except ConflictingVoteError as e:
            libmetrics.node_metrics().duplicate_votes.inc()
            if (
                self.priv_validator_pub_key is not None
                and vote.validator_address
                == bytes(self.priv_validator_pub_key.address())
            ):
                return False  # our own double-sign?! do not gossip evidence
            if self.evidence_pool is not None:
                self.evidence_pool.report_conflicting_votes(e.new, e.existing)
            return False
        except FatalConsensusError:
            # Commit-chain failure triggered by this vote (enterCommit →
            # finalize → ApplyBlock): NOT a vote-admission error — the
            # node may hold a half-applied block. Propagate; the receive
            # loop fail-stops (reference panics in finalizeCommit).
            raise
        except Exception:
            if self.replay_mode:
                raise
            # NOT silent: peer votes may legitimately fail validation, but
            # the traceback must reach the logs.
            import traceback

            if self.logger is not None:
                self.logger.error(
                    "exception adding vote",
                    height=vote.height,
                    round=vote.round,
                    peer=peer_id,
                )
            traceback.print_exc()
            return False

    def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        """state.go:2137."""
        rs = self.rs

        # Late precommit for the previous height completes rs.last_commit.
        if (
            vote.height + 1 == rs.height
            and vote.msg_type == canonical.PRECOMMIT_TYPE
        ):
            if rs.step != RoundStep.NEW_HEIGHT or rs.last_commit is None:
                return False
            if not rs.last_commit.add_vote(vote):
                return False
            if libtrace.enabled():
                libtrace.event(
                    "consensus.vote",
                    height=vote.height,
                    round=vote.round,
                    type="precommit-late",
                    index=vote.validator_index,
                    peer=peer_id,
                )
            self._publish(self.event_bus.publish_vote, EventDataVote(vote))
            self._publish(self.evsw.fire_event, EVENT_VOTE, vote)
            if self.config.skip_timeout_commit and rs.last_commit.has_all():
                self._enter_new_round(rs.height, 0)
            return True

        if vote.height != rs.height:
            if vote.height < rs.height:
                libmetrics.node_metrics().late_votes.labels(
                    "precommit"
                    if vote.msg_type == canonical.PRECOMMIT_TYPE
                    else "prevote"
                ).inc()
            return False

        extensions_enabled = rs.votes.extensions_enabled
        if (
            extensions_enabled
            and vote.msg_type == canonical.PRECOMMIT_TYPE
            and not vote.block_id.is_nil()
            and (
                self.priv_validator_pub_key is None
                or vote.validator_address
                != bytes(self.priv_validator_pub_key.address())
            )
        ):
            # App-level extension check (sig checked in VoteSet).
            val = rs.validators.get_by_index(vote.validator_index)
            if val is None:
                return False
            vote.verify_extension(self.state.chain_id, val.pub_key)
            if not self.block_exec.verify_vote_extension(vote, self.state):
                raise ConsensusError("rejected vote extension")

        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        libhealth.record(
            libhealth.EV_VOTE, vote.height, vote.round,
            vote.msg_type, vote.validator_index,
        )
        if libtrace.enabled():
            libtrace.event(
                "consensus.vote",
                height=vote.height,
                round=vote.round,
                type=(
                    "precommit"
                    if vote.msg_type == canonical.PRECOMMIT_TYPE
                    else "prevote"
                ),
                index=vote.validator_index,
                peer=peer_id,
            )
        self._publish(self.event_bus.publish_vote, EventDataVote(vote))
        self._publish(self.evsw.fire_event, EVENT_VOTE, vote)

        if vote.msg_type == canonical.PREVOTE_TYPE:
            self._on_prevote_added(vote)
        else:
            self._on_precommit_added(vote)
        return True

    def _on_prevote_added(self, vote: Vote) -> None:
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round)
        maj23 = prevotes.two_thirds_majority()
        if maj23 is not None:
            # Track the latest valid block.  No unlocking here — 0.39
            # removed the unlock-on-later-polka rule (state.go:2260-2296).
            if (
                not maj23.is_nil()
                and rs.valid_round < vote.round == rs.round
            ):
                if (
                    rs.proposal_block is not None
                    and rs.proposal_block.hash() == maj23.hash
                ):
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    # We're getting the wrong block.
                    rs.proposal_block = None
                if (
                    rs.proposal_block_parts is None
                    or rs.proposal_block_parts.header != maj23.part_set_header
                ):
                    rs.proposal_block_parts = PartSet(maj23.part_set_header)
                self._publish(
                    self.evsw.fire_event, EVENT_VALID_BLOCK,
                    dataclasses.replace(rs),
                )

        if rs.round < vote.round and prevotes.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
        elif rs.round == vote.round and rs.step >= RoundStep.PREVOTE:
            if maj23 is not None and (
                rs.proposal_complete() or maj23.is_nil()
            ):
                self._enter_precommit(rs.height, vote.round)
            elif prevotes.has_two_thirds_any():
                self._enter_prevote_wait(rs.height, vote.round)
        elif (
            rs.proposal is not None
            and 0 <= rs.proposal.pol_round == vote.round
        ):
            if rs.proposal_complete():
                self._enter_prevote(rs.height, rs.round)

    def _on_precommit_added(self, vote: Vote) -> None:
        rs = self.rs
        precommits = rs.votes.precommits(vote.round)
        maj23 = precommits.two_thirds_majority()
        if maj23 is not None:
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit(rs.height, vote.round)
            if not maj23.is_nil():
                self._enter_commit(rs.height, vote.round)
                if self.config.skip_timeout_commit and precommits.has_all():
                    self._enter_new_round(rs.height, 0)
            else:
                self._enter_precommit_wait(rs.height, vote.round)
        elif rs.round <= vote.round and precommits.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit_wait(rs.height, vote.round)

    # -- own votes ---------------------------------------------------------

    def _sign_vote(
        self, msg_type: int, block_hash: bytes, part_set_header
    ) -> Vote | None:
        """state.go:2355 signVote."""
        rs = self.rs
        # barrier (defense in depth — _decide_proposal and _do_prevote
        # already fence): NO vote for H leaves this node until H-1 is
        # durable, so a crash can never forget a signature the network
        # already counted (the WAL double-sign guarantee, preserved
        # across the pipelined commit chain).  This is also what keeps
        # WAL replay sound: own H messages are always logged after the
        # worker's EndHeight(H-1) marker.
        self._wait_pipeline_durable(rs.height - 1)
        addr = bytes(self.priv_validator_pub_key.address())
        idx, val = rs.validators.get_by_address(addr)
        if val is None:
            return None
        block_id = (
            BlockID(block_hash, part_set_header) if block_hash else BlockID()
        )
        vote = Vote(
            msg_type=msg_type,
            height=rs.height,
            round=rs.round,
            block_id=block_id,
            timestamp_ns=self._clock.time_ns(),
            validator_address=addr,
            validator_index=idx,
        )
        extensions_enabled = rs.votes.extensions_enabled
        if (
            extensions_enabled
            and msg_type == canonical.PRECOMMIT_TYPE
            and not block_id.is_nil()
        ):
            vote.extension = self.block_exec.extend_vote(vote, self.state)
        self.priv_validator.sign_vote(
            self.state.chain_id, vote,
            sign_extension=extensions_enabled,
        )
        return vote

    def _sign_add_vote(
        self, msg_type: int, block_hash: bytes, part_set_header
    ) -> None:
        """state.go:2426 signAddVote."""
        rs = self.rs
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            return
        if not rs.validators.has_address(
            bytes(self.priv_validator_pub_key.address())
        ):
            return
        try:
            vote = self._sign_vote(msg_type, block_hash, part_set_header)
        except FatalConsensusError:
            raise  # durability-barrier failure: fail-stop, never absorbed
        except Exception:
            # FilePV double-sign refusal — silent in replay, where the WAL
            # already carries the originally-signed vote (state.go:2426+).
            return
        if vote is not None:
            self._send_internal(VoteMessage(vote))

    # ------------------------------------------------------------------
    # WAL crash recovery (replay.go catchupReplay:94)
    # ------------------------------------------------------------------

    def _catchup_replay(self) -> None:
        height = self.rs.height
        msgs = self.wal.search_for_end_height(height - 1)
        if msgs is None:
            # A crash between save_block(h) and write_end_height(h) leaves
            # the WAL one marker BEHIND the store; the handshake already
            # replayed the block into the app, so everything after the
            # last marker concerns committed heights and is safely stale
            # (the state.go:1753-1820 crash matrix, cs-after-save-block
            # case). Only a WAL with no markers at all — it is seeded
            # with EndHeight(0) at creation — signals real corruption:
            # refusing to sign blindly is the whole point of the WAL
            # (replay.go:94). ONE scan finds the newest stale marker.
            from .wal import EndHeightMessage

            has_stale_marker = False
            for msg in self.wal.iter_messages():
                if (
                    isinstance(msg, EndHeightMessage)
                    and msg.height <= height - 1
                ):
                    has_stale_marker = True
            if has_stale_marker:
                msgs = []  # tail is pre-handshake noise, nothing to replay
        if msgs is None:
            raise ConsensusError(
                f"WAL has no #ENDHEIGHT marker at or below height "
                f"{height - 1}; refusing to start (possible WAL corruption)"
            )
        # Replay drives the live FSM handlers under the state mutex,
        # same as _locked_dispatch: on_start runs before the receive
        # routine spawns, so the lock is uncontended, and holding it
        # keeps the guarded-field invariant (every FSM write holds
        # 'consensus.state') uniform across replay and live operation.
        # The blocking/publish work reachable from the handlers is the
        # startup path of the same single-writer chain the baseline
        # documents for the live commit.
        # cometlint: disable=CLNT009,CLNT010 -- single-threaded startup replay; no routine exists to contend, and on_start's deferral buffer holds replay events until the mutex is released
        with self._mtx:
            self.replay_mode = True
            live_wal, self.wal = self.wal, NopWAL()
            try:
                for msg in msgs:
                    if isinstance(msg, MsgInfo):
                        self._handle_msg(msg)
                    elif isinstance(msg, TimeoutInfo):
                        self._handle_timeout(msg)
            finally:
                self.wal = live_wal
                self.replay_mode = False
