"""Consensus wire/internal messages (reference: consensus/msgs.go,
consensus/reactor.go message types). Used on the in-process queues, the
WAL, and (later) the p2p DataChannel/VoteChannel payloads.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs.bits import BitArray
from ..types import BlockID
from ..types.part_set import Part
from ..types.vote import Proposal, Vote
from ..types import serialization as ser


@dataclass(slots=True)
class ProposalMessage:
    proposal: Proposal


@dataclass(slots=True)
class BlockPartMessage:
    height: int
    round: int
    part: Part


@dataclass(slots=True)
class VoteMessage:
    vote: Vote


@dataclass(slots=True)
class NewRoundStepMessage:
    height: int
    round: int
    step: int
    seconds_since_start_time: int = 0
    last_commit_round: int = -1


@dataclass(slots=True)
class NewValidBlockMessage:
    height: int
    round: int
    block_part_set_header: object = None
    block_parts: BitArray | None = None
    is_commit: bool = False


@dataclass(slots=True)
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: BitArray | None = None


@dataclass(slots=True)
class HasVoteMessage:
    height: int
    round: int
    msg_type: int
    index: int


@dataclass(slots=True)
class VoteSetMaj23Message:
    height: int
    round: int
    msg_type: int
    block_id: BlockID = field(default_factory=BlockID)


@dataclass(slots=True)
class VoteSetBitsMessage:
    height: int
    round: int
    msg_type: int
    block_id: BlockID = field(default_factory=BlockID)
    votes: BitArray | None = None


ser.codec.register(
    ProposalMessage,
    BlockPartMessage,
    VoteMessage,
    NewRoundStepMessage,
    NewValidBlockMessage,
    ProposalPOLMessage,
    HasVoteMessage,
    VoteSetMaj23Message,
    VoteSetBitsMessage,
)

# BitArray is a plain class; adapt it for the codec.
ser.codec.register_adapter(
    BitArray,
    "bits",
    lambda ba: {"bits": ba.size(), "elems": ba.to_bytes().hex()},
    lambda d: BitArray.from_bytes(d["bits"], bytes.fromhex(d["elems"])),
)
