"""L6 consensus engine (reference: consensus/)."""

from .round_state import RoundState, RoundStep  # noqa: F401
from .height_vote_set import HeightVoteSet  # noqa: F401
from .ticker import TimeoutInfo, TimeoutTicker  # noqa: F401
from .wal import WAL, EndHeightMessage, NopWAL  # noqa: F401
from .state import ConsensusState  # noqa: F401
