"""Round → {prevotes, precommits} vote tracking for one height
(reference: consensus/types/height_vote_set.go:286).

Tracks every round's VoteSets, bounds peer-initiated round creation via
peer-claimed 2/3 majorities (one catchup round per peer), and surfaces
POL (proof-of-lock) queries.
"""

from __future__ import annotations

from ..libs import sync as libsync

from ..types import canonical
from ..types.validator_set import ValidatorSet
from ..types.vote import Vote
from ..types.vote_set import VoteSet

MAX_CATCHUP_ROUNDS = 2


class HeightVoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        validators: ValidatorSet,
        extensions_enabled: bool = False,
    ):
        self.chain_id = chain_id
        self.height = height
        self.val_set = validators
        self.extensions_enabled = extensions_enabled
        self._mtx = libsync.RLock("consensus.height_vote_set._mtx")
        self._round = 0
        self._round_vote_sets: dict[int, tuple[VoteSet, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        # Shared across every VoteSet of this height: the consensus receive
        # loop batch-preverifies drained vote signatures into this memo so
        # per-vote admission skips the per-signature check (SURVEY §7(d)).
        self.sig_memo: dict = {}
        # uncontended here, but every post-construction write to
        # _round_vote_sets holds this lock — taking it for the round-0
        # seed keeps the inferred guard (cometlint CLNT011) exact
        with self._mtx:
            self._add_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        prevotes = VoteSet(
            self.chain_id, self.height, round_,
            canonical.PREVOTE_TYPE, self.val_set,
            sig_memo=self.sig_memo,
        )
        precommits = VoteSet(
            self.chain_id, self.height, round_,
            canonical.PRECOMMIT_TYPE, self.val_set,
            extensions_enabled=self.extensions_enabled,
            sig_memo=self.sig_memo,
        )
        self._round_vote_sets[round_] = (prevotes, precommits)
        libsync.lockset_note("HeightVoteSet._round_vote_sets")

    def set_round(self, round_: int) -> None:
        """Ensure vote sets exist through round_+1 (height_vote_set.go:104)."""
        with self._mtx:
            new_round = self._round
            for r in range(self._round, round_ + 2):
                self._add_round(r)
            self._round = max(new_round, round_)

    def round(self) -> int:
        with self._mtx:
            return self._round

    # -- vote ingest -------------------------------------------------------

    def add_vote(self, vote: Vote, peer_id: str = "") -> bool:
        """AddVote (height_vote_set.go:126): unknown rounds are only
        admitted for peers that claimed a 2/3 majority there (bounded)."""
        with self._mtx:
            if not canonical.is_vote_type(vote.msg_type):
                raise ValueError(f"not a vote type: {vote.msg_type}")
            vs = self._get_locked(vote.round, vote.msg_type)
            if vs is None:
                rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
                if len(rounds) < MAX_CATCHUP_ROUNDS:
                    self._add_round(vote.round)
                    vs = self._get_locked(vote.round, vote.msg_type)
                    rounds.append(vote.round)
                else:
                    # Punishable spam: peer opens too many rounds.
                    raise GotVoteFromUnwantedRoundError(
                        f"peer {peer_id} round {vote.round}"
                    )
            return vs.add_vote(vote)

    # -- queries -----------------------------------------------------------

    def _get_locked(self, round_: int, msg_type: int) -> VoteSet | None:
        pair = self._round_vote_sets.get(round_)
        if pair is None:
            return None
        return pair[0] if msg_type == canonical.PREVOTE_TYPE else pair[1]

    def prevotes(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_locked(round_, canonical.PREVOTE_TYPE)

    def precommits(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_locked(round_, canonical.PRECOMMIT_TYPE)

    def pol_info(self) -> tuple[int, object]:
        """Highest round with a prevote 2/3 majority (POLRound, POLBlockID)."""
        with self._mtx:
            for r in range(self._round, -1, -1):
                vs = self._get_locked(r, canonical.PREVOTE_TYPE)
                if vs is not None:
                    maj = vs.two_thirds_majority()
                    if maj is not None:
                        return r, maj
            return -1, None

    def set_peer_maj23(
        self, round_: int, msg_type: int, peer_id: str, block_id
    ) -> None:
        """Only existing rounds — claimed majorities must not let a peer
        allocate arbitrary rounds (height_vote_set.go SetPeerMaj23)."""
        with self._mtx:
            vs = self._get_locked(round_, msg_type)
            if vs is not None:
                vs.set_peer_maj23(peer_id, block_id)


class GotVoteFromUnwantedRoundError(Exception):
    pass
