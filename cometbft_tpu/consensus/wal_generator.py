"""WAL fixture generator (reference: consensus/wal_generator.go:31).

Runs a REAL single-validator node against the in-process kvstore app
until ``num_blocks`` are committed, then hands back the node's consensus
WAL — authentic fixture content (proposals, block parts, votes,
timeouts, end-height markers in true order) for replay/corruption tests,
instead of hand-assembled message sequences.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time

_MS = 1_000_000


def generate_wal(
    out_path: str, num_blocks: int = 3, timeout_s: float = 60.0
) -> str:
    """Produce a WAL covering >= ``num_blocks`` committed heights.

    Returns ``out_path`` (the WAL head file; rotated tail files, if any,
    are copied alongside). The node runs in a throwaway home with
    mem-backed stores except the WAL itself.
    """
    from ..config import default_config
    from ..node import Node, init_files, load_genesis

    home = tempfile.mkdtemp(prefix="walgen-")
    try:
        cfg = default_config()
        cfg.base.home = home
        cfg.base.db_backend = "mem"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""  # no RPC needed for fixture generation
        cfg.consensus = dataclasses.replace(
            cfg.consensus,
            timeout_propose_ns=400 * _MS,
            timeout_prevote_ns=200 * _MS,
            timeout_precommit_ns=200 * _MS,
            timeout_commit_ns=100 * _MS,
            skip_timeout_commit=False,
            create_empty_blocks=True,
        )
        init_files(cfg)
        from ..privval import FilePV

        pv = FilePV.load_or_generate(
            cfg.base.resolve(cfg.base.priv_validator_key_file),
            cfg.base.resolve(cfg.base.priv_validator_state_file),
        )
        node = Node(cfg, load_genesis(cfg), pv)
        node.start()
        try:
            deadline = time.monotonic() + timeout_s
            while (
                node.block_store.height() < num_blocks
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            if node.block_store.height() < num_blocks:
                raise RuntimeError(
                    f"wal generator made only {node.block_store.height()} "
                    f"of {num_blocks} blocks in {timeout_s}s"
                )
        finally:
            node.stop()

        wal_dir = os.path.dirname(
            cfg.base.resolve(cfg.consensus.wal_file)
        )
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        head = cfg.base.resolve(cfg.consensus.wal_file)
        shutil.copy(head, out_path)
        # Rotated tails travel with the head, RENAMED to out_path's
        # basename: autofile.Group discovers tails by the head's own
        # basename prefix, so copying them under the source name would
        # silently orphan them whenever out_path is named differently.
        src_base = os.path.basename(head)
        dst_base = os.path.basename(out_path)
        dst_dir = os.path.dirname(out_path) or "."
        for name in sorted(os.listdir(wal_dir)):
            src = os.path.join(wal_dir, name)
            if src != head and name.startswith(src_base):
                suffix = name[len(src_base):]
                shutil.copy(src, os.path.join(dst_dir, dst_base + suffix))
        return out_path
    finally:
        shutil.rmtree(home, ignore_errors=True)
