"""Per-height consensus round state (reference:
consensus/types/round_state.go:224).

``RoundStep`` is the 8-step enum; ``RoundState`` is ALL mutable state the
single-writer consensus loop owns for the current height.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from ..types.block import Block, Commit
from ..types.part_set import PartSet
from ..types.validator_set import ValidatorSet
from ..types.vote import Proposal


class RoundStep(enum.IntEnum):
    NEW_HEIGHT = 1  # wait til commit_time + timeout_commit
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8

    @property
    def short(self) -> str:
        return {
            1: "NewHeight", 2: "NewRound", 3: "Propose", 4: "Prevote",
            5: "PrevoteWait", 6: "Precommit", 7: "PrecommitWait", 8: "Commit",
        }[int(self)]


@dataclass(slots=True)
class RoundState:
    height: int = 0
    round: int = 0
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0

    validators: ValidatorSet | None = None

    proposal: Proposal | None = None
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None

    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None

    # Last known block with a POL (+2/3 prevotes); gossiped for catch-up.
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None

    votes: object | None = None  # HeightVoteSet
    commit_round: int = -1
    last_commit: object | None = None  # precommit VoteSet of height-1
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def proposal_complete(self) -> bool:
        return (
            self.proposal is not None
            and self.proposal_block is not None
        )

    def step_name(self) -> str:
        return self.step.short

    def event_fields(self) -> dict:
        return {
            "height": self.height,
            "round": self.round,
            "step": self.step.short,
        }
