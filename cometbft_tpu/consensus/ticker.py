"""Timeout scheduling (reference: consensus/ticker.go:17-47).

One pending timeout at a time; scheduling a new one for a later (H,R,S)
replaces the old (timeoutRoutine's stopTimer semantics). Fired timeouts
land on ``tock_queue`` for the consensus loop.
"""

from __future__ import annotations

import queue
import threading

from ..libs.service import BaseService
from .wal import TimeoutInfo


class TimeoutTicker(BaseService):
    def __init__(self):
        super().__init__("timeout-ticker")
        self.tock_queue: queue.Queue[TimeoutInfo] = queue.Queue()
        self._tick_queue: queue.Queue[TimeoutInfo | None] = queue.Queue()
        self._thread: threading.Thread | None = None

    def on_start(self) -> None:
        self._thread = threading.Thread(
            target=self._timeout_routine, name="timeout-ticker", daemon=True
        )
        self._thread.start()

    def on_stop(self) -> None:
        self._tick_queue.put(None)  # cometlint: disable=CLNT009 -- unbounded queue: put cannot block

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        self._tick_queue.put(ti)  # cometlint: disable=CLNT009 -- unbounded queue: put cannot block

    def _timeout_routine(self) -> None:
        pending: TimeoutInfo | None = None
        deadline: float | None = None
        import time as _time

        while True:
            timeout = None
            if deadline is not None:
                timeout = max(0.0, deadline - _time.monotonic())
            try:
                ti = self._tick_queue.get(timeout=timeout)
            except queue.Empty:
                # deadline reached → fire
                if pending is not None:
                    self.tock_queue.put(pending)  # cometlint: disable=CLNT009 -- unbounded queue: put cannot block
                pending, deadline = None, None
                continue
            if ti is None:
                return
            # Newer (H,R,S) replaces pending (ticker.go:95 — must be later)
            if pending is not None and (
                ti.height, ti.round, ti.step
            ) < (pending.height, pending.round, pending.step):
                continue
            pending = ti
            deadline = _time.monotonic() + ti.duration_s
