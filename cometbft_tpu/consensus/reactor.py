"""Consensus gossip reactor (reference: consensus/reactor.go).

Channels (reactor.go:27-30): State ``0x20`` (round steps, has-vote,
maj23 claims), Data ``0x21`` (proposals + block parts), Vote ``0x22``,
VoteSetBits ``0x23``. Per peer: a ``PeerState`` mirror of the remote
round state and two gossip threads (data + votes) plus a maj23 query
thread (reactor.go:563,731,886). Consensus-state events (via its evsw)
are re-broadcast to all peers.
"""

from __future__ import annotations

import random
import threading
from ..libs import sync as libsync
import time

from ..libs import log as _log
from ..libs import netstats as libnetstats
from ..libs.bits import BitArray
from ..p2p.base_reactor import ChannelDescriptor, Reactor
from ..types import canonical
from ..types import serialization as ser
from .messages import (
    BlockPartMessage,
    HasVoteMessage,
    NewRoundStepMessage,
    NewValidBlockMessage,
    ProposalMessage,
    ProposalPOLMessage,
    VoteMessage,
    VoteSetBitsMessage,
    VoteSetMaj23Message,
)
from .round_state import RoundStep
from .state import (
    EVENT_NEW_ROUND_STEP,
    EVENT_VALID_BLOCK,
    EVENT_VOTE,
)

def _gossip_log():
    """Logger for the per-peer gossip/query routines (lazy: honors
    whatever default logger the node configured after import)."""
    return _log.default_logger().with_module("consensus.reactor")


STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23


class PeerState:
    """Mirror of a peer's round state (reactor.go PeerState).

    ``rng`` seeds the vote-pick draw: the simnet plane injects a
    per-peer child rng so gossip schedules are reproducible from one
    seed; the default (module ``random``) keeps live-net behavior.
    """

    def __init__(self, rng=None):
        self._rng = rng if rng is not None else random
        self.mtx = libsync.RLock("consensus.reactor.mtx")
        self.height = 0
        self.round = -1
        self.step = RoundStep.NEW_HEIGHT
        self.start_time_ns = 0
        self.proposal = False
        self.proposal_block_parts_header = None
        self.proposal_block_parts: BitArray | None = None
        self.proposal_pol_round = -1
        self.proposal_pol: BitArray | None = None
        self.last_commit_round = -1
        self.last_commit: BitArray | None = None
        self.catchup_commit_round = -1
        self.catchup_commit: BitArray | None = None
        self.prevotes: dict[int, BitArray] = {}
        self.precommits: dict[int, BitArray] = {}

    # -- updates from messages --------------------------------------------

    def apply_new_round_step(self, msg: NewRoundStepMessage) -> None:
        with self.mtx:
            new_height = msg.height != self.height
            new_round = new_height or msg.round != self.round
            self.height = msg.height
            self.round = msg.round
            self.step = RoundStep(msg.step)
            if new_round:
                self.proposal = False
                self.proposal_block_parts_header = None
                self.proposal_block_parts = None
                self.proposal_pol_round = -1
                self.proposal_pol = None
            if new_height:
                self.prevotes = {}
                self.precommits = {}
                self.last_commit_round = msg.last_commit_round
                self.last_commit = None
                self.catchup_commit_round = -1
                self.catchup_commit = None

    def apply_new_valid_block(self, msg: NewValidBlockMessage) -> None:
        with self.mtx:
            if self.height != msg.height:
                return
            if self.round != msg.round and not msg.is_commit:
                return
            self.proposal_block_parts_header = msg.block_part_set_header
            self.proposal_block_parts = msg.block_parts

    def set_has_proposal(self, proposal) -> None:
        with self.mtx:
            if self.height != proposal.height or self.round != proposal.round:
                return
            if self.proposal:
                return
            self.proposal = True
            if self.proposal_block_parts is None:
                self.proposal_block_parts_header = (
                    proposal.block_id.part_set_header
                )
                self.proposal_block_parts = BitArray(
                    proposal.block_id.part_set_header.total
                )
            self.proposal_pol_round = proposal.pol_round

    def set_has_block_part(self, height: int, round_: int, index: int) -> None:
        with self.mtx:
            if self.height != height or self.round != round_:
                return
            if self.proposal_block_parts is None:
                return
            self.proposal_block_parts.set_index(index, True)

    def _votes_bitarray(
        self, height: int, round_: int, msg_type: int, n_validators: int
    ) -> BitArray | None:
        if self.height == height:
            table = (
                self.prevotes
                if msg_type == canonical.PREVOTE_TYPE
                else self.precommits
            )
            if round_ not in table:
                table[round_] = BitArray(n_validators)
            return table[round_]
        if self.height == height + 1 and msg_type == canonical.PRECOMMIT_TYPE:
            if round_ == self.last_commit_round:
                if self.last_commit is None:
                    self.last_commit = BitArray(n_validators)
                return self.last_commit
        return None

    def set_has_vote(
        self, height: int, round_: int, msg_type: int, index: int,
        n_validators: int = 0,
    ) -> None:
        with self.mtx:
            ba = self._votes_bitarray(height, round_, msg_type, n_validators)
            if ba is not None and index < ba.size():
                ba.set_index(index, True)

    def apply_vote_set_bits(self, msg: VoteSetBitsMessage, our_votes) -> None:
        """Overwrite our has-vote marks with the peer's OWN report
        (reactor.go ApplyVoteSetBitsMessage). This must be able to CLEAR
        bits, not just set them: a vote we sent while the peer was still
        syncing (wait_sync drops it) stays marked as delivered forever,
        and with it the liveness self-heal — the maj23 query → VoteSetBits
        reply loop is how a rejoining node gets its round's votes
        re-gossiped. For votes in ``our_votes`` the peer's word is
        authoritative; marks for votes we don't even have stay (we could
        never resend them anyway)."""
        with self.mtx:
            ba = self._votes_bitarray(
                msg.height, msg.round, msg.msg_type,
                msg.votes.size() if msg.votes else 0,
            )
            if ba is None or msg.votes is None:
                return
            if our_votes is None or our_votes.size() != ba.size():
                new = msg.votes
            else:
                new = ba.sub(our_votes).or_(msg.votes)
            for i in range(ba.size()):
                ba.set_index(
                    i, new.get_index(i) if i < new.size() else False
                )

    def pick_vote_to_send(self, votes) -> object | None:
        """A vote from ``votes`` (a VoteSet) the peer hasn't seen."""
        with self.mtx:
            if votes is None or votes.size() == 0:
                return None
            ba = self._votes_bitarray(
                votes.height, votes.round, votes.signed_msg_type, votes.size()
            )
            if ba is None:
                return None
            candidates = [
                i
                for i in range(votes.size())
                if votes.get_by_index(i) is not None and not ba.get_index(i)
            ]
            if not candidates:
                return None
            return votes.get_by_index(self._rng.choice(candidates))


class ConsensusReactor(Reactor):
    def __init__(self, consensus_state, wait_sync: bool = False):
        super().__init__("consensus-reactor")
        self.cs = consensus_state
        self.wait_sync = wait_sync  # True while blocksync runs
        self._gossip_sleep = (
            self.cs.config.peer_gossip_sleep_duration_ns / 1e9
        )
        self._maj23_sleep = (
            self.cs.config.peer_query_maj23_sleep_duration_ns / 1e9
        )

    # -- channels (reactor.go GetChannels) ---------------------------------

    def get_channels(self):
        return [
            ChannelDescriptor(
                id=STATE_CHANNEL, priority=6, send_queue_capacity=64
            ),
            ChannelDescriptor(
                id=DATA_CHANNEL, priority=10, send_queue_capacity=100
            ),
            ChannelDescriptor(
                id=VOTE_CHANNEL, priority=7, send_queue_capacity=100
            ),
            ChannelDescriptor(
                id=VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=4
            ),
        ]

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self._subscribe_events()
        if not self.wait_sync and not self.cs.is_running():
            self.cs.start()

    def on_stop(self) -> None:
        self.cs.evsw.remove_listener("cs-reactor")
        if self.cs.is_running():
            self.cs.stop()

    def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        """Blocksync finished → start the FSM (reactor.go:109).

        ``wait_sync`` must drop BEFORE update_to_state broadcasts the new
        height: once peers see it they catch-up-gossip votes exactly once,
        and a still-syncing reactor would silently drop them."""
        self.wait_sync = False
        # This runs on the blocksync pool routine while the node's other
        # threads are live — mutating FSM state needs the state mutex,
        # exactly like the reference (reactor.go:109 takes conS.mtx
        # before updateToState). update_to_state publishes the new-step
        # event; deferral delivers it only after the mutex is released,
        # same as the FSM receive loop.
        with self.cs._deferred_events():
            with self.cs._mtx:
                self.cs.update_to_state(state)
                self.cs.reconstruct_last_commit_if_needed(state)
                self.cs.do_wal_catchup = not skip_wal
        self.cs.start()

    # -- event re-broadcast (reactor.go:415-530) ---------------------------

    def _subscribe_events(self) -> None:
        self.cs.evsw.add_listener_for_event(
            "cs-reactor", EVENT_NEW_ROUND_STEP, self._on_new_round_step
        )
        self.cs.evsw.add_listener_for_event(
            "cs-reactor", EVENT_VALID_BLOCK, self._on_valid_block
        )
        self.cs.evsw.add_listener_for_event(
            "cs-reactor", EVENT_VOTE, self._on_vote_event
        )

    def _round_step_msg(self, rs) -> NewRoundStepMessage:
        return NewRoundStepMessage(
            height=rs.height,
            round=rs.round,
            step=int(rs.step),
            seconds_since_start_time=max(
                0, int((self.cs._clock.time_ns() - rs.start_time_ns) / 1e9)
            ),
            last_commit_round=(
                rs.last_commit.round if rs.last_commit is not None else -1
            ),
        )

    def _on_new_round_step(self, rs) -> None:
        if self.switch is not None:
            self.switch.try_broadcast(
                STATE_CHANNEL, ser.dumps(self._round_step_msg(rs))
            )

    def _on_valid_block(self, rs) -> None:
        if self.switch is None or rs.proposal_block_parts is None:
            return
        msg = NewValidBlockMessage(
            height=rs.height,
            round=rs.round,
            block_part_set_header=rs.proposal_block_parts.header,
            block_parts=rs.proposal_block_parts.parts_bit_array.copy(),
            is_commit=rs.step == RoundStep.COMMIT,
        )
        self.switch.try_broadcast(STATE_CHANNEL, ser.dumps(msg))

    def _on_vote_event(self, vote) -> None:
        if self.switch is None:
            return
        msg = HasVoteMessage(
            height=vote.height,
            round=vote.round,
            msg_type=vote.msg_type,
            index=vote.validator_index,
        )
        self.switch.try_broadcast(STATE_CHANNEL, ser.dumps(msg))

    # -- peer lifecycle ----------------------------------------------------

    def init_peer(self, peer) -> None:
        peer.set(
            "consensus_peer_state",
            PeerState(rng=getattr(peer, "gossip_rng", None)),
        )

    def add_peer(self, peer) -> None:
        ps = peer.get("consensus_peer_state")
        # Announce our current step so the peer can route gossip — but
        # NOT while we're still syncing (reactor.go AddPeer: "If we're
        # syncing, broadcast a RoundStepMessage later upon
        # SwitchToConsensus"). Announcing invites vote gossip that
        # wait_sync DROPS while the sender marks it delivered — a
        # restarting validator then wedges missing exactly those votes.
        # switch_to_consensus broadcasts the round step when we're ready.
        if not self.wait_sync:
            rs = self.cs.get_round_state()
            peer.try_send(STATE_CHANNEL, ser.dumps(self._round_step_msg(rs)))
        if getattr(peer, "sim_driven", False):
            # simnet peers: the scheduler drives the three per-peer
            # routines as virtual-time ticks (_gossip_data_once /
            # _gossip_votes_once / _query_maj23_once) — spawning the
            # thread-per-peer loops here would reintroduce wall-clock
            # nondeterminism and break at N=100+ nodes
            return
        for fn, name in (
            (self._gossip_data_routine, "gossip-data"),
            (self._gossip_votes_routine, "gossip-votes"),
            (self._query_maj23_routine, "maj23"),
        ):
            threading.Thread(
                target=fn, args=(peer, ps), name=f"{name}-{peer.id[:8]}",
                daemon=True,
            ).start()

    def remove_peer(self, peer, reason) -> None:
        pass  # routines exit when the peer stops

    # -- receive dispatch (reactor.go Receive:233) -------------------------

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        msg = ser.loads(msg_bytes)
        ps: PeerState = peer.get("consensus_peer_state")
        if ps is None:
            return
        if ch_id == STATE_CHANNEL:
            if isinstance(msg, NewRoundStepMessage):
                if msg.step == int(RoundStep.COMMIT):
                    # the peer's step broadcast entering COMMIT is the
                    # reliable per-height commit announcement (the
                    # NewValidBlock is_commit path below only fires on
                    # catch-up edges) — the commit leg of the
                    # proposal→prevote→precommit→commit chain
                    libnetstats.observe_propagation("commit", msg.height)
                ps.apply_new_round_step(msg)
            elif isinstance(msg, NewValidBlockMessage):
                if msg.is_commit:
                    # the peer announced a committed block: the commit
                    # leg of the proposal→…→commit propagation chain
                    libnetstats.observe_propagation("commit", msg.height)
                ps.apply_new_valid_block(msg)
            elif isinstance(msg, HasVoteMessage):
                ps.set_has_vote(
                    msg.height, msg.round, msg.msg_type, msg.index,
                    len(self.cs.get_round_state().validators or ()),
                )
            elif isinstance(msg, VoteSetMaj23Message):
                self._handle_maj23(peer, ps, msg)
        elif ch_id == DATA_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, ProposalMessage):
                libnetstats.observe_propagation(
                    "proposal", msg.proposal.height
                )
                ps.set_has_proposal(msg.proposal)
                self.cs.set_proposal_from_peer(msg.proposal, peer.id)
            elif isinstance(msg, ProposalPOLMessage):
                with ps.mtx:
                    if ps.height == msg.height:
                        ps.proposal_pol_round = msg.proposal_pol_round
                        ps.proposal_pol = msg.proposal_pol
            elif isinstance(msg, BlockPartMessage):
                libnetstats.observe_propagation("block_part", msg.height)
                ps.set_has_block_part(msg.height, msg.round, msg.part.index)
                self.cs.add_block_part_from_peer(
                    msg.height, msg.round, msg.part, peer.id
                )
        elif ch_id == VOTE_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, VoteMessage):
                libnetstats.observe_propagation(
                    "prevote"
                    if msg.vote.msg_type == canonical.PREVOTE_TYPE
                    else "precommit",
                    msg.vote.height,
                )
                rs = self.cs.get_round_state()
                ps.set_has_vote(
                    msg.vote.height, msg.vote.round, msg.vote.msg_type,
                    msg.vote.validator_index,
                    len(rs.validators or ()),
                )
                self.cs.add_vote_from_peer(msg.vote, peer.id)
        elif ch_id == VOTE_SET_BITS_CHANNEL:
            if isinstance(msg, VoteSetBitsMessage):
                # our own bits for the claimed block decide which of the
                # peer's reports are authoritative (reactor.go:316-330)
                rs = self.cs.get_round_state()
                our = None
                if rs.height == msg.height and rs.votes is not None:
                    vs = (
                        rs.votes.prevotes(msg.round)
                        if msg.msg_type == canonical.PREVOTE_TYPE
                        else rs.votes.precommits(msg.round)
                    )
                    if vs is not None:
                        our = vs.bit_array_by_block_id(msg.block_id)
                ps.apply_vote_set_bits(msg, our)

    def _handle_maj23(self, peer, ps: PeerState, msg: VoteSetMaj23Message):
        """reactor.go: record claim, respond with our vote bits."""
        rs = self.cs.get_round_state()
        if rs.height != msg.height or rs.votes is None:
            return
        try:
            rs.votes.set_peer_maj23(msg.round, msg.msg_type, peer.id, msg.block_id)
        except Exception:
            return
        vs = (
            rs.votes.prevotes(msg.round)
            if msg.msg_type == canonical.PREVOTE_TYPE
            else rs.votes.precommits(msg.round)
        )
        if vs is None:
            return
        our = vs.bit_array_by_block_id(msg.block_id)
        peer.try_send(
            VOTE_SET_BITS_CHANNEL,
            ser.dumps(
                VoteSetBitsMessage(
                    height=msg.height,
                    round=msg.round,
                    msg_type=msg.msg_type,
                    block_id=msg.block_id,
                    votes=our,
                )
            ),
        )

    # -- gossip: data (reactor.go:563) -------------------------------------

    def _gossip_data_routine(self, peer, ps: PeerState) -> None:
        while peer.is_running() and self.is_running():
            rs = self.cs.get_round_state()
            try:
                if self._gossip_data_once(peer, ps, rs):
                    continue
            except Exception as e:  # CLNT006: keep gossiping, but say why
                _gossip_log().debug(
                    "gossip data failed; retrying after sleep",
                    peer=str(getattr(peer, "id", "?"))[:16],
                    err=repr(e)[:120],
                )
            time.sleep(self._gossip_sleep)

    def _gossip_data_once(self, peer, ps: PeerState, rs) -> bool:
        # 1. our proposal block parts the peer lacks (same H/R)
        if (
            rs.proposal_block_parts is not None
            and ps.height == rs.height
            and ps.proposal_block_parts is not None
            and ps.proposal_block_parts_header == rs.proposal_block_parts.header
        ):
            have = rs.proposal_block_parts.parts_bit_array
            for i in range(rs.proposal_block_parts.header.total):
                if have.get_index(i) and not ps.proposal_block_parts.get_index(i):
                    part = rs.proposal_block_parts.get_part(i)
                    if part is not None and peer.send(
                        DATA_CHANNEL,
                        ser.dumps(BlockPartMessage(rs.height, rs.round, part)),
                    ):
                        ps.set_has_block_part(rs.height, rs.round, i)
                        return True
                    return False
        # 2. peer is catching up: send parts of their next block
        if ps.height > 0 and ps.height < rs.height:
            return self._gossip_catchup_part(peer, ps)
        # 3. the proposal itself
        if rs.proposal is not None and ps.height == rs.height and not ps.proposal:
            if peer.send(
                DATA_CHANNEL, ser.dumps(ProposalMessage(rs.proposal))
            ):
                ps.set_has_proposal(rs.proposal)
                # POL info lets the peer verify an old-round proposal
                if 0 <= rs.proposal.pol_round:
                    pol = rs.votes.prevotes(rs.proposal.pol_round)
                    if pol is not None:
                        peer.send(
                            DATA_CHANNEL,
                            ser.dumps(
                                ProposalPOLMessage(
                                    height=rs.height,
                                    proposal_pol_round=rs.proposal.pol_round,
                                    proposal_pol=pol.bit_array(),
                                )
                            ),
                        )
                return True
        return False

    def _gossip_catchup_part(self, peer, ps: PeerState) -> bool:
        """reactor.go gossipDataForCatchup:679."""
        store = self.cs.block_store
        meta = store.load_block_meta(ps.height) if store else None
        if meta is None:
            return False
        with ps.mtx:
            header_ok = (
                ps.proposal_block_parts_header
                == meta.block_id.part_set_header
                and ps.proposal_block_parts is not None
            )
        if not header_ok:
            return False
        for i in range(meta.block_id.part_set_header.total):
            if not ps.proposal_block_parts.get_index(i):
                part = store.load_block_part(ps.height, i)
                if part is None:
                    return False
                if peer.send(
                    DATA_CHANNEL,
                    ser.dumps(BlockPartMessage(ps.height, ps.round, part)),
                ):
                    ps.set_has_block_part(ps.height, ps.round, i)
                    return True
                return False
        return False

    # -- gossip: votes (reactor.go:731) ------------------------------------

    def _gossip_votes_routine(self, peer, ps: PeerState) -> None:
        while peer.is_running() and self.is_running():
            rs = self.cs.get_round_state()
            try:
                if self._gossip_votes_once(peer, ps, rs):
                    continue
            except Exception as e:  # CLNT006: keep gossiping, but say why
                _gossip_log().debug(
                    "gossip votes failed; retrying after sleep",
                    peer=str(getattr(peer, "id", "?"))[:16],
                    err=repr(e)[:120],
                )
            time.sleep(self._gossip_sleep)

    def _gossip_votes_once(self, peer, ps: PeerState, rs) -> bool:
        if rs.votes is None:
            return False
        # same height: peer's round votes, POL prevotes, our last commit
        if ps.height == rs.height:
            for votes in (
                rs.votes.prevotes(ps.round) if ps.round >= 0 else None,
                rs.votes.precommits(ps.round) if ps.round >= 0 else None,
            ):
                if votes is not None and self._send_vote_from(peer, ps, votes):
                    return True
        if (
            ps.height + 1 == rs.height
            and rs.last_commit is not None
        ):
            if self._send_vote_from(peer, ps, rs.last_commit):
                return True
        # deep catchup: votes from the stored commit of the peer's height
        if ps.height > 0 and ps.height < rs.height - 1:
            return self._gossip_catchup_commit_votes(peer, ps)
        return False

    def _send_vote_from(self, peer, ps: PeerState, votes) -> bool:
        vote = ps.pick_vote_to_send(votes)
        if vote is None:
            return False
        if peer.send(VOTE_CHANNEL, ser.dumps(VoteMessage(vote))):
            ps.set_has_vote(
                vote.height, vote.round, vote.msg_type, vote.validator_index,
                votes.size(),
            )
            return True
        return False

    def _gossip_catchup_commit_votes(self, peer, ps: PeerState) -> bool:
        store = self.cs.block_store
        commit = store.load_block_commit(ps.height) if store else None
        if commit is None:
            return False
        # send one commit-sig as a vote the peer lacks
        with ps.mtx:
            ba = ps.precommits.setdefault(
                commit.round, BitArray(commit.size())
            )
        for idx, cs_sig in enumerate(commit.signatures):
            if cs_sig.block_id_flag == 1:  # absent
                continue
            if ba is not None and ba.get_index(idx):
                continue
            from ..types.vote import Vote

            vote = Vote(
                msg_type=canonical.PRECOMMIT_TYPE,
                height=ps.height,
                round=commit.round,
                block_id=cs_sig.block_id(commit.block_id),
                timestamp_ns=cs_sig.timestamp_ns,
                validator_address=cs_sig.validator_address,
                validator_index=idx,
                signature=cs_sig.signature,
            )
            if peer.send(VOTE_CHANNEL, ser.dumps(VoteMessage(vote))):
                ps.set_has_vote(
                    ps.height, commit.round, canonical.PRECOMMIT_TYPE, idx,
                    commit.size(),
                )
                return True
            return False
        return False

    # -- maj23 queries (reactor.go:886) ------------------------------------

    def _query_maj23_routine(self, peer, ps: PeerState) -> None:
        while peer.is_running() and self.is_running():
            rs = self.cs.get_round_state()
            try:
                self._query_maj23_once(peer, ps, rs)
            except Exception as e:  # CLNT006: keep querying, but say why
                _gossip_log().debug(
                    "maj23 query failed; retrying after sleep",
                    peer=str(getattr(peer, "id", "?"))[:16],
                    err=repr(e)[:120],
                )
            time.sleep(self._maj23_sleep)

    def _query_maj23_once(self, peer, ps: PeerState, rs) -> None:
        """One maj23 probe toward ``peer`` (the routine's body; also the
        simnet tick)."""
        if rs.votes is not None and ps.height == rs.height:
            for msg_type, vs in (
                (canonical.PREVOTE_TYPE, rs.votes.prevotes(rs.round)),
                (
                    canonical.PRECOMMIT_TYPE,
                    rs.votes.precommits(rs.round),
                ),
            ):
                if vs is None:
                    continue
                maj = vs.two_thirds_majority()
                if maj is not None:
                    peer.try_send(
                        STATE_CHANNEL,
                        ser.dumps(
                            VoteSetMaj23Message(
                                height=rs.height,
                                round=rs.round,
                                msg_type=msg_type,
                                block_id=maj,
                            )
                        ),
                    )
        # Catch-up query (reactor.go:938-960): a peer stuck on an
        # OLDER height is asked against our STORED commit. Its
        # VoteSetBits reply exposes which precommits it actually
        # holds, clearing stale has-vote marks (votes we sent
        # while it was syncing were dropped but stayed marked) so
        # the last-commit/catch-up gossip resends them — without
        # this, a validator that restarts during its own commit
        # wedges one height behind forever.
        elif (
            ps.height > 0
            and ps.height < rs.height
            and self.cs.block_store is not None
        ):
            commit = self.cs.block_store.load_block_commit(ps.height)
            if commit is not None:
                peer.try_send(
                    STATE_CHANNEL,
                    ser.dumps(
                        VoteSetMaj23Message(
                            height=ps.height,
                            round=commit.round,
                            msg_type=canonical.PRECOMMIT_TYPE,
                            block_id=commit.block_id,
                        )
                    ),
                )
