"""ABCI handshake replay (reference: consensus/replay.go:242-516).

On boot the application may be behind the block store (crash between
SaveBlock and Commit) or brand new (statesync'd node store, wiped app
dir). ``Handshaker.handshake`` asks the app where it is via ABCI ``Info``
and replays the missing blocks from the store — FinalizeBlock+Commit
without re-validation for fully-committed heights, the full
``BlockExecutor.apply_block`` path for a stored-but-unapplied tip.
"""

from __future__ import annotations

import json

from ..abci import types as abci
from ..state.execution import (
    build_last_commit_info,
    validator_updates_to_validators,
)
from ..types import GenesisDoc
from ..types.validator_set import ValidatorSet


class HandshakeError(Exception):
    pass


def exec_commit_block(proxy_app, block, state, store=None) -> bytes:
    """state/execution.go:679 ExecCommitBlock — replay one stored block
    through FinalizeBlock+Commit, no validation, no events.

    DecidedLastCommit is built from the validator set at height-1 loaded
    from the state store (buildLastCommitInfo), NOT the boot-time
    state.last_validators — they diverge when the replayed window spans
    validator-set changes.
    """
    resp = proxy_app.finalize_block(
        abci.RequestFinalizeBlock(
            txs=list(block.data.txs),
            decided_last_commit=build_last_commit_info(block, store, state),
            misbehavior=[],
            hash=block.hash(),
            height=block.header.height,
            time_ns=block.header.time_ns,
            next_validators_hash=block.header.next_validators_hash,
            proposer_address=block.header.proposer_address,
        )
    )
    if store is not None:
        store.save_finalize_block_response(block.header.height, resp)
    proxy_app.commit()
    return resp.app_hash


class Handshaker:
    def __init__(
        self,
        state_store,
        state,  # sm.State loaded from disk (or genesis)
        block_store,
        genesis_doc: GenesisDoc,
        block_exec=None,  # needed only for the stored-but-unapplied tip
    ):
        self.state_store = state_store
        self.state = state
        self.block_store = block_store
        self.genesis = genesis_doc
        self.block_exec = block_exec
        self.n_blocks = 0

    def handshake(self, app_conns) -> bytes:
        """replay.go:242 — Info on the query connection, then ReplayBlocks
        on the consensus connection. Returns the final app hash."""
        info = app_conns.query.info(
            abci.RequestInfo(abci_version="2.0.0", block_version=11)
        )
        app_hash = self.replay_blocks(
            info.last_block_app_hash, info.last_block_height, app_conns
        )
        return app_hash

    # -- replay.go:285 ReplayBlocks ----------------------------------------

    def replay_blocks(
        self, app_hash: bytes, app_height: int, app_conns
    ) -> bytes:
        store_height = self.block_store.height()
        store_base = self.block_store.base()
        state_height = self.state.last_block_height
        state = self.state

        if app_height < 0:
            raise HandshakeError(f"app reported negative height {app_height}")

        # Fresh chain: InitChain with the genesis validator set.
        if app_height == 0:
            res = app_conns.consensus.init_chain(
                abci.RequestInitChain(
                    time_ns=self.genesis.genesis_time_ns,
                    chain_id=self.genesis.chain_id,
                    consensus_params=self.genesis.consensus_params,
                    validators=[
                        abci.ValidatorUpdate(
                            gv.pub_key.type, gv.pub_key.bytes(), gv.power
                        )
                        for gv in self.genesis.validators
                    ],
                    app_state_bytes=json.dumps(
                        self.genesis.app_state
                    ).encode(),
                    initial_height=self.genesis.initial_height,
                )
            )
            if state_height == 0:  # only overwrite genesis-derived state
                if res.app_hash:
                    state.app_hash = res.app_hash
                if res.validators:
                    vals = ValidatorSet(
                        validator_updates_to_validators(res.validators)
                    )
                    state.validators = vals
                    state.next_validators = vals.copy_increment_proposer_priority(1)
                elif not self.genesis.validators:
                    raise HandshakeError(
                        "validator set is nil in genesis and InitChain"
                    )
                if res.consensus_params is not None:
                    state.consensus_params = res.consensus_params
                self.state_store.save(state)
                app_hash = state.app_hash

        if store_height == 0:
            return app_hash

        if app_height > 0 and app_height < store_base - 1:
            raise HandshakeError(
                f"app height {app_height} below block store base {store_base}"
            )
        if store_height < app_height:
            raise HandshakeError(
                f"app is ahead of the block store: {app_height} > {store_height}"
            )
        if store_height < state_height:
            raise HandshakeError(
                f"state height {state_height} ahead of store {store_height}"
            )
        if store_height > state_height + 1:
            raise HandshakeError(
                f"store height {store_height} more than one above state "
                f"{state_height}"
            )

        # Replay fully-committed heights the app is missing.
        replay_until = (
            state_height  # the tip (if unapplied) goes through apply_block
            if store_height == state_height + 1
            else store_height
        )
        for height in range(app_height + 1, replay_until + 1):
            block = self.block_store.load_block(height)
            if block is None:
                raise HandshakeError(f"missing block {height} in store")
            app_hash = exec_commit_block(
                app_conns.consensus, block, state, self.state_store
            )
            self.n_blocks += 1

        # Stored-but-unapplied tip: full apply (validates, saves state).
        if store_height == state_height + 1:
            block = self.block_store.load_block(store_height)
            meta = self.block_store.load_block_meta(store_height)
            if self.block_exec is None:
                raise HandshakeError(
                    "unapplied tip block requires a block executor"
                )
            if app_height == store_height:
                # App already has it; just sync our state via replay of
                # the responses (light path): recompute state only.
                resp = self.state_store.load_finalize_block_response(
                    store_height
                )
                if resp is None:
                    raise HandshakeError(
                        f"app at {app_height} but no stored responses"
                    )
                new_state = self.block_exec._update_state(
                    state, meta.block_id, block, resp
                )
                new_state.app_hash = app_hash
                self.state_store.save(new_state)
                self.state = new_state
            else:
                new_state = self.block_exec.apply_block(
                    state, meta.block_id, block
                )
                self.state = new_state
                app_hash = new_state.app_hash
            self.n_blocks += 1

        return app_hash
