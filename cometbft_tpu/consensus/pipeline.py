"""Pipelined heights: the commit-boundary overlap engine.

The serial engine runs the whole commit chain — save_block, the WAL's
EndHeight fsync, ApplyBlock — on the FSM thread under `consensus.state`,
so the stages the per-height budget plane shows dominating commit
latency (wal_fsync, apply) serialize with next-height work by
construction.  This module hosts the three overlaps that remove them
from the serial span without weakening any durability invariant:

* **Speculative execution** (`cs-spec-exec` worker): at prevote time the
  FSM submits the block it just validated; the worker runs FinalizeBlock
  through the ABCI client's snapshot/finalize/restore sandwich
  (`abci/client.LocalClient.speculate_finalize`), so the app is
  bit-identical afterwards and a speculation that never wins needs no
  cleanup.  If the same block wins precommit, `_finalize_commit`
  consumes the memoized ``(response, post_token)`` instead of
  re-executing; a miss falls back to the serial FinalizeBlock.

* **Ordered commit-writer**: the durable suffix of every height —
  save_block -> WAL EndHeight fsync -> app Commit/state persist/prune/
  events — runs as ONE FIFO job off the FSM thread.  The order inside
  the job and across jobs is exactly the serial order, so every crash
  window maps onto the existing recovery matrix (WAL replay before
  save, handshake replay of the stored-but-unapplied tip after), and
  the handshake invariant "the app is never durably ahead of the block
  store" (consensus/replay.py) is preserved verbatim.

* **Durability barrier**: the FSM may PROCESS height H+1's proposal
  while H's job drains, but it must not SIGN any vote for H+1, reap the
  mempool for H+1's proposal, or prune state until H is durable —
  `wait_durable` is that fence (consensus/state.py calls it at
  decide-proposal, do-prevote and sign-vote; state/execution._prune
  caps pruning at `durable_height`).

Inline mode (`sim_driven` FSMs, or ``COMETBFT_TPU_PIPELINE=inline``)
runs both workers synchronously on the submitting thread: identical
code path and ring rows, zero added concurrency — the simnet
determinism pairs stay bit-reproducible.

Lock order: `consensus.state` -> `consensus.pipeline._mtx` (the FSM
enqueues and waits under its own mutex).  The workers hold
`consensus.pipeline._mtx` only to pop/publish — never while running a
job — and job bodies acquire the store/WAL/mempool/ABCI locks the
serial path already documents, so the pipeline mutex stays a leaf on
the worker side and the graph stays acyclic.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..abci.client import SpeculationUnsupported
from ..libs import devledger as libdevledger
from ..libs import fail as libfail
from ..libs import health as libhealth
from ..libs import metrics as libmetrics
from ..libs import sync as libsync

# how long a barrier waiter tolerates an undrained commit-writer before
# declaring the pipeline wedged (a disk that slow trips the WAL's
# degraded state long before this); generous because the penalty for a
# false trip is a node fail-stop
BARRIER_TIMEOUT_S = 60.0
# bound on waiting for an in-flight speculation at consume time: by
# then the serial fallback costs one FinalizeBlock, so don't wait much
# longer than one typically takes
SPEC_CONSUME_WAIT_S = 5.0
_STOP = object()


def pipeline_mode() -> str:
    """COMETBFT_TPU_PIPELINE: "auto" (default — node boot turns the
    pipelined chain on for live nodes; sim-driven FSMs run inline),
    "on"/"1" force, "inline" run jobs synchronously on the submitting
    thread, "off"/"0" fully serial."""
    v = os.environ.get("COMETBFT_TPU_PIPELINE", "auto").lower()
    if v in ("1", "on", "true", "yes"):
        return "on"
    if v in ("0", "off", "false", "no"):
        return "off"
    if v == "inline":
        return "inline"
    return "auto"


def spec_mode() -> str:
    """COMETBFT_TPU_SPEC_EXEC: "auto" (default — on when the ABCI
    client supports the speculation extension), "on"/"1" force,
    "off"/"0" never speculate."""
    v = os.environ.get("COMETBFT_TPU_SPEC_EXEC", "auto").lower()
    if v in ("1", "on", "true", "yes"):
        return "on"
    if v in ("0", "off", "false", "no"):
        return "off"
    return "auto"


class PipelineError(Exception):
    """The commit-writer failed or wedged; the node must fail-stop
    (consensus/state.py converts this to FatalConsensusError)."""


class CommitPipeline:
    """Spec-exec worker + ordered commit-writer + durability barrier.

    One instance per node, wired by node boot (node/node.py) between
    the block executor and the consensus FSM.  All cross-thread state
    lives under ``consensus.pipeline._mtx``; the FSM is the only
    submitter, the two workers the only consumers.
    """

    def __init__(self, block_exec, wal, on_fatal=None):
        self.block_exec = block_exec
        self.wal = wal
        self.on_fatal = on_fatal
        self.enabled = False  # pipelined commit chain (knob-gated)
        self.spec_enabled = False  # speculative execution (knob-gated)
        # inline mode: execute jobs synchronously on the submitting
        # thread (sim_driven FSMs; COMETBFT_TPU_PIPELINE=inline)
        self.inline = False
        # flight-ring origin the workers declare (node boot sets it to
        # the same node-id prefix as the cs-receive thread)
        self.health_origin = 0
        self._mtx = libsync.Mutex("consensus.pipeline._mtx")
        self._cv = libsync.Condition(self._mtx, name="consensus.pipeline._mtx")
        # commit-writer state
        self._jobs: deque = deque()
        self._durable = 0  # highest height whose job completed
        self._enqueued = 0  # highest height handed to the writer
        self._error: BaseException | None = None
        self._stopping = False
        self._writer: threading.Thread | None = None
        # speculation slot (at most ONE in flight: the FSM only ever
        # speculates the block it is prevoting at its current height)
        self._spec_key = None  # (height, block_hash)
        self._spec_state = "idle"  # idle|pending|inflight|done|failed
        self._spec_thunk = None
        self._spec_result = None  # (resp, post_token, dur_ns)
        self._spec_thread: threading.Thread | None = None
        self._prestage_threads: list[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def note_base(self, height: int) -> None:
        """Seed the durable height at boot (state.last_block_height):
        everything at or below it is already fsynced by the serial
        paths that produced it."""
        with self._mtx:
            libsync.lockset_note("CommitPipeline._durable")
            if height > self._durable:
                self._durable = height
            if height > self._enqueued:
                self._enqueued = height

    def durable_height(self) -> int:
        """The prune gate (state/execution.BlockExecutor.prune_gate):
        pruning must never outrun the fsynced suffix."""
        with self._mtx:
            libsync.lockset_note("CommitPipeline._durable")
            return self._durable

    def _ensure_threads(self) -> None:
        # lazily, under _mtx: inline/sim runs never pay for threads
        if self._writer is None:
            self._writer = threading.Thread(
                target=self._writer_run, name="cs-commit-writer", daemon=True
            )
            self._writer.start()
        if self.spec_enabled and self._spec_thread is None:
            self._spec_thread = threading.Thread(
                target=self._spec_run, name="cs-spec-exec", daemon=True
            )
            self._spec_thread.start()

    def stop(self, drain_s: float = 10.0) -> None:
        """Drain pending jobs (bounded), then stop both workers.  Must
        run BEFORE the WAL closes — the writer fsyncs through it."""
        with self._mtx:
            libsync.lockset_note("CommitPipeline._durable")
            self._stopping = True
            deadline = time.monotonic() + drain_s
            while (
                self._jobs
                and self._error is None
                and time.monotonic() < deadline
            ):
                self._cv.wait(0.1)
            self._jobs.append(_STOP)
            self._cv.notify_all()
            # snapshot under the mutex; joins happen after release
            workers = (self._writer, self._spec_thread)
            prestage = list(self._prestage_threads)
        me = threading.current_thread()
        for t in workers:
            if t is not None and t is not me:
                t.join(timeout=5)
        for t in prestage:
            if t is not me:
                t.join(timeout=2)

    def _fatal(self, exc: BaseException) -> None:
        with self._mtx:
            libsync.lockset_note("CommitPipeline._durable")
            if self._error is None:
                self._error = exc
            self._cv.notify_all()
        if self.on_fatal is not None:
            self.on_fatal(exc)

    # -- commit-writer -----------------------------------------------------

    def enqueue_commit(self, height: int, fn) -> None:
        """Hand one height's durable suffix to the ordered writer.
        ``fn`` is the whole job — save_block -> EndHeight fsync -> app
        commit/persist — built by the FSM with everything it needs
        bound in; the writer only supplies ordering, attribution and
        the durability handshake.  Inline mode runs it right here."""
        if self.inline:
            with libdevledger.caller_class("proposal"):
                fn()
            with self._mtx:
                libsync.lockset_note("CommitPipeline._durable")
                self._enqueued = max(self._enqueued, height)
                self._durable = max(self._durable, height)
            return
        with self._mtx:
            libsync.lockset_note("CommitPipeline._durable")
            if self._error is not None:
                raise PipelineError(
                    f"commit-writer already failed: {self._error!r}"
                )
            if self._stopping:
                raise PipelineError("commit pipeline stopping")
            self._ensure_threads()
            self._jobs.append((height, fn))
            self._enqueued = max(self._enqueued, height)
            lag = self._enqueued - self._durable
            self._cv.notify_all()
        libmetrics.node_metrics().fsync_lag_heights.set(lag)

    def _writer_run(self) -> None:
        libhealth.set_thread_origin(self.health_origin)
        while True:
            with self._mtx:
                libsync.lockset_note("CommitPipeline._durable")
                while not self._jobs:
                    self._cv.wait(0.5)
                job = self._jobs.popleft()
            if job is _STOP:
                return
            height, fn = job
            try:
                # device tickets from save_block's merkle work and the
                # app-commit path belong to the block-production plane
                with libdevledger.caller_class("proposal"):
                    fn()
            except BaseException as e:  # noqa: BLE001 — fail-stop, never a silent dead writer
                import traceback

                traceback.print_exc()
                self._fatal(
                    e
                    if isinstance(e, Exception)
                    else PipelineError(f"commit-writer died: {e!r}")
                )
                return
            with self._mtx:
                libsync.lockset_note("CommitPipeline._durable")
                self._durable = max(self._durable, height)
                lag = self._enqueued - self._durable
                self._cv.notify_all()
            libmetrics.node_metrics().fsync_lag_heights.set(lag)

    def wait_durable(self, height: int, timeout_s: float | None = None) -> None:
        """Block until every height <= ``height`` is durable (saved +
        fsynced + applied).  The FSM calls this holding
        `consensus.state` — by design: the whole point is that the FSM
        must not advance past this fence.  Raises PipelineError on a
        failed writer or a wedge (caller fail-stops)."""
        if timeout_s is None:
            timeout_s = BARRIER_TIMEOUT_S
        with self._mtx:
            libsync.lockset_note("CommitPipeline._durable")
            # Only heights actually handed to the writer can be owed:
            # anything else (WAL catchup replay, blocksync/statesync
            # applies, pre-pipeline history) was made durable
            # synchronously by the serial path that produced it, so
            # waiting on it would wedge on a debt that does not exist.
            height = min(height, self._enqueued)
            if self._durable >= height:
                if self._error is not None:
                    raise PipelineError(
                        f"commit-writer failed: {self._error!r}"
                    )
                return
            deadline = time.monotonic() + timeout_s
            while self._durable < height and self._error is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PipelineError(
                        f"durability barrier wedged: height {height} not "
                        f"durable after {timeout_s:.0f}s "
                        f"(durable={self._durable})"
                    )
                self._cv.wait(min(remaining, 0.5))
            if self._error is not None:
                raise PipelineError(
                    f"commit-writer failed: {self._error!r}"
                )

    # -- speculation -------------------------------------------------------

    def submit_speculation(self, height: int, block_hash: bytes, thunk) -> None:
        """FSM, at prevote time, after validate_block passed: start
        FinalizeBlock speculatively for the block being prevoted.
        ``thunk()`` returns ``(resp, post_token)`` (built over
        BlockExecutor.speculate_block).  At most one speculation is
        live; a resubmit for the same key is a no-op, a different key
        supersedes (the old one counts as an abort)."""
        if not self.spec_enabled:
            return
        key = (height, bytes(block_hash))
        run_inline = False
        with self._mtx:
            libsync.lockset_note("CommitPipeline._spec_state")
            if self._spec_key == key and self._spec_state in (
                "pending", "inflight", "done"
            ):
                return
            if self._spec_state in ("pending", "done") or (
                self._spec_state == "inflight" and self._spec_key != key
            ):
                # superseded before consumption
                self._record_outcome(
                    self._spec_key[0] if self._spec_key else height,
                    0, libhealth.SPEC_ABORT, 0,
                )
            self._spec_key = key
            self._spec_thunk = thunk
            self._spec_result = None
            if self.inline:
                self._spec_state = "inflight"
                run_inline = True
            else:
                self._spec_state = "pending"
                self._ensure_threads()
                self._cv.notify_all()
        if run_inline:
            self._run_spec(key, thunk)

    def _spec_run(self) -> None:
        libhealth.set_thread_origin(self.health_origin)
        while True:
            with self._mtx:
                libsync.lockset_note("CommitPipeline._spec_state")
                while self._spec_state != "pending" and not self._stopping:
                    self._cv.wait(0.5)
                if self._stopping:
                    return
                self._spec_state = "inflight"
                key, thunk = self._spec_key, self._spec_thunk
            self._run_spec(key, thunk)

    def _run_spec(self, key, thunk) -> None:
        """Execute one speculation (worker thread, or the FSM thread in
        inline mode) and publish its result if the slot still wants it."""
        # The crash seam sits OUTSIDE the failure-absorbing try: a real
        # speculation error degrades to a serial commit, but an armed
        # crash point must kill the node — live runs os._exit inside
        # fail_point, simnet's handler raises and the exception
        # propagates to the (inline) FSM caller as a fatal.
        libfail.fail_point("cs-spec-exec")
        t0 = time.perf_counter()
        result = None
        failed = None
        try:
            # attribution: the speculative finalize is commit-side
            # verification work racing the vote gossip
            with libdevledger.caller_class("commit-verify"):
                resp, post = thunk()
            result = (resp, post, int((time.perf_counter() - t0) * 1e9))
        except SpeculationUnsupported:
            # the client/app pair can't sandbox — stop trying, forever
            # lockfree: boot-time knob plus this one-way False latch; GIL-atomic, and a stale True merely submits one more speculation that records 'unsupported' again
            self.spec_enabled = False
            failed = "unsupported"
        except Exception:
            import traceback

            traceback.print_exc()
            failed = "error"
        with self._mtx:
            libsync.lockset_note("CommitPipeline._spec_state")
            if self._spec_key != key or self._spec_state != "inflight":
                # superseded while executing: the submitter already
                # recorded the abort
                return
            if failed is None:
                self._spec_state = "done"
                self._spec_result = result
            else:
                self._spec_state = "failed"
                self._spec_result = None
                if failed == "error":
                    self._record_outcome(
                        key[0], 0, libhealth.SPEC_ABORT, 0
                    )
            self._cv.notify_all()

    def consume_speculation(self, height: int, round_: int, block_hash: bytes):
        """FSM, at finalize-commit time: claim the memoized result for
        the block that won precommit.  Returns ``(resp, post_token)``
        on a hit, None on a miss (caller runs the serial FinalizeBlock).
        Waits briefly for an in-flight speculation of the RIGHT block —
        the work already happened, discarding it to re-execute would be
        strictly worse."""
        if not self.spec_enabled:
            return None
        key = (height, bytes(block_hash))
        outcome = libhealth.SPEC_MISS
        dur_ns = 0
        result = None
        with self._mtx:
            libsync.lockset_note("CommitPipeline._spec_state")
            if self._spec_key == key:
                deadline = time.monotonic() + SPEC_CONSUME_WAIT_S
                while (
                    self._spec_state in ("pending", "inflight")
                    and time.monotonic() < deadline
                ):
                    self._cv.wait(0.2)
                if self._spec_state == "done":
                    resp, post, dur_ns = self._spec_result
                    result = (resp, post)
                    outcome = libhealth.SPEC_HIT
                self._spec_key = None
                self._spec_state = "idle"
                self._spec_thunk = None
                self._spec_result = None
            elif self._spec_state in ("pending", "done"):
                # we speculated some OTHER block and it lost
                self._record_outcome(
                    self._spec_key[0] if self._spec_key else height,
                    round_, libhealth.SPEC_ABORT, 0,
                )
                self._spec_key = None
                self._spec_state = "idle"
                self._spec_thunk = None
                self._spec_result = None
        self._record_outcome(height, round_, outcome, dur_ns)
        return result

    def _record_outcome(
        self, height: int, round_: int, outcome: int, dur_ns: int
    ) -> None:
        libhealth.record(
            libhealth.EV_SPEC, height, round_, outcome, dur_ns
        )
        libmetrics.node_metrics().spec_exec.labels(
            libhealth._SPEC_OUTCOMES[outcome]
        ).inc()

    # -- next-height prestaging --------------------------------------------

    def prestage_next(self, validator_set) -> None:
        """While H's durable suffix drains: warm H+1's device windows —
        the next validator set's expanded pubkeys into the PubkeyArena
        (crypto/batch.prestage_validators) and the hash plane's device
        path (crypto/hashplane.prewarm), so the proposer's PartSet
        build and the first verify windows of H+1 form without a cold
        start.  Pure cache warm-up: results are bit-identical with or
        without it, so inline/sim runs skip it entirely."""
        if self.inline:
            return

        def _warm(vs=validator_set):
            try:
                with libdevledger.caller_class("proposal"):
                    from ..crypto import batch as crypto_batch
                    from ..crypto import hashplane as crypto_hashplane

                    crypto_batch.prestage_validators(vs)
                    crypto_hashplane.prewarm()
            except Exception:
                pass  # warm-up must never take anything down

        alive = [t for t in self._prestage_threads if t.is_alive()]
        t = threading.Thread(
            target=_warm, name="cs-prestage-next", daemon=True
        )
        t.start()
        alive.append(t)
        # lockfree: single-writer (FSM) list of daemon warm-up threads; stop() tolerates a stale snapshot — missing a just-spawned warmer only skips one bounded join of a side-effect-free daemon
        self._prestage_threads = alive
