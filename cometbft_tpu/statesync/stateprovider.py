"""Trusted state provider for statesync (statesync/stateprovider.go:29-56).

Builds the ``sm.State`` a node needs after restoring an app snapshot at
height H — validators at H/H+1, consensus params, app hash — plus the
commit FOR H, all verified through the light client (so a statesyncing
node trusts nothing but its configured trust root).
"""

from __future__ import annotations

from ..light import Client as LightClient
from ..light import TrustOptions
from ..state.state import State
from ..types.params import ConsensusParams


class StateProvider:
    """Light-client-backed provider (LightClientStateProvider)."""

    def __init__(
        self,
        chain_id: str,
        genesis,
        providers: list,
        trust_options: TrustOptions,
        initial_height: int = 1,
    ):
        if not providers:
            raise ValueError("statesync needs at least one light provider")
        self.chain_id = chain_id
        self.genesis = genesis
        self.initial_height = initial_height
        self.client = LightClient(
            chain_id=chain_id,
            trust_options=trust_options,
            primary=providers[0],
            witnesses=list(providers[1:]),
        )

    def app_hash(self, height: int) -> bytes:
        """App hash AFTER height = header(height+1).app_hash
        (stateprovider.go AppHash)."""
        lb = self.client.verify_light_block_at_height(height + 1)
        return lb.signed_header.header.app_hash

    def commit(self, height: int):
        """Verified commit for ``height`` (stateprovider.go Commit)."""
        lb = self.client.verify_light_block_at_height(height)
        return lb.signed_header.commit

    def state(self, height: int) -> State:
        """Trusted sm.State for resuming AFTER ``height``
        (stateprovider.go State): needs light blocks at H, H+1, H+2 —
        header H+1 proves app_hash(H), vals(H+2) gives next_validators of
        the resumed state."""
        cur = self.client.verify_light_block_at_height(height)
        nxt = self.client.verify_light_block_at_height(height + 1)
        nxt2 = self.client.verify_light_block_at_height(height + 2)
        params = (
            self.genesis.consensus_params
            if self.genesis is not None
            else ConsensusParams()
        )
        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=cur.height,
            # the signed header's own commit carries cur's BlockID
            last_block_id=cur.signed_header.commit.block_id,
            last_block_time_ns=cur.time_ns,
            validators=nxt.validator_set,
            next_validators=nxt2.validator_set,
            last_validators=cur.validator_set,
            last_height_validators_changed=nxt.height,
            consensus_params=params,
            last_height_consensus_params_changed=self.initial_height,
            app_hash=nxt.signed_header.header.app_hash,
            last_results_hash=nxt.signed_header.header.last_results_hash,
        )
