"""Chunk queue for one snapshot restore (statesync/chunks.go).

Chunks arrive out of order from multiple peers; the applier consumes them
strictly in index order. Bounded in memory (chunks are app-defined blobs;
the reference spools to a temp dir — here the queue holds at most
``chunks`` entries of one snapshot, the kvstore-scale case, and can be
swapped for file spooling transparently behind put/next)."""

from __future__ import annotations

import threading


class ChunkQueue:
    def __init__(self, n_chunks: int):
        self.n_chunks = n_chunks
        self._mtx = threading.Condition()
        self._chunks: dict[int, tuple[bytes, str]] = {}  # index -> (blob, peer)
        self._next = 0
        self._closed = False
        self._returned: set[int] = set()

    def put(self, index: int, chunk: bytes, peer_id: str) -> bool:
        """Store a fetched chunk; True if newly added."""
        with self._mtx:
            if self._closed or index >= self.n_chunks or index < self._next:
                return False
            if index in self._chunks:
                return False
            self._chunks[index] = (chunk, peer_id)
            self._mtx.notify_all()
            return True

    def next(self, timeout: float | None = None):
        """Blocking in-order consume: (index, chunk, peer_id) or None on
        close/timeout."""
        with self._mtx:
            if not self._mtx.wait_for(
                lambda: self._closed or self._next in self._chunks,
                timeout=timeout,
            ):
                return None
            if self._closed:
                return None
            idx = self._next
            chunk, peer = self._chunks.pop(idx)
            self._next += 1
            return idx, chunk, peer

    def retry(self, index: int) -> None:
        """Re-request from ``index`` on (refetch semantics of
        ApplySnapshotChunkResult.RETRY / refetch_chunks)."""
        with self._mtx:
            self._next = min(self._next, index)
            for i in list(self._chunks):
                if i >= index:
                    del self._chunks[i]

    def pending(self) -> list[int]:
        """Indexes not yet stored nor consumed (fetch targets)."""
        with self._mtx:
            return [
                i
                for i in range(self._next, self.n_chunks)
                if i not in self._chunks
            ]

    def done(self) -> bool:
        with self._mtx:
            return self._next >= self.n_chunks

    def close(self) -> None:
        with self._mtx:
            self._closed = True
            self._mtx.notify_all()
