"""Chunk queue for one snapshot restore (statesync/chunks.go:43-86).

Chunks arrive out of order from multiple peers; the applier consumes
them strictly in index order. Chunk BODIES are spooled to a per-restore
temp dir (one file per index, like the reference's newChunkQueue) so an
app snapshot larger than memory can restore: the queue holds only
(path, peer) bookkeeping in RAM. The directory is removed on close.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from ..libs import sync as libsync


DEFAULT_MAX_RETRIES = 8


class ChunkRetryLimitError(Exception):
    """One chunk index exceeded its retry cap: the snapshot is poisoned
    (an app that answers RETRY forever, or a chunk no peer can serve
    correctly) and the sync must fail CLEANLY instead of re-enqueueing
    the same index until the heat death of the deadline."""


class ChunkQueue:
    def __init__(
        self,
        n_chunks: int,
        temp_dir: str | None = None,
        max_retries: int = DEFAULT_MAX_RETRIES,
    ):
        self.n_chunks = n_chunks
        self.max_retries = max_retries
        self._dir = tempfile.mkdtemp(
            prefix="cometbft-tpu-statesync-", dir=temp_dir
        )
        self._mtx = libsync.Condition()
        self._peers: dict[int, str] = {}  # index -> sender peer
        self._next = 0
        self._closed = False
        self._returned: set[int] = set()
        self._retries: dict[int, int] = {}  # index -> retry() count

    def _path(self, index: int) -> str:
        return os.path.join(self._dir, str(index))

    def _accepts_locked(self, index: int) -> bool:
        return not (
            self._closed
            or index >= self.n_chunks
            or index < self._next
            or index in self._peers
        )

    def put(self, index: int, chunk: bytes, peer_id: str) -> bool:
        """Spool a fetched chunk to disk; True if newly added.

        The body WRITE happens outside the condition lock (cometlint
        CLNT009 discipline): chunks can be megabytes and a slow disk
        must not stall other peers' deliveries or wake-ups of the
        applier. Only bookkeeping and the atomic rename run under the
        lock; a racing duplicate loses at the re-check and removes its
        own spool file.
        """
        with self._mtx:
            if not self._accepts_locked(index):
                return False
        try:
            fd, tmp = tempfile.mkstemp(prefix=f"{index}.", dir=self._dir)
        except OSError:
            # close() may have removed the spool dir between our check
            # and here — equivalent to delivering after close
            return False
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(chunk)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        with self._mtx:
            if not self._accepts_locked(index):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return False
            try:
                os.replace(tmp, self._path(index))
            except OSError:
                return False
            self._peers[index] = peer_id
            self._mtx.notify_all()
            return True

    def next(self, timeout: float | None = None):
        """Blocking in-order consume: (index, chunk, peer_id) or None on
        close/timeout. The chunk file is deleted once loaded.

        The body READ happens after the lock is released — there is one
        consumer (the applier thread; ``retry`` runs on the same
        thread), so claiming index + peer under the lock is enough, and
        a multi-megabyte load never blocks ``put``.
        """
        with self._mtx:
            if not self._mtx.wait_for(
                lambda: self._closed or self._next in self._peers,
                timeout=timeout,
            ):
                return None
            if self._closed:
                return None
            idx = self._next
            peer = self._peers.pop(idx)
            # claim the index BEFORE releasing: a duplicate delivery of
            # idx during the unlocked read below must be rejected
            # (index < _next), not re-admitted into _peers
            self._next = idx + 1
        try:
            with open(self._path(idx), "rb") as f:
                chunk = f.read()
            os.remove(self._path(idx))
        except OSError:
            # spool file vanished (operator tampering / disk fault):
            # unclaim so pending() re-requests this index, and wake the
            # fetcher
            with self._mtx:
                self._next = min(self._next, idx)
                self._mtx.notify_all()
            return None
        return idx, chunk, peer

    def retry(self, index: int) -> None:
        """Re-request from ``index`` on (refetch semantics of
        ApplySnapshotChunkResult.RETRY / refetch_chunks).

        Raises :class:`ChunkRetryLimitError` once ``index`` has been
        retried ``max_retries`` times — a poisoned chunk (the app keeps
        rejecting every copy) must fail the sync cleanly so the syncer
        can reject the snapshot and rotate, not loop forever."""
        with self._mtx:
            count = self._retries.get(index, 0) + 1
            if count > self.max_retries:
                raise ChunkRetryLimitError(
                    f"chunk {index} retried {count - 1} times "
                    f"(cap {self.max_retries}) — poisoned snapshot"
                )
            self._retries[index] = count
            self._next = min(self._next, index)
            for i in list(self._peers):
                if i >= index:
                    del self._peers[i]
                    try:
                        os.remove(self._path(i))
                    except OSError:
                        pass

    def retry_count(self, index: int) -> int:
        with self._mtx:
            return self._retries.get(index, 0)

    def pending(self) -> list[int]:
        """Indexes not yet stored nor consumed (fetch targets)."""
        with self._mtx:
            return [
                i
                for i in range(self._next, self.n_chunks)
                if i not in self._peers
            ]

    def done(self) -> bool:
        with self._mtx:
            return self._next >= self.n_chunks

    def close(self) -> None:
        with self._mtx:
            self._closed = True
            self._mtx.notify_all()
        # directory teardown is pure disk work — outside the lock
        shutil.rmtree(self._dir, ignore_errors=True)
