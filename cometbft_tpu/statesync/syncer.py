"""Statesync syncer: snapshot discovery → offer → chunk restore → verify.

Reference: statesync/syncer.go:145-516. Flow per snapshot (best first):

  OfferSnapshot(app) → parallel chunk fetch from serving peers →
  ApplySnapshotChunk in order (RETRY/REJECT semantics) → verify the
  restored app hash against the light-client-verified header → hand back
  (state, commit) for the node to bootstrap stores and fall into
  blocksync/consensus.

Gray-failure hardening (PR 13): chunk fetching carries **per-peer
failure accounting** (:class:`ChunkFetchPlan`) — a request that times
out counts a consecutive failure against the peer that owned it, each
failure puts that peer into exponential backoff (base
``COMETBFT_TPU_STATESYNC_BACKOFF_S``, doubling, capped), and the
re-request **rotates** to the next serving peer.  The old behavior —
re-asking the same dead peer at fixed cadence forever — made a single
half-alive snapshot server fatal to the whole restore.  A successful
chunk delivery clears the sender's failure streak.

The fetch/apply control flow is also factored into non-blocking steps
(:meth:`Syncer.begin` / :meth:`Syncer.step_fetch` /
:meth:`Syncer.step_apply` / :meth:`Syncer.finish`), so the simnet
scheduler can drive a REAL statesync restore in virtual time (the
``statesync_join`` scenario) while the live node keeps the thread +
blocking-wait loop (:meth:`sync_any`) built from the same pieces.
``now_fn`` injects the clock both paths share.
"""

from __future__ import annotations

import threading
from ..libs import sync as libsync
import time

from ..abci import types as abci
from ..libs import health as libhealth
from .chunks import ChunkQueue, ChunkRetryLimitError
from .snapshots import Snapshot, SnapshotPool

_ENV_BACKOFF = "COMETBFT_TPU_STATESYNC_BACKOFF_S"
DEFAULT_BACKOFF_S = 1.0
BACKOFF_MAX_S = 30.0


def _backoff_base_s() -> float:
    return max(
        0.05, libhealth._env_float(_ENV_BACKOFF, DEFAULT_BACKOFF_S)
    )


class SyncError(Exception):
    pass


class RejectSnapshotError(SyncError):
    """App rejected this snapshot; try another (syncer.go errRejectSnapshot)."""


class RejectFormatError(SyncError):
    """App rejected the format; skip all snapshots of it."""


class RetryError(SyncError):
    pass


class RetrySnapshotError(SyncError):
    """App asked to re-offer the SAME snapshot (errRetrySnapshot)."""


class AppHashMismatchError(SyncError):
    """Restored app hash != trusted header's — the fatal outcome."""


class AbortError(SyncError):
    """App demanded the sync stop (syncer.go errAbort): terminal."""


class ChunkFetchPlan:
    """Per-restore chunk-request bookkeeping with peer rotation.

    Owned by ONE requester (the live fetch thread or the sim tick);
    ``note_delivery`` may be called from the reactor's receive path and
    only appends to a list (GIL-atomic), which the owner drains.
    """

    def __init__(
        self,
        chunk_timeout: float,
        backoff_base_s: float | None = None,
        backoff_max_s: float = BACKOFF_MAX_S,
    ):
        self.chunk_timeout = chunk_timeout
        self.backoff_base_s = (
            backoff_base_s if backoff_base_s is not None
            else _backoff_base_s()
        )
        self.backoff_max_s = backoff_max_s
        # index -> [last_request_time, attempts, peer]
        self._idx: dict[int, list] = {}
        # peer -> consecutive timed-out requests / backed-off-until
        self.failures: dict[str, int] = {}
        self._banned_until: dict[str, float] = {}
        self._delivered: list[str] = []  # drained by the owner
        self.rotations = 0

    def note_delivery(self, peer_id: str) -> None:
        """A chunk from ``peer_id`` was accepted into the queue (called
        from the reactor path — append only)."""
        self._delivered.append(peer_id)

    def _drain_deliveries(self) -> None:
        while self._delivered:
            peer = self._delivered.pop()
            self.failures.pop(peer, None)
            self._banned_until.pop(peer, None)

    def _pick_peer(self, index: int, attempts: int, peers: list, now: float):
        """Rotate: the attempt count walks the (sorted) peer list, and
        peers in backoff are skipped while any alternative exists."""
        usable = [
            p for p in peers if now >= self._banned_until.get(p, 0.0)
        ]
        pool = usable if usable else peers
        return pool[(index + attempts) % len(pool)]

    def due(self, pending: list, peers: list, now: float) -> list:
        """-> [(index, peer)] requests to fire now.  A pending index
        whose last request aged past ``chunk_timeout`` counts one
        consecutive failure against the peer that owned the request,
        puts that peer into exponential backoff, and rotates."""
        self._drain_deliveries()
        if not peers:
            return []
        out = []
        for index in pending:
            ent = self._idx.get(index)
            if ent is None:
                peer = self._pick_peer(index, 0, peers, now)
                self._idx[index] = [now, 0, peer]
                out.append((index, peer))
                continue
            last, attempts, owner = ent
            if now - last < self.chunk_timeout:
                continue
            # timed out: charge the owner, back it off, rotate
            fails = self.failures.get(owner, 0) + 1
            self.failures[owner] = fails
            self._banned_until[owner] = now + min(
                self.backoff_max_s,
                self.backoff_base_s * (2 ** (fails - 1)),
            )
            attempts += 1
            peer = self._pick_peer(index, attempts, peers, now)
            if peer != owner:
                self.rotations += 1
            self._idx[index] = [now, attempts, peer]
            out.append((index, peer))
        return out

    def forget(self, index: int) -> None:
        """Chunk applied (or rewound): drop its request bookkeeping so
        a later re-fetch starts fresh and immediate."""
        self._idx.pop(index, None)

    def forget_from(self, index: int) -> None:
        for i in list(self._idx):
            if i >= index:
                del self._idx[i]


class Syncer:
    def __init__(
        self,
        proxy_snapshot,  # ABCI snapshot connection
        proxy_query,  # ABCI query connection (Info for verify)
        state_provider,
        request_chunk,  # f(peer_id, snapshot, index) -> None (reactor send)
        chunk_timeout: float = 10.0,
        discovery_time: float = 5.0,
        now_fn=None,
        backoff_base_s: float | None = None,
    ):
        self.proxy_snapshot = proxy_snapshot
        self.proxy_query = proxy_query
        self.state_provider = state_provider
        self.request_chunk = request_chunk
        self.chunk_timeout = chunk_timeout
        self.discovery_time = discovery_time
        self._now = now_fn if now_fn is not None else time.monotonic
        self._backoff_base_s = backoff_base_s
        self.pool = SnapshotPool()
        self._chunk_queue: ChunkQueue | None = None
        self._current: Snapshot | None = None
        self._plan: ChunkFetchPlan | None = None
        self._applied = 0
        self._trusted_app_hash = b""
        self.rotations_total = 0  # chunk-peer rotations across restores
        self._mtx = libsync.Mutex("statesync.syncer._mtx")
        # Once ANY chunk has been applied the app's state is no longer
        # genesis: callers must not fall back to blocksync-from-genesis
        # (the reference fail-stops post-restore errors for this reason).
        self.applied_any = False

    # -- inputs from the reactor -------------------------------------------

    def add_snapshot(self, snapshot: Snapshot, peer_id: str) -> bool:
        return self.pool.add(snapshot, peer_id)

    def add_chunk(self, height, fmt, index, chunk: bytes, peer_id: str) -> bool:
        with self._mtx:
            cur, q, plan = self._current, self._chunk_queue, self._plan
        if cur is None or q is None:
            return False
        if height != cur.height or fmt != cur.format:
            return False
        added = q.put(index, chunk, peer_id)
        if added and plan is not None:
            # a delivered chunk clears the sender's failure streak
            plan.note_delivery(peer_id)
        return added

    def remove_peer(self, peer_id: str) -> None:
        self.pool.remove_peer(peer_id)

    # -- main entry (syncer.go:145 SyncAny) ---------------------------------

    def sync_any(self, deadline: float | None = None):
        """Try snapshots until one restores; returns (state, commit).

        Raises SyncError when no snapshot could be restored before the
        deadline (the node then falls back to blocksync from genesis).
        """
        end = None if deadline is None else time.monotonic() + deadline
        waited = 0.0
        retries: dict[tuple, int] = {}
        while True:
            snapshot = self.pool.best()
            if snapshot is None:
                if end is not None and time.monotonic() > end:
                    raise SyncError("no viable snapshots discovered")
                time.sleep(0.2)
                waited += 0.2
                if end is None and waited >= self.discovery_time:
                    raise SyncError("no snapshots discovered")
                continue
            try:
                return self._sync_one(snapshot)
            except RejectFormatError:
                self.pool.reject_format(snapshot.format)
            except (AppHashMismatchError, AbortError):
                raise  # terminal: never offer the app anything else
            except RetrySnapshotError:
                # app wants the SAME snapshot again; cap the retries so a
                # permanently failing app can't loop forever
                retries[snapshot.key()] = retries.get(snapshot.key(), 0) + 1
                if retries[snapshot.key()] >= 3:
                    self.pool.reject(snapshot)
            except (RejectSnapshotError, RetryError, SyncError):
                self.pool.reject(snapshot)

    # -- restore lifecycle (shared by the live loop and the sim steps) -----

    def begin(
        self, snapshot: Snapshot, provider_attempts: int = 20
    ) -> None:
        """Offer ``snapshot`` to the app and set up the chunk restore.
        The trusted app hash for this height must exist BEFORE
        restoring (fetched in :meth:`finish` against the same header).
        Snapshot.hash is an OPAQUE app identifier (abci spec) —
        comparing it to the chain app hash is the APP's job via
        RequestOfferSnapshot.app_hash, not ours.  ``provider_attempts``
        caps the real-time provider retries like :meth:`finish` — a
        virtual-time driver passes 1 and retries on its own clock."""
        trusted_app_hash = self._provider_call(
            lambda: self.state_provider.app_hash(snapshot.height),
            attempts=provider_attempts,
        )
        res = self.proxy_snapshot.offer_snapshot(
            abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(
                    height=snapshot.height,
                    format=snapshot.format,
                    chunks=snapshot.chunks,
                    hash=snapshot.hash,
                    metadata=snapshot.metadata,
                ),
                app_hash=trusted_app_hash,
            )
        )
        r = abci.OfferSnapshotResult
        if res.result == r.ABORT:
            raise AbortError("app aborted statesync")
        if res.result == r.REJECT_FORMAT:
            raise RejectFormatError()
        if res.result in (r.REJECT, r.REJECT_SENDER, r.UNKNOWN):
            raise RejectSnapshotError(f"offer result {res.result}")
        self._trusted_app_hash = trusted_app_hash
        with self._mtx:
            self._current = snapshot
            self._chunk_queue = ChunkQueue(snapshot.chunks)
            self._plan = ChunkFetchPlan(
                self.chunk_timeout, backoff_base_s=self._backoff_base_s
            )
        self._applied = 0

    def abort_restore(self) -> None:
        """Tear down the in-progress restore's queue/plan (idempotent)."""
        with self._mtx:
            q = self._chunk_queue
            plan = self._plan
            self._current = None
            self._chunk_queue = None
            self._plan = None
        if plan is not None:
            self.rotations_total += plan.rotations
        if q is not None:
            q.close()

    def step_fetch(self) -> int:
        """Fire the chunk requests that are due now (non-blocking); one
        pass of the fetch loop.  Returns the number sent."""
        with self._mtx:
            cur, q, plan = self._current, self._chunk_queue, self._plan
        if cur is None or q is None or plan is None:
            return 0
        peers = self.pool.peers_of(cur)
        sent = 0
        rot0 = plan.rotations
        for index, peer in plan.due(q.pending(), peers, self._now()):
            try:
                self.request_chunk(peer, cur, index)
                sent += 1
            except Exception:
                pass
        for _ in range(plan.rotations - rot0):
            # the defense acted: rotation abandoned a timing-out chunk
            # peer — annotate the flight ring (peer_evicted detector)
            libhealth.record(
                libhealth.EV_FAULT,
                a=libhealth.FAULT_PEER_EVICT,
                b=libhealth.PEER_EVICT_STATESYNC_ROTATE,
            )
        return sent

    def step_apply(self, block: float = 0.0) -> bool:
        """Apply every chunk available in order (waiting up to
        ``block`` seconds for the first); True once ALL chunks applied.
        Raises the syncer.go control-flow errors on app verdicts."""
        with self._mtx:
            cur, q, plan = self._current, self._chunk_queue, self._plan
        if cur is None or q is None:
            raise SyncError("no restore in progress")
        timeout = block
        while self._applied < cur.chunks:
            item = q.next(timeout=timeout)
            if item is None:
                return False
            timeout = 0.0  # only the first wait blocks
            index, chunk, peer = item
            if plan is not None:
                plan.forget(index)
            res = self.proxy_snapshot.apply_snapshot_chunk(
                abci.RequestApplySnapshotChunk(
                    index=index, chunk=chunk, sender=peer
                )
            )
            r = abci.ApplySnapshotChunkResult
            if res.result == r.ACCEPT:
                self._applied += 1
                self.applied_any = True
                continue
            if res.result == r.ABORT:
                raise AbortError("app aborted during chunk apply")
            if res.result == r.RETRY:
                try:
                    q.retry(index)
                except ChunkRetryLimitError as e:
                    # poisoned chunk: fail THIS snapshot cleanly; the
                    # caller rejects it and rotates to the next one
                    raise RejectSnapshotError(str(e)) from e
                # make the requester re-fire immediately: the per-index
                # throttle would otherwise eat the deadline
                if plan is not None:
                    plan.forget_from(index)
                self._applied = min(self._applied, index)
                continue
            if res.result == r.RETRY_SNAPSHOT:
                raise RetrySnapshotError()
            raise RejectSnapshotError(f"chunk apply result {res.result}")
        return True

    def finish(self, snapshot: Snapshot, provider_attempts: int = 20):
        """Verify the restored app against the trusted header
        (syncer.go:485) and fetch the bootstrap (state, commit)."""
        info = self.proxy_query.info(abci.RequestInfo())
        if info.last_block_app_hash != self._trusted_app_hash:
            raise AppHashMismatchError(
                f"restored app hash {info.last_block_app_hash.hex()} != "
                f"trusted {self._trusted_app_hash.hex()}"
            )
        if info.last_block_height != snapshot.height:
            raise AppHashMismatchError(
                f"restored app height {info.last_block_height} != "
                f"snapshot height {snapshot.height}"
            )
        # The chain tip may be exactly at the snapshot height: state()
        # needs light blocks H+1/H+2, which can lag the restore by a block
        # or two — retry instead of treating a young tip as fatal.
        state = self._provider_call(
            lambda: self.state_provider.state(snapshot.height),
            attempts=provider_attempts,
        )
        commit = self._provider_call(
            lambda: self.state_provider.commit(snapshot.height),
            attempts=provider_attempts,
        )
        state.app_version = info.app_version
        return state, commit

    def fetch_rotations(self) -> int:
        """Chunk-peer rotations across every restore (live plan
        included) — the observable the chunk-peer-failure scenario
        asserts on."""
        with self._mtx:
            plan = self._plan
        live = plan.rotations if plan is not None else 0
        return self.rotations_total + live

    def _sync_one(self, snapshot: Snapshot):
        """syncer.go:236 Sync: offer → fetch+apply → verify."""
        self.begin(snapshot)
        try:
            self._fetch_and_apply(snapshot)
        finally:
            self.abort_restore()
        return self.finish(snapshot)

    def _provider_call(self, fn, attempts: int = 20, delay: float = 0.5):
        """Light-provider fetches retry through transient misses (young
        chain tip, RPC hiccup); persistent failure surfaces as a SyncError
        so sync_any's control flow — not the caller's thread — handles it."""
        last: Exception | None = None
        for i in range(attempts):
            try:
                return fn()
            except Exception as e:  # light-client or provider/transport
                last = e
                if i + 1 < attempts:
                    time.sleep(delay)
        raise SyncError(f"state provider unavailable: {last}")

    # -- chunk plumbing -----------------------------------------------------

    def _fetch_and_apply(self, snapshot: Snapshot) -> None:
        q = self._chunk_queue
        stop = threading.Event()
        fetcher = threading.Thread(
            target=self._fetch_loop, args=(q, stop), daemon=True
        )
        fetcher.start()
        try:
            deadline = time.monotonic() + self.chunk_timeout * max(
                1, snapshot.chunks
            )
            while not self.step_apply(block=1.0):
                if time.monotonic() > deadline:
                    raise RetryError("timed out fetching chunks")
        finally:
            stop.set()
            fetcher.join(timeout=2)

    def _fetch_loop(self, q: ChunkQueue, stop) -> None:
        """Requester thread (syncer.go:415 fetchChunks, collapsed to one
        — chunk application is serial anyway and peers stream
        responses); each pass fires the due requests under the plan's
        rotation + backoff accounting."""
        while not stop.is_set() and not q.done():
            if self.step_fetch() == 0:
                time.sleep(0.1)
            else:
                time.sleep(0.02)
