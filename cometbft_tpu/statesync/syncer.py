"""Statesync syncer: snapshot discovery → offer → chunk restore → verify.

Reference: statesync/syncer.go:145-516. Flow per snapshot (best first):

  OfferSnapshot(app) → parallel chunk fetch from serving peers →
  ApplySnapshotChunk in order (RETRY/REJECT semantics) → verify the
  restored app hash against the light-client-verified header → hand back
  (state, commit) for the node to bootstrap stores and fall into
  blocksync/consensus.
"""

from __future__ import annotations

import threading
from ..libs import sync as libsync
import time

from ..abci import types as abci
from .chunks import ChunkQueue
from .snapshots import Snapshot, SnapshotPool


class SyncError(Exception):
    pass


class RejectSnapshotError(SyncError):
    """App rejected this snapshot; try another (syncer.go errRejectSnapshot)."""


class RejectFormatError(SyncError):
    """App rejected the format; skip all snapshots of it."""


class RetryError(SyncError):
    pass


class RetrySnapshotError(SyncError):
    """App asked to re-offer the SAME snapshot (errRetrySnapshot)."""


class AppHashMismatchError(SyncError):
    """Restored app hash != trusted header's — the fatal outcome."""


class AbortError(SyncError):
    """App demanded the sync stop (syncer.go errAbort): terminal."""


class Syncer:
    def __init__(
        self,
        proxy_snapshot,  # ABCI snapshot connection
        proxy_query,  # ABCI query connection (Info for verify)
        state_provider,
        request_chunk,  # f(peer_id, snapshot, index) -> None (reactor send)
        chunk_timeout: float = 10.0,
        discovery_time: float = 5.0,
    ):
        self.proxy_snapshot = proxy_snapshot
        self.proxy_query = proxy_query
        self.state_provider = state_provider
        self.request_chunk = request_chunk
        self.chunk_timeout = chunk_timeout
        self.discovery_time = discovery_time
        self.pool = SnapshotPool()
        self._chunk_queue: ChunkQueue | None = None
        self._current: Snapshot | None = None
        self._mtx = libsync.Mutex("statesync.syncer._mtx")
        # Once ANY chunk has been applied the app's state is no longer
        # genesis: callers must not fall back to blocksync-from-genesis
        # (the reference fail-stops post-restore errors for this reason).
        self.applied_any = False
        self._requested: dict[int, float] = {}  # chunk index -> last request

    # -- inputs from the reactor -------------------------------------------

    def add_snapshot(self, snapshot: Snapshot, peer_id: str) -> bool:
        return self.pool.add(snapshot, peer_id)

    def add_chunk(self, height, fmt, index, chunk: bytes, peer_id: str) -> bool:
        with self._mtx:
            cur, q = self._current, self._chunk_queue
        if cur is None or q is None:
            return False
        if height != cur.height or fmt != cur.format:
            return False
        return q.put(index, chunk, peer_id)

    def remove_peer(self, peer_id: str) -> None:
        self.pool.remove_peer(peer_id)

    # -- main entry (syncer.go:145 SyncAny) ---------------------------------

    def sync_any(self, deadline: float | None = None):
        """Try snapshots until one restores; returns (state, commit).

        Raises SyncError when no snapshot could be restored before the
        deadline (the node then falls back to blocksync from genesis).
        """
        end = None if deadline is None else time.monotonic() + deadline
        waited = 0.0
        retries: dict[tuple, int] = {}
        while True:
            snapshot = self.pool.best()
            if snapshot is None:
                if end is not None and time.monotonic() > end:
                    raise SyncError("no viable snapshots discovered")
                time.sleep(0.2)
                waited += 0.2
                if end is None and waited >= self.discovery_time:
                    raise SyncError("no snapshots discovered")
                continue
            try:
                return self._sync_one(snapshot)
            except RejectFormatError:
                self.pool.reject_format(snapshot.format)
            except (AppHashMismatchError, AbortError):
                raise  # terminal: never offer the app anything else
            except RetrySnapshotError:
                # app wants the SAME snapshot again; cap the retries so a
                # permanently failing app can't loop forever
                retries[snapshot.key()] = retries.get(snapshot.key(), 0) + 1
                if retries[snapshot.key()] >= 3:
                    self.pool.reject(snapshot)
            except (RejectSnapshotError, RetryError, SyncError):
                self.pool.reject(snapshot)

    def _sync_one(self, snapshot: Snapshot):
        """syncer.go:236 Sync: offer → fetch+apply → verify."""
        # The trusted app hash for this height must exist BEFORE restoring.
        # Snapshot.hash is an OPAQUE app identifier (abci spec) — comparing
        # it to the chain app hash is the APP's job via
        # RequestOfferSnapshot.app_hash, not ours.
        trusted_app_hash = self._provider_call(
            lambda: self.state_provider.app_hash(snapshot.height)
        )

        res = self.proxy_snapshot.offer_snapshot(
            abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(
                    height=snapshot.height,
                    format=snapshot.format,
                    chunks=snapshot.chunks,
                    hash=snapshot.hash,
                    metadata=snapshot.metadata,
                ),
                app_hash=trusted_app_hash,
            )
        )
        r = abci.OfferSnapshotResult
        if res.result == r.ABORT:
            raise AbortError("app aborted statesync")
        if res.result == r.REJECT_FORMAT:
            raise RejectFormatError()
        if res.result in (r.REJECT, r.REJECT_SENDER, r.UNKNOWN):
            raise RejectSnapshotError(f"offer result {res.result}")

        with self._mtx:
            self._current = snapshot
            self._chunk_queue = ChunkQueue(snapshot.chunks)
        try:
            self._fetch_and_apply(snapshot)
        finally:
            with self._mtx:
                q = self._chunk_queue
                self._current = None
                self._chunk_queue = None
            if q is not None:
                q.close()

        # verify restored app against the trusted header (syncer.go:485)
        info = self.proxy_query.info(abci.RequestInfo())
        if info.last_block_app_hash != trusted_app_hash:
            raise AppHashMismatchError(
                f"restored app hash {info.last_block_app_hash.hex()} != "
                f"trusted {trusted_app_hash.hex()}"
            )
        if info.last_block_height != snapshot.height:
            raise AppHashMismatchError(
                f"restored app height {info.last_block_height} != "
                f"snapshot height {snapshot.height}"
            )
        # The chain tip may be exactly at the snapshot height: state()
        # needs light blocks H+1/H+2, which can lag the restore by a block
        # or two — retry instead of treating a young tip as fatal.
        state = self._provider_call(
            lambda: self.state_provider.state(snapshot.height)
        )
        commit = self._provider_call(
            lambda: self.state_provider.commit(snapshot.height)
        )
        state.app_version = info.app_version
        return state, commit

    def _provider_call(self, fn, attempts: int = 20, delay: float = 0.5):
        """Light-provider fetches retry through transient misses (young
        chain tip, RPC hiccup); persistent failure surfaces as a SyncError
        so sync_any's control flow — not the caller's thread — handles it."""
        last: Exception | None = None
        for _ in range(attempts):
            try:
                return fn()
            except Exception as e:  # light-client or provider/transport
                last = e
                time.sleep(delay)
        raise SyncError(f"state provider unavailable: {last}")

    # -- chunk plumbing -----------------------------------------------------

    def _fetch_and_apply(self, snapshot: Snapshot) -> None:
        q = self._chunk_queue
        stop = threading.Event()
        fetcher = threading.Thread(
            target=self._fetch_loop, args=(snapshot, q, stop), daemon=True
        )
        fetcher.start()
        try:
            applied = 0
            deadline = time.monotonic() + self.chunk_timeout * max(
                1, snapshot.chunks
            )
            while applied < snapshot.chunks:
                item = q.next(timeout=1.0)
                if item is None:
                    if time.monotonic() > deadline:
                        raise RetryError("timed out fetching chunks")
                    continue
                index, chunk, peer = item
                res = self.proxy_snapshot.apply_snapshot_chunk(
                    abci.RequestApplySnapshotChunk(
                        index=index, chunk=chunk, sender=peer
                    )
                )
                r = abci.ApplySnapshotChunkResult
                if res.result == r.ACCEPT:
                    applied += 1
                    self.applied_any = True
                    continue
                if res.result == r.ABORT:
                    raise AbortError("app aborted during chunk apply")
                if res.result == r.RETRY:
                    q.retry(index)
                    # make the fetcher re-request immediately: its
                    # per-index throttle would otherwise eat the deadline
                    for i in list(self._requested):
                        if i >= index:
                            del self._requested[i]
                    applied = min(applied, index)
                    continue
                if res.result == r.RETRY_SNAPSHOT:
                    raise RetrySnapshotError()
                raise RejectSnapshotError(f"chunk apply result {res.result}")
        finally:
            stop.set()
            fetcher.join(timeout=2)

    def _fetch_loop(self, snapshot: Snapshot, q: ChunkQueue, stop) -> None:
        """Round-robin pending chunk requests over serving peers
        (syncer.go:415 fetchChunks, collapsed to one requester thread —
        chunk application is serial anyway and peers stream responses)."""
        self._requested.clear()
        requested = self._requested
        while not stop.is_set() and not q.done():
            peers = self.pool.peers_of(snapshot)
            if not peers:
                time.sleep(0.2)
                continue
            now = time.monotonic()
            for n, index in enumerate(q.pending()):
                last = requested.get(index, 0.0)
                if now - last < self.chunk_timeout:
                    continue
                peer = peers[(index + int(now)) % len(peers)]
                try:
                    self.request_chunk(peer, snapshot, index)
                    requested[index] = now
                except Exception:
                    pass
            time.sleep(0.1)
