"""Statesync: snapshot bootstrap of fresh nodes over channels 0x60/0x61.

Reference: /root/reference/statesync/ (syncer, reactor, chunks, snapshots,
stateprovider).
"""

from .chunks import ChunkQueue
from .messages import CHUNK_CHANNEL, SNAPSHOT_CHANNEL
from .reactor import StatesyncReactor
from .snapshots import Snapshot, SnapshotPool
from .stateprovider import StateProvider
from .syncer import (
    AbortError,
    AppHashMismatchError,
    RejectFormatError,
    RejectSnapshotError,
    SyncError,
    Syncer,
)

__all__ = [
    "AbortError",
    "AppHashMismatchError",
    "ChunkQueue",
    "CHUNK_CHANNEL",
    "RejectFormatError",
    "RejectSnapshotError",
    "SNAPSHOT_CHANNEL",
    "Snapshot",
    "SnapshotPool",
    "StateProvider",
    "StatesyncReactor",
    "SyncError",
    "Syncer",
]
