"""Snapshot pool: deduped peer-advertised snapshots ranked for offering.

Reference: statesync/snapshots.go — snapshots keyed by
(height, format, chunks, hash); tracks which peers can serve each so
chunk fetches spread across providers and peer failures prune cleanly.
"""

from __future__ import annotations

from ..libs import sync as libsync
from dataclasses import dataclass


@dataclass(frozen=True)
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""

    def key(self) -> tuple:
        return (self.height, self.format, self.chunks, self.hash)


class SnapshotPool:
    def __init__(self):
        self._mtx = libsync.Mutex("statesync.snapshots._mtx")
        self._snapshots: dict[tuple, Snapshot] = {}
        self._peers: dict[tuple, set[str]] = {}
        self._rejected: set[tuple] = set()

    def add(self, snapshot: Snapshot, peer_id: str) -> bool:
        """Returns True if the snapshot is new."""
        with self._mtx:
            key = snapshot.key()
            if key in self._rejected:
                return False
            new = key not in self._snapshots
            self._snapshots[key] = snapshot
            self._peers.setdefault(key, set()).add(peer_id)
            return new

    def best(self) -> Snapshot | None:
        """Highest height first, then newest format (snapshots.go Best)."""
        with self._mtx:
            if not self._snapshots:
                return None
            return max(
                self._snapshots.values(), key=lambda s: (s.height, s.format)
            )

    def peers_of(self, snapshot: Snapshot) -> list[str]:
        with self._mtx:
            return sorted(self._peers.get(snapshot.key(), ()))

    def reject(self, snapshot: Snapshot) -> None:
        with self._mtx:
            key = snapshot.key()
            self._rejected.add(key)
            self._snapshots.pop(key, None)
            self._peers.pop(key, None)

    def reject_format(self, fmt: int) -> None:
        with self._mtx:
            for key in [k for k, s in self._snapshots.items() if s.format == fmt]:
                self._rejected.add(key)
                self._snapshots.pop(key)
                self._peers.pop(key, None)

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            for key in list(self._peers):
                self._peers[key].discard(peer_id)
                if not self._peers[key]:
                    del self._peers[key]
                    self._snapshots.pop(key, None)
