"""Statesync p2p reactor: snapshot/chunk channels 0x60/0x61.

Reference: statesync/reactor.go. Two roles:

* server — every node answers SnapshotsRequest from the app's
  ListSnapshots and ChunkRequest from LoadSnapshotChunk (capped sizes);
* client — a statesyncing node broadcasts SnapshotsRequest on peer add
  and forwards responses into its Syncer.
"""

from __future__ import annotations

from ..abci import types as abci
from ..p2p.base_reactor import ChannelDescriptor, Reactor
from ..types import serialization as ser
from .messages import (
    CHUNK_CHANNEL,
    SNAPSHOT_CHANNEL,
    ChunkRequestMessage,
    ChunkResponseMessage,
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
)
from .snapshots import Snapshot

_MAX_SNAPSHOTS_ADVERTISED = 10  # reactor.go recentSnapshots


class StatesyncReactor(Reactor):
    def __init__(self, proxy_snapshot, syncer=None):
        super().__init__("statesync-reactor")
        self.proxy_snapshot = proxy_snapshot
        self.syncer = syncer  # None on nodes that aren't statesyncing

    def get_channels(self):
        return [
            ChannelDescriptor(
                id=SNAPSHOT_CHANNEL,
                priority=5,
                send_queue_capacity=10,
                recv_message_capacity=4 << 20,
            ),
            ChannelDescriptor(
                id=CHUNK_CHANNEL,
                priority=3,
                send_queue_capacity=4,
                recv_message_capacity=16 << 20,
            ),
        ]

    def add_peer(self, peer) -> None:
        if self.syncer is not None:
            peer.try_send(
                SNAPSHOT_CHANNEL, ser.dumps(SnapshotsRequestMessage())
            )

    def remove_peer(self, peer, reason) -> None:
        if self.syncer is not None:
            self.syncer.remove_peer(peer.id)

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        msg = ser.loads(msg_bytes)
        if ch_id == SNAPSHOT_CHANNEL:
            self._receive_snapshot(peer, msg)
        elif ch_id == CHUNK_CHANNEL:
            self._receive_chunk(peer, msg)

    # -- snapshot channel ----------------------------------------------------

    def _receive_snapshot(self, peer, msg) -> None:
        if isinstance(msg, SnapshotsRequestMessage):
            res = self.proxy_snapshot.list_snapshots(
                abci.RequestListSnapshots()
            )
            for s in (res.snapshots or [])[:_MAX_SNAPSHOTS_ADVERTISED]:
                peer.try_send(
                    SNAPSHOT_CHANNEL,
                    ser.dumps(
                        SnapshotsResponseMessage(
                            height=s.height,
                            format=s.format,
                            chunks=s.chunks,
                            hash=s.hash,
                            metadata=s.metadata,
                        )
                    ),
                )
        elif isinstance(msg, SnapshotsResponseMessage):
            if self.syncer is not None:
                self.syncer.add_snapshot(
                    Snapshot(
                        height=msg.height,
                        format=msg.format,
                        chunks=msg.chunks,
                        hash=msg.hash,
                        metadata=msg.metadata,
                    ),
                    peer.id,
                )

    # -- chunk channel ---------------------------------------------------------

    def _receive_chunk(self, peer, msg) -> None:
        if isinstance(msg, ChunkRequestMessage):
            res = self.proxy_snapshot.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(
                    height=msg.height, format=msg.format, chunk=msg.index
                )
            )
            peer.try_send(
                CHUNK_CHANNEL,
                ser.dumps(
                    ChunkResponseMessage(
                        height=msg.height,
                        format=msg.format,
                        index=msg.index,
                        chunk=res.chunk or b"",
                        missing=not res.chunk,
                    )
                ),
            )
        elif isinstance(msg, ChunkResponseMessage):
            if self.syncer is not None and not msg.missing:
                self.syncer.add_chunk(
                    msg.height, msg.format, msg.index, msg.chunk, peer.id
                )

    # -- outgoing chunk requests (used by the Syncer) -------------------------

    def request_chunk(self, peer_id: str, snapshot, index: int) -> None:
        if self.switch is None:
            return
        peer = self.switch.get_peer(peer_id)
        if peer is not None:
            peer.try_send(
                CHUNK_CHANNEL,
                ser.dumps(
                    ChunkRequestMessage(
                        height=snapshot.height,
                        format=snapshot.format,
                        index=index,
                    )
                ),
            )
