"""Statesync wire messages, channels 0x60/0x61 (statesync/reactor.go:21-23,
proto/tendermint/statesync)."""

from __future__ import annotations

from dataclasses import dataclass

from ..types import serialization as ser

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61


@dataclass(slots=True)
class SnapshotsRequestMessage:
    pass


@dataclass(slots=True)
class SnapshotsResponseMessage:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


@dataclass(slots=True)
class ChunkRequestMessage:
    height: int = 0
    format: int = 0
    index: int = 0


@dataclass(slots=True)
class ChunkResponseMessage:
    height: int = 0
    format: int = 0
    index: int = 0
    chunk: bytes = b""
    missing: bool = False


ser.codec.register(
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
    ChunkRequestMessage,
    ChunkResponseMessage,
)
