"""Block-sync: fast catch-up by downloading committed blocks
(reference: blocksync/)."""

from .pool import BlockPool  # noqa: F401
from .reactor import BlocksyncReactor  # noqa: F401
