"""Blocksync reactor (reference: blocksync/reactor.go, channel 0x40).

Serves stored blocks to catching-up peers and, while syncing, drives the
pool: request blocks → verify the first of each pair via the second's
LastCommit (VerifyCommitLight — the batched hot path, reactor.go:447) →
ApplyBlock → switch to consensus when caught up (reactor.go:383-386).
"""

from __future__ import annotations

import threading
import time

from ..libs import netstats as libnetstats
from ..libs import trace as libtrace
from ..p2p.base_reactor import ChannelDescriptor, Reactor
from ..types import serialization as ser
from ..types.validation import VerificationError, verify_commit_light
from .messages import (
    BlockRequestMessage,
    BlockResponseMessage,
    NoBlockResponseMessage,
    StatusRequestMessage,
    StatusResponseMessage,
)
from .pool import BlockPool

BLOCKSYNC_CHANNEL = 0x40
STATUS_INTERVAL = 5.0
SWITCH_TO_CONSENSUS_INTERVAL = 1.0


class BlocksyncReactor(Reactor):
    def __init__(
        self,
        state,  # sm.State at boot
        block_exec,
        block_store,
        block_sync: bool,
        consensus_reactor=None,  # for switch_to_consensus
        min_recv_rate: int | None = None,
        now_fn=None,
    ):
        super().__init__("blocksync-reactor")
        self.initial_state = state
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.block_sync = block_sync
        self.consensus_reactor = consensus_reactor
        self.min_recv_rate = min_recv_rate
        # monotonic-seconds source for the pool loop's status/timeout
        # cadence; the simnet substitutes its virtual clock and drives
        # _pool_step from its scheduler instead of the pool thread
        self._now = now_fn if now_fn is not None else time.monotonic
        self.sim_driven = False
        self.pool = BlockPool(
            block_store.height() + 1,
            send_request=self._send_block_request,
            on_peer_error=self._on_pool_peer_error,
            min_recv_rate=min_recv_rate,
            now_fn=now_fn,
        )
        self.synced = threading.Event()
        self._n_synced = 0
        # _pool_step cadence state (locals of the reference's
        # poolRoutine; -inf = the first step broadcasts/checks
        # immediately on ANY clock, including the sim clock at t~0)
        self._last_status = float("-inf")
        self._last_switch_check = float("-inf")
        self._caught_up_since: float | None = None
        if not block_sync:
            self.synced.set()

    def get_channels(self):
        return [
            ChannelDescriptor(
                id=BLOCKSYNC_CHANNEL,
                priority=5,
                send_queue_capacity=1000,
                recv_message_capacity=50 * 1024 * 1024,
            )
        ]

    def on_start(self) -> None:
        if self.block_sync and not self.sim_driven:
            threading.Thread(
                target=self._pool_routine, name="blocksync-pool", daemon=True
            ).start()

    def switch_to_block_sync(self, state) -> None:
        """Statesync finished: start block-syncing FROM the restored state
        (reactor.go SwitchToBlockSync). Rebuilds the pool at the restored
        height — the one chosen at construction assumed genesis."""
        self.state = state
        self.block_sync = True
        self.synced.clear()
        self._last_status = float("-inf")
        self._last_switch_check = float("-inf")
        self._caught_up_since = None
        self.pool = BlockPool(
            state.last_block_height + 1,
            send_request=self._send_block_request,
            on_peer_error=self._on_pool_peer_error,
            min_recv_rate=self.min_recv_rate,
            now_fn=None if self._now is time.monotonic else self._now,
        )
        # re-announce status so peers learn we now need blocks
        self._broadcast_status_request()
        if not self.sim_driven:
            threading.Thread(
                target=self._pool_routine, name="blocksync-pool", daemon=True
            ).start()

    # -- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer) -> None:
        peer.try_send(
            BLOCKSYNC_CHANNEL,
            ser.dumps(
                StatusResponseMessage(
                    height=self.block_store.height(),
                    base=self.block_store.base(),
                )
            ),
        )

    def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    # -- receive (reactor.go Receive) --------------------------------------

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        msg = ser.loads(msg_bytes)
        if isinstance(msg, StatusRequestMessage):
            peer.try_send(
                BLOCKSYNC_CHANNEL,
                ser.dumps(
                    StatusResponseMessage(
                        height=self.block_store.height(),
                        base=self.block_store.base(),
                    )
                ),
            )
        elif isinstance(msg, StatusResponseMessage):
            self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, BlockRequestMessage):
            block = self.block_store.load_block(msg.height)
            if block is None:
                peer.try_send(
                    BLOCKSYNC_CHANNEL,
                    ser.dumps(NoBlockResponseMessage(height=msg.height)),
                )
                return
            ext = self.block_store.load_block_extended_commit(msg.height)
            peer.try_send(
                BLOCKSYNC_CHANNEL,
                ser.dumps(BlockResponseMessage(block=block, ext_commit=ext)),
            )
        elif isinstance(msg, BlockResponseMessage):
            # one-hop serve latency of a synced block (provenance stamp)
            libnetstats.observe_propagation("block", msg.block.header.height)
            self.pool.add_block(
                peer.id, msg.block, msg.ext_commit, size=len(msg_bytes)
            )
        elif isinstance(msg, NoBlockResponseMessage):
            pass  # the requester will time out and re-pick

    # -- pool plumbing -----------------------------------------------------

    def _send_block_request(self, height: int, peer_id: str) -> None:
        if self.switch is None:
            return
        peer = self.switch.get_peer(peer_id)
        if peer is not None:
            peer.try_send(
                BLOCKSYNC_CHANNEL, ser.dumps(BlockRequestMessage(height))
            )

    def _on_pool_peer_error(self, peer_id: str, reason) -> None:
        if self.switch is None:
            return
        peer = self.switch.get_peer(peer_id)
        if peer is not None:
            self.switch.stop_and_remove_peer(peer, reason)

    def _broadcast_status_request(self) -> None:
        if self.switch is not None:
            self.switch.try_broadcast(
                BLOCKSYNC_CHANNEL, ser.dumps(StatusRequestMessage())
            )

    # -- the sync loop (reactor.go:272 poolRoutine) ------------------------

    # _pool_step outcomes
    STEP_IDLE = 0  # nothing applied; caller may sleep a beat
    STEP_APPLIED = 1  # a block landed; step again immediately
    STEP_SWITCHED = 2  # handed off to consensus; the loop is done

    def _pool_routine(self) -> None:
        while not self.quit_event().is_set():
            outcome = self._pool_step(self._now())
            if outcome == self.STEP_SWITCHED:
                return
            if outcome == self.STEP_IDLE:
                time.sleep(0.05)

    def _pool_step(self, now: float) -> int:
        """One iteration of the sync loop (also the simnet tick: the
        scheduler calls it with virtual ``now``)."""
        if now - self._last_status > STATUS_INTERVAL:
            self._broadcast_status_request()
            self._last_status = now
        self.pool.make_requests()

        # Try to verify+apply the next block.
        first, first_ext, second = self.pool.peek_two_blocks()
        if first is not None and second is not None:
            try:
                self._apply_first(first, first_ext, second)
            except Exception:
                import traceback

                traceback.print_exc()
                raise  # local apply failure: fail-stop (reference panics)
            return self.STEP_APPLIED

        # Caught up? Need a stable signal before switching.
        if now - self._last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
            self._last_switch_check = now
            if self.pool.is_caught_up():
                if self._caught_up_since is None:
                    self._caught_up_since = now
                elif (
                    now - self._caught_up_since
                    > SWITCH_TO_CONSENSUS_INTERVAL
                ):
                    self._switch_to_consensus()
                    return self.STEP_SWITCHED
            else:
                self._caught_up_since = None
        return self.STEP_IDLE

    def _apply_first(self, first, first_ext, second) -> None:
        """reactor.go:447: first's validity is proven by second.LastCommit."""
        from ..types import BlockID, PartSet

        t0 = time.perf_counter() if libtrace.enabled() else 0.0
        parts = PartSet.from_data(ser.dumps(first))
        first_id = BlockID(first.hash(), parts.header)
        try:
            if second.last_commit is None:
                raise VerificationError("second block missing last commit")
            if second.last_commit.block_id != first_id:
                raise VerificationError("second block commits a fork?")
            from ..libs import devledger

            with devledger.caller_class("blocksync"):
                verify_commit_light(
                    self.state.chain_id,
                    self.state.validators,
                    first_id,
                    first.header.height,
                    second.last_commit,
                )  # ◄◄ HOT BATCH (types/validation.go via TPU verifier)
        except (VerificationError, ValueError):
            # Either block may be the forged one: redo BOTH and punish both
            # serving peers (reactor.go:447-470).
            if t0:
                libtrace.event(
                    "blocksync.reject", height=first.header.height
                )
            self.pool.redo_request(first.header.height)
            self.pool.redo_request(second.header.height)
            return
        seen_commit = second.last_commit
        if self.block_store.height() < first.header.height:
            if first_ext is not None and self.state.consensus_params.vote_extensions_enabled(
                first.header.height
            ):
                self.block_store.save_block_with_extended_commit(
                    first, parts, first_ext
                )
            else:
                self.block_store.save_block(first, parts, seen_commit)
        # ApplyBlock failure on a commit-verified block is a LOCAL fault —
        # fail-stop like the reference's panic, never punish the peer.
        self.state = self.block_exec.apply_block(self.state, first_id, first)
        if t0:
            libtrace.event(
                "blocksync.apply",
                height=first.header.height,
                lanes=len(seen_commit.signatures),
                dur_ns=int((time.perf_counter() - t0) * 1e9),
            )
        self._n_synced += 1
        self.pool.pop_request()

    def _switch_to_consensus(self) -> None:
        """reactor.go:383-386 → consensus/reactor.go:109."""
        if self.logger is not None:
            self.logger.info(
                "switching to consensus",
                height=self.block_store.height(),
                blocks_synced=self._n_synced,
            )
        self.pool.stop()
        self.synced.set()
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(
                self.state, skip_wal=self._n_synced > 0
            )
