"""Block download scheduler (reference: blocksync/pool.go:63-683).

Work-stealing pool: one requester per in-flight height, each picking an
available peer and re-picking (with the old peer banned for that height)
on timeout or bad data. The reactor consumes blocks strictly in order via
``peek_two_blocks`` → verify → ``pop_request``.
"""

from __future__ import annotations

from ..libs import sync as libsync
import time

REQUEST_WINDOW = 20  # max heights in flight (pool.go maxPendingRequests≈)
REQUEST_TIMEOUT = 15.0  # per-height peer response timeout
# Minimum bytes/sec a peer with pending requests must deliver, else it is
# evicted (pool.go:133-160 minRecvRate, 7680 B/s there). A peer trickling
# bytes under the request timeout would otherwise never be caught.
MIN_RECV_RATE = 7680
RATE_GRACE = 2.0  # monitor must run this long before a verdict


class _Peer:
    def __init__(self, peer_id: str, base: int, height: int):
        self.id = peer_id
        self.base = base
        self.height = height
        self.num_pending = 0
        self.timeout_count = 0
        self.recv_monitor = None  # armed while requests are pending
        self.monitor_start = 0.0

    def arm_monitor(self, now: float) -> None:
        """(Re)start rate tracking when pending goes 0 -> 1
        (pool.go resetMonitor). ``now`` comes from the pool's clock so
        the grace window stays on ONE timeline (the simnet drives the
        pool on virtual time)."""
        from ..libs.flowrate import Monitor

        self.recv_monitor = Monitor(window=5.0)
        self.monitor_start = now


class _Requester:
    def __init__(self, height: int):
        self.height = height
        self.peer_id: str | None = None
        self.block = None
        self.ext_commit = None
        self.request_time = 0.0
        self.banned: set[str] = set()


class BlockPool:
    def __init__(self, start_height: int, send_request, on_peer_error=None,
                 min_recv_rate: int | None = None, now_fn=None):
        """``send_request(height, peer_id)`` dispatches a BlockRequest;
        ``on_peer_error(peer_id, reason)`` reports misbehaving peers.
        ``min_recv_rate``: B/s floor for peers with pending requests
        (0 disables; default MIN_RECV_RATE). ``now_fn``: monotonic
        seconds source for request timeouts (the simnet passes its
        virtual clock; default wall clock)."""
        self._mtx = libsync.RLock("blocksync.pool._mtx")
        self._now = now_fn if now_fn is not None else time.monotonic
        self.height = start_height  # next height to apply
        self.send_request = send_request
        self.on_peer_error = on_peer_error or (lambda pid, r: None)
        self.min_recv_rate = (
            MIN_RECV_RATE if min_recv_rate is None else min_recv_rate
        )
        self.peers: dict[str, _Peer] = {}
        self.requesters: dict[int, _Requester] = {}
        self.max_peer_height = 0
        self._running = True

    # -- peers -------------------------------------------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        """StatusResponse from a peer (pool.go SetPeerRange)."""
        with self._mtx:
            p = self.peers.get(peer_id)
            if p is None:
                p = _Peer(peer_id, base, height)
                self.peers[peer_id] = p
            else:
                p.base, p.height = base, height
            self.max_peer_height = max(self.max_peer_height, height)

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self.peers.pop(peer_id, None)
            for r in self.requesters.values():
                if r.peer_id == peer_id and r.block is None:
                    r.peer_id = None  # re-dispatch
            self.max_peer_height = max(
                (p.height for p in self.peers.values()), default=0
            )

    def _pick_peer(self, height: int, banned: set[str]) -> _Peer | None:
        candidates = [
            p
            for p in self.peers.values()
            if p.base <= height <= p.height
            and p.id not in banned
            and p.num_pending < 10
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda p: p.num_pending)

    # -- scheduling (call periodically from the reactor loop) --------------

    def _evict_slow_peers(self, now: float) -> None:
        """Evict peers trickling below min_recv_rate while owing blocks
        (pool.go removeTimedoutPeers' rate branch)."""
        if self.min_recv_rate <= 0:
            return
        for peer in list(self.peers.values()):
            if peer.num_pending <= 0 or peer.recv_monitor is None:
                continue
            if now - peer.monitor_start < RATE_GRACE:
                continue
            rate = peer.recv_monitor.rate()
            # rate == 0 means nothing measured YET (the monitor is fed on
            # block receipt, and a first large block can legitimately
            # take longer than the grace period): only judge peers that
            # have delivered something slowly — pool.go's "curRate can
            # be 0 on start" guard. Fully silent peers fall to the
            # REQUEST_TIMEOUT path instead.
            if rate > 0 and rate < self.min_recv_rate:
                self.on_peer_error(
                    peer.id,
                    f"slow peer: {rate:.0f} B/s < {self.min_recv_rate} B/s "
                    f"with {peer.num_pending} pending",
                )
                self.remove_peer(peer.id)

    def make_requests(self) -> None:
        with self._mtx:
            if not self._running:
                return
            self._evict_slow_peers(self._now())
            for h in range(self.height, self.height + REQUEST_WINDOW):
                if self.max_peer_height and h > self.max_peer_height:
                    break
                r = self.requesters.get(h)
                if r is None:
                    r = _Requester(h)
                    self.requesters[h] = r
                if r.block is not None:
                    continue
                now = self._now()
                if r.peer_id is not None:
                    if now - r.request_time < REQUEST_TIMEOUT:
                        continue
                    # timeout: ban + re-pick
                    r.banned.add(r.peer_id)
                    peer = self.peers.get(r.peer_id)
                    if peer is not None:
                        peer.num_pending = max(0, peer.num_pending - 1)
                        peer.timeout_count += 1
                        if peer.timeout_count >= 3:
                            self.on_peer_error(peer.id, "repeated timeouts")
                    r.peer_id = None
                peer = self._pick_peer(h, r.banned)
                if peer is None:
                    r.banned.clear()  # all candidates banned: retry all
                    continue
                r.peer_id = peer.id
                r.request_time = now
                peer.num_pending += 1
                if peer.num_pending == 1:
                    peer.arm_monitor(now)
                self.send_request(h, peer.id)

    # -- block ingest ------------------------------------------------------

    def add_block(self, peer_id: str, block, ext_commit=None,
                  size: int = 0) -> bool:
        with self._mtx:
            peer = self.peers.get(peer_id)
            if peer is not None and peer.recv_monitor is not None and size:
                peer.recv_monitor.update(size)
            r = self.requesters.get(block.header.height)
            if r is None or r.peer_id != peer_id:
                # unsolicited — could be a late response; ignore
                return False
            if r.block is not None:
                return False
            r.block = block
            r.ext_commit = ext_commit
            peer = self.peers.get(peer_id)
            if peer is not None:
                peer.num_pending = max(0, peer.num_pending - 1)
                peer.timeout_count = 0
            return True

    def redo_request(self, height: int) -> None:
        """Block at ``height`` failed verification: ban the peer, refetch
        (pool.go RedoRequest)."""
        with self._mtx:
            r = self.requesters.get(height)
            if r is None:
                return
            if r.peer_id is not None:
                r.banned.add(r.peer_id)
                self.on_peer_error(r.peer_id, f"bad block {height}")
                self.remove_peer(r.peer_id)
            r.peer_id = None
            r.block = None
            r.ext_commit = None

    # -- ordered consumption ----------------------------------------------

    def peek_two_blocks(self):
        with self._mtx:
            r1 = self.requesters.get(self.height)
            r2 = self.requesters.get(self.height + 1)
            return (
                (r1.block if r1 else None),
                (r1.ext_commit if r1 else None),
                (r2.block if r2 else None),
            )

    def pop_request(self) -> None:
        with self._mtx:
            self.requesters.pop(self.height, None)
            self.height += 1

    def is_caught_up(self) -> bool:
        with self._mtx:
            if not self.peers:
                return False
            # maxPeerHeight - 1, NOT maxPeerHeight (pool.go IsCaughtUp):
            # the tip block can only be VERIFIED by the next block's
            # LastCommit, which doesn't exist yet — requiring equality
            # deadlocks a restarted validator against the very consensus
            # that needs it (peers can't produce block H+1 without us,
            # we wait in blocksync for H+1 to verify H, and wait_sync
            # drops every consensus vote meanwhile). The final block is
            # fetched by consensus catch-up gossip instead.
            return self.height >= self.max_peer_height - 1

    def stop(self) -> None:
        with self._mtx:
            self._running = False
