"""Blocksync wire messages (reference: blocksync/msgs.go, channel 0x40)."""

from __future__ import annotations

from dataclasses import dataclass

from ..types import serialization as ser


@dataclass(slots=True)
class StatusRequestMessage:
    pass


@dataclass(slots=True)
class StatusResponseMessage:
    height: int
    base: int


@dataclass(slots=True)
class BlockRequestMessage:
    height: int


@dataclass(slots=True)
class BlockResponseMessage:
    block: object  # types.Block
    ext_commit: object | None = None


@dataclass(slots=True)
class NoBlockResponseMessage:
    height: int


ser.codec.register(
    StatusRequestMessage,
    StatusResponseMessage,
    BlockRequestMessage,
    BlockResponseMessage,
    NoBlockResponseMessage,
)
