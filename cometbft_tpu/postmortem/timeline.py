"""Cross-node causal timeline: merge N flight rings into one story.

Every node already records a flight ring (libs/health): step
transitions, proposal/vote admission, per-height commit latency,
per-hop gossip lag, and the fault/breaker/recompile/watchdog overlay.
What no single ring answers is the operator's actual question — *why
did height H take 4 rounds across the network?* — because each ring is
one node's view.  This module merges N rings (live rings over RPC,
``flight.json`` from black-box bundles, or a completed simnet run) into
one globally ordered **per-height timeline**:

    proposal -> per-node prevote/precommit admission -> per-hop gossip
    lag -> per-node commit

with ``simnet.fault`` / ``coalesce.breaker`` / ``xla.recompile`` /
``health.watchdog`` / ``wal.fsync`` rows overlaid as annotations on the
height window they land in.

Clock semantics (the part that decides whether the merge is exact):

* **virtual** domain — simnet rings are stamped from ONE shared
  virtual clock (libs/health.set_clock), so cross-node ordering is
  exact by construction and skew bounds are zero.  Wall-measured
  durations (``wal.fsync``) are dropped: real disk time is meaningless
  on a virtual axis and would break byte-reproducibility.
* **wall** domain — live rings are stamped from each node's wall
  clock.  The merge does NOT rewrite timestamps; instead every
  cross-node edge (commit spread, gossip hops) is tagged with the
  measured per-peer skew bound from the netstamp round-trip estimator
  (libs/netstats.skew_table, exported with the ring), so a reader
  knows exactly how much of an apparent lag could be clock, not
  network.

``Timeline.to_json()`` is a canonical serialization: same sources in,
same bytes out — the determinism contract tests/test_postmortem.py
pins for simnet runs.
"""

from __future__ import annotations

import dataclasses
import json
import urllib.request

from ..libs import health as libhealth

# event names (mirrors libs/health._CODE_NAMES; names, not codes, so
# the merge accepts rings from bundles written by other versions)
_EV_STEP = "consensus.step"
_EV_PROPOSAL = "consensus.proposal"
_EV_VOTE = "consensus.vote"
_EV_COMMIT = "consensus.commit"
_EV_GOSSIP = "p2p.gossip"
_EV_TX = "tx.stage"

_HEIGHT_EVENTS = frozenset(
    {_EV_STEP, _EV_PROPOSAL, _EV_VOTE, _EV_COMMIT}
)
# wall-duration rows dropped from virtual-domain sources — derived
# from the recorder's own registry so a future wall-measured code
# cannot be dropped from one side and kept by the other
_WALL_ONLY = frozenset(
    libhealth._CODE_NAMES[c] for c in libhealth.WALL_DURATION_CODES
)

# vote types (types/canonical)
_PREVOTE = 1
_PRECOMMIT = 2

_NEW_ROUND_STEP = 2  # RoundStep.NEW_ROUND in the EV_STEP ``step`` column


@dataclasses.dataclass
class Source:
    """One node's decoded flight ring + its clock metadata.

    ``attributed`` = the rows named their node explicitly (origin
    attribution).  A multi-node ring's fallback group — origin-0 rows
    like watchdog trips, breaker notices, simnet fault-plane events —
    is NOT a node: it merges as annotations but is excluded from the
    node list and the skew pair enumeration, so a phantom "local"
    cannot drag ``skew.complete`` to False on an otherwise
    fully-measured merge."""

    name: str
    events: list
    domain: str = "wall"  # "wall" | "virtual"
    skews: dict = dataclasses.field(default_factory=dict)
    attributed: bool = True


def sources_from_obj(obj, name: str | None = None) -> list[Source]:
    """Split one ring export (``flight.json`` / ``/debug/flight`` body,
    or a bare ``{"events": [...]}``) into per-node sources.

    Rows carry their origin in the decoded ``node`` field (simnet and
    in-process multi-node rings interleave several nodes in one ring);
    rows without one fall back to the export's ``node`` / the caller's
    ``name`` — so a single-node live ring becomes one source and a
    simnet ring becomes N, with no flag to pass."""
    if isinstance(obj, dict):
        events = obj.get("events", [])
        domain = obj.get("domain", "wall")
        base = obj.get("node") or name or "local"
        skews = obj.get("skews") or {}
    else:
        events, domain, base, skews = list(obj), "wall", name or "local", {}
    groups: dict[str, list] = {}
    order: list[str] = []
    explicit: set[str] = set()  # names that came from row-level origins
    for ev in events:
        node = ev.get("node")
        if node:
            explicit.add(node)
        else:
            node = base
        bucket = groups.get(node)
        if bucket is None:
            bucket = groups[node] = []
            order.append(node)
        bucket.append(ev)
    if not order:
        order.append(base)
        groups[base] = []
    # the export's skew table describes the PROCESS's stamped
    # connections (keyed by remote node-id prefix) — every source split
    # out of this export shares it, which is also correct for the
    # in-process multi-node case where one table holds all pairs.
    # The fallback group counts as a node only when it is the whole
    # export (single-node ring with no origin wiring): alongside
    # origin-attributed groups it is the unattributed remainder.
    return [
        Source(
            n, groups[n], domain, skews,
            attributed=(n in explicit or len(order) == 1),
        )
        for n in order
    ]


def load_sources(paths) -> list[Source]:
    """Sources from ``flight.json`` files on disk (bundle post-mortem)."""
    out: list[Source] = []
    for p in paths:
        with open(p) as f:
            obj = json.load(f)
        out.extend(sources_from_obj(obj, name=str(p)))
    return out


def fetch_ring(url: str, timeout: float = 2.0) -> dict:
    """GET one peer's ring export.  A bare ``host:port`` / node address
    is completed to its pprof ``/debug/flight`` route."""
    if "://" not in url:
        url = "http://" + url
    if "/debug/" not in url:
        url = url.rstrip("/") + "/debug/flight"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


# --------------------------------------------------------------- merge


def _round9(x: float) -> float:
    return round(float(x), 9)


def _quantile(sorted_vals, q: float):
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def _lag_stats(lags: list) -> dict | None:
    if not lags:
        return None
    vs = sorted(lags)
    return {
        "count": len(vs),
        "p50_s": _round9(_quantile(vs, 0.50)),
        "p90_s": _round9(_quantile(vs, 0.90)),
        "max_s": _round9(vs[-1]),
    }


class Timeline:
    """The merged view: ``data`` is a plain JSON-able dict;
    ``lag_samples`` keeps the raw per-window gossip-lag samples for the
    attribution pass (aggregates only go to JSON — a 50k-hop run must
    not serialize 50k floats); ``tx_samples`` keeps the sampled-tx
    submit->commit waits and admit-depth samples the mempool_backlog
    detector scores."""

    def __init__(self, data: dict, lag_samples: dict, tx_samples=None):
        self.data = data
        self.lag_samples = lag_samples
        self.tx_samples = tx_samples or {
            "run": [], "heights": {}, "depths": {},
        }

    @property
    def domain(self) -> str:
        return self.data["domain"]

    @property
    def heights(self) -> list[dict]:
        return self.data["heights"]

    @property
    def run(self) -> dict:
        return self.data["run"]

    def to_json(self) -> str:
        """Canonical bytes: sorted keys, no whitespace — the
        determinism pin for virtual-domain merges."""
        return json.dumps(
            self.data, sort_keys=True, separators=(",", ":"),
            default=str,
        )

    def summary(self) -> dict:
        d = self.data
        return {
            "domain": d["domain"],
            "nodes": d["nodes"],
            "heights": len(d["heights"]),
            "events": d["n_events"],
            "skew_max_bound_s": d["skew"].get("max_bound_s"),
        }


def _pair_skew_bound(a: Source, b: Source):
    """Tightest available bound between two live sources, looking from
    both ends (skew tables are keyed by 10-char peer-id prefixes — the
    same prefix live source names use)."""
    bounds = []
    ra = a.skews.get(b.name[:10])
    if ra:
        bounds.append(ra.get("bound_s"))
    rb = b.skews.get(a.name[:10])
    if rb:
        bounds.append(rb.get("bound_s"))
    bounds = [x for x in bounds if x is not None]
    return min(bounds) if bounds else None


def merge(sources: list[Source]) -> Timeline:
    """Merge N sources into one globally ordered per-height timeline.

    Virtual-domain sources merge exactly (shared clock); any wall
    source makes the whole merge wall-domain and cross-node rows carry
    ``skew_bound_s`` tags (None = no measured bound for that pair)."""
    sources = list(sources)
    domain = (
        "virtual"
        if sources and all(s.domain == "virtual" for s in sources)
        else "wall"
    )
    # node identity comes from attributed sources; an unattributed
    # remainder group (origin-0 watchdog/breaker/fault rows) merges as
    # annotations but is not a node
    attributed = [s for s in sources if s.attributed]
    if not attributed:
        attributed = sources
    nodes = [s.name for s in attributed]

    # pairwise skew edges (wall domain, >= 2 nodes)
    skew_edges: dict[str, dict] = {}
    bounds_all: list[float] = []
    complete = domain == "virtual"
    if domain == "wall" and len(attributed) > 1:
        complete = True
        for i, a in enumerate(attributed):
            for b in attributed[i + 1:]:
                bound = _pair_skew_bound(a, b)
                skew_edges[f"{a.name}|{b.name}"] = {"bound_s": bound}
                if bound is None:
                    complete = False
                else:
                    bounds_all.append(bound)

    # one globally ordered row stream; ties break by (source, slot) so
    # equal-timestamp rows (common under the virtual clock) order
    # deterministically
    rows = []
    for si, s in enumerate(sources):
        for k, ev in enumerate(s.events):
            if domain == "virtual" and ev.get("event") in _WALL_ONLY:
                continue
            rows.append((ev.get("ts", 0), si, k, ev))
    rows.sort(key=lambda t: (t[0], t[1], t[2]))

    heights: dict[int, dict] = {}
    votes_acc: dict[int, dict] = {}
    loose: list[tuple[int, int, dict]] = []  # (ts, si, ev) to place later
    tx_rows: list[tuple[int, int, dict]] = []  # sampled tx.stage rows

    for ts, si, _k, ev in rows:
        name = ev.get("event")
        h = ev.get("height", 0)
        node = ev.get("node") or sources[si].name
        if name == _EV_TX:
            # sampled tx-lifecycle rows get their own per-height view
            # below (never the annotation stream — a storm's sampled
            # txs would drown the fault/breaker rows there)
            tx_rows.append((ts, si, ev))
        elif name in _HEIGHT_EVENTS and h > 0:
            hv = heights.get(h)
            if hv is None:
                hv = heights[h] = {
                    "height": h,
                    "t0_ns": ts,
                    "end_ns": ts,
                    "rounds": 1,
                    "proposal": None,
                    "proposal_rejects": 0,
                    "round_starts": {},
                    "commits": {},
                }
                votes_acc[h] = {}
            hv["end_ns"] = max(hv["end_ns"], ts)
            r = ev.get("round", 0)
            hv["rounds"] = max(hv["rounds"], r + 1)
            if name == _EV_STEP:
                if (
                    ev.get("step") == _NEW_ROUND_STEP
                    and r not in hv["round_starts"]
                ):
                    hv["round_starts"][r] = ts
            elif name == _EV_PROPOSAL:
                if ev.get("accepted"):
                    if hv["proposal"] is None or ts < hv["proposal"]["ts_ns"]:
                        hv["proposal"] = {
                            "node": node, "ts_ns": ts, "round": r,
                        }
                else:
                    hv["proposal_rejects"] += 1
            elif name == _EV_VOTE:
                va = votes_acc[h].setdefault(
                    node,
                    {
                        "prevote_ns": None, "prevotes": 0,
                        "precommit_ns": None, "precommits": 0,
                    },
                )
                if ev.get("type") == _PREVOTE:
                    va["prevotes"] += 1
                    if va["prevote_ns"] is None:
                        va["prevote_ns"] = ts
                elif ev.get("type") == _PRECOMMIT:
                    va["precommits"] += 1
                    if va["precommit_ns"] is None:
                        va["precommit_ns"] = ts
            elif name == _EV_COMMIT:
                hv["commits"][node] = {
                    "ts_ns": ts,
                    "round": r,
                    "latency_s": _round9(ev.get("dur_ns", 0) / 1e9),
                    "txs": ev.get("txs", 0),
                }
        else:
            loose.append((ts, si, ev))

    ordered = [heights[h] for h in sorted(heights)]
    for hv in ordered:
        h = hv["height"]
        hv["votes"] = votes_acc[h]
        commits = hv["commits"]
        if commits:
            tss = [c["ts_ns"] for c in commits.values()]
            hv["first_commit_ns"] = min(tss)
            hv["commit_spread_s"] = _round9((max(tss) - min(tss)) / 1e9)
        else:
            hv["first_commit_ns"] = None
            hv["commit_spread_s"] = None
        hv["round_starts"] = {
            str(r): t for r, t in sorted(hv["round_starts"].items())
        }

    # window assignment for gossip + annotations: a loose row belongs
    # to the first height whose window END it precedes — a fault in
    # the gap between commits delays the NEXT height
    lag_samples: dict = {"run": [], "heights": {}}
    run_ann: list[dict] = []
    gossip_acc: dict = {}

    def _height_for(ts: int):
        for hv in ordered:
            if ts <= hv["end_ns"]:
                return hv["height"]
        return None

    def _gossip_bucket(key):
        b = gossip_acc.get(key)
        if b is None:
            b = gossip_acc[key] = {"lags": [], "by_phase": {}, "worst": None}
        return b

    for ts, si, ev in loose:
        name = ev.get("event")
        node = ev.get("node") or sources[si].name
        if name == _EV_GOSSIP:
            h = ev.get("height", 0) or _height_for(ts)
            lag_s = ev.get("lag_ns", 0) / 1e9
            phase = ev.get("phase_name", "?")
            for key in ("run", h):
                if key is None:
                    continue
                b = _gossip_bucket(key)
                b["lags"].append(lag_s)
                ph = b["by_phase"].setdefault(phase, [])
                ph.append(lag_s)
                worst = b["worst"]
                if worst is None or lag_s > worst["lag_s"]:
                    b["worst"] = {
                        "lag_s": _round9(lag_s),
                        "phase": phase,
                        "node": node,
                        "src": ev.get("src"),
                    }
        else:
            ann = dict(ev)
            ann.pop("node", None)
            ann["node"] = node
            h = ev.get("height", 0)
            if name in _HEIGHT_EVENTS and h:
                target = h if h in heights else _height_for(ts)
            else:
                target = _height_for(ts)
            ann["assigned_height"] = target
            run_ann.append(ann)

    # -- sampled tx-lifecycle rows: per-height tx tables + the wait /
    # depth samples the mempool_backlog detector scores.  Deterministic
    # sampling (libs/txtrace) means every node traced the SAME keys,
    # so cross-node commit rows of one tx join here for free.
    tx_samples: dict = {"run": [], "heights": {}, "depths": {}}
    stage_acc: dict[str, dict] = {}  # key -> first-seen non-commit stamps
    tx_acc: dict[int, dict] = {}  # height -> key -> joined row
    for ts, si, ev in tx_rows:
        stage = ev.get("stage_name", "?")
        if stage == "commit":
            continue  # second pass below (needs stage_acc complete)
        key = ev.get("key", "?")
        stamps = stage_acc.setdefault(key, {})
        if stage not in stamps:
            stamps[stage] = {
                "node": ev.get("node") or sources[si].name,
                "ts_ns": ts,
            }
        if stage == "admit":
            h = _height_for(ts)
            if h is not None:
                tx_samples["depths"].setdefault(h, []).append(
                    ev.get("val", 0)
                )
    for ts, si, ev in tx_rows:
        if ev.get("stage_name") != "commit":
            continue
        h = ev.get("height", 0) or _height_for(ts)
        if h is None:
            continue
        key = ev.get("key", "?")
        wait_ns = ev.get("val", 0)
        if wait_ns > 0:
            tx_samples["run"].append(wait_ns / 1e9)
            tx_samples["heights"].setdefault(h, []).append(wait_ns / 1e9)
        bucket = tx_acc.setdefault(h, {})
        row = bucket.get(key)
        if row is None:
            row = bucket[key] = {
                "key": key,
                "stages": stage_acc.get(key, {}),
                "commits": {},
            }
        row["commits"][ev.get("node") or sources[si].name] = {
            "ts_ns": ts,
            "since_admit_s": (
                _round9(wait_ns / 1e9) if wait_ns > 0 else None
            ),
        }

    def _gossip_view(key):
        b = gossip_acc.get(key)
        if b is None:
            return None
        stats = _lag_stats(b["lags"])
        stats["by_phase"] = {
            ph: _lag_stats(ls) for ph, ls in sorted(b["by_phase"].items())
        }
        stats["worst"] = b["worst"]
        return stats

    for hv in ordered:
        h = hv["height"]
        hv["gossip"] = _gossip_view(h)
        bucket = tx_acc.get(h)
        hv["txs"] = (
            [bucket[k] for k in sorted(bucket)] if bucket else []
        )
        hv["annotations"] = [
            a for a in run_ann if a["assigned_height"] == h
        ]
        b = gossip_acc.get(h)
        lag_samples["heights"][h] = b["lags"] if b else []
        # cross-node edge tag: how much of any apparent cross-node lag
        # in this height could be clock skew, not network/protocol
        if domain == "virtual":
            hv["skew_bound_s"] = 0.0
            hv["skew_complete"] = True
        else:
            involved = sorted(set(hv["commits"]) | set(hv["votes"]))
            hb: list[float] = []
            comp = True
            for i, a in enumerate(involved):
                for bn in involved[i + 1:]:
                    e = skew_edges.get(f"{a}|{bn}") or skew_edges.get(
                        f"{bn}|{a}"
                    )
                    bd = e.get("bound_s") if e else None
                    if bd is None:
                        comp = False
                    else:
                        hb.append(bd)
            hv["skew_bound_s"] = _round9(max(hb)) if hb else None
            hv["skew_complete"] = comp and len(involved) > 1

    # per-height latency budgets over the merged stream: the SAME
    # decomposition libs/health.budget serves locally (stage tiling
    # from the committing node's step rows + plane.budget / wal.fsync
    # overlays), so a timeline.json reader sees where each height's
    # wall time went next to who proposed and who lagged.  Pure
    # function of the decoded rows — deterministic per (seed, scenario)
    # like the rest of the canonical serialization.
    budgets = libhealth.budget_from_events([r[3] for r in rows])
    # critical-path verdicts ride the same merged stream: per height,
    # the gating resource (dominant stage × hottest in-window lock ×
    # coalescer plane) from the budget tiles + EV_LOCK wait rows — the
    # contention plane's answer to "what actually gated this commit".
    cpaths = libhealth.critical_path_from_events([r[3] for r in rows])
    for hv in ordered:
        b = budgets.get(hv["height"])
        hv["budget"] = (
            {"stages": b["stages"], "coverage": b["coverage"]}
            if b is not None
            else None
        )
        cp = cpaths.get(hv["height"])
        hv["critical_path"] = (
            {k: cp[k] for k in cp if k not in ("height", "node")}
            if cp is not None
            else None
        )

    run_b = gossip_acc.get("run")
    lag_samples["run"] = run_b["lags"] if run_b else []

    t0 = rows[0][0] if rows else 0
    end = rows[-1][0] if rows else 0
    data = {
        "schema": 1,
        "domain": domain,
        "nodes": nodes,
        "n_events": len(rows),
        "heights": ordered,
        "run": {
            "t0_ns": t0,
            "end_ns": end,
            "duration_s": _round9((end - t0) / 1e9),
            "gossip": _gossip_view("run"),
            "annotations": run_ann,
        },
        "skew": {
            "edges": skew_edges,
            "max_bound_s": (
                _round9(max(bounds_all)) if bounds_all else
                (0.0 if domain == "virtual" else None)
            ),
            "complete": complete,
        },
    }
    return Timeline(data, lag_samples, tx_samples)


def merge_ring_export(export: dict, name: str | None = None) -> Timeline:
    """Convenience: one ring export (possibly multi-node) -> Timeline."""
    return merge(sources_from_obj(export, name=name))


# re-export for callers that build synthetic sources in tests
__all__ = [
    "Source",
    "Timeline",
    "sources_from_obj",
    "load_sources",
    "fetch_ring",
    "merge",
    "merge_ring_export",
]

# keep a reference so the decoder-completeness contract is importable
# from one place (tests walk libhealth.ring_event_codes())
RING_EVENT_CODES = libhealth.ring_event_codes
