"""CLI: merge flight rings into a cross-node timeline + verdicts.

    # post-mortem over bundle artifacts / exported rings
    python -m cometbft_tpu.postmortem merge node0/flight.json node1/flight.json

    # live nodes: pull /debug/flight over RPC and merge
    python -m cometbft_tpu.postmortem merge http://127.0.0.1:6060 10.0.0.2:6060

    # attach to a deterministic simnet scenario run
    python -m cometbft_tpu.postmortem scenario partition_heal --seed 7

Prints the attribution table (one line per slow height, top-ranked
cause + evidence); ``--json`` emits the full merged timeline + report.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import (
    DEFAULT_BASELINE_LAG_S,
    REPORT_THRESHOLD,
    attribute,
    fetch_ring,
    merge,
    sources_from_obj,
)


def _common(ap: argparse.ArgumentParser) -> None:
    ap.add_argument(
        "--json", action="store_true",
        help="emit the full timeline + report as JSON",
    )
    ap.add_argument(
        "--threshold", type=float, default=REPORT_THRESHOLD,
        help="minimum score a cause needs to make the verdict",
    )
    ap.add_argument(
        "--baseline-lag-ms", type=float,
        default=DEFAULT_BASELINE_LAG_S * 1e3,
        help="healthy one-hop gossip lag floor for the latency detector",
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cometbft_tpu.postmortem",
        description="cross-node flight-ring post-mortems",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    mp = sub.add_parser(
        "merge", help="merge flight.json files and/or /debug/flight URLs"
    )
    mp.add_argument(
        "inputs", nargs="+",
        help="flight.json paths, or node addresses/URLs to pull live",
    )
    _common(mp)

    sp = sub.add_parser(
        "scenario", help="run a simnet scenario and attribute it"
    )
    sp.add_argument("name")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--nodes", type=int, default=None)
    _common(sp)

    args = ap.parse_args(argv)

    if args.cmd == "merge":
        sources = []
        for i, inp in enumerate(args.inputs):
            if "://" in inp or (":" in inp and not _is_path(inp)):
                obj = fetch_ring(inp)
            else:
                with open(inp) as f:
                    obj = json.load(f)
            sources.extend(sources_from_obj(obj, name=f"src{i}:{inp}"))
        tl = merge(sources)
    else:
        from ..simnet.scenarios import run_scenario

        kw = {}
        if args.nodes is not None:
            kw["n_nodes"] = args.nodes
        result = run_scenario(args.name, args.seed, **kw)
        print(json.dumps(result.summary(), default=str), file=sys.stderr)
        from . import merge_ring_export

        tl = merge_ring_export(result.ring)

    rep = attribute(
        tl,
        baseline_lag_s=args.baseline_lag_ms / 1e3,
        threshold=args.threshold,
    )
    if args.json:
        print(json.dumps(
            {"timeline": tl.data, "report": rep.to_dict()},
            indent=1, default=str,
        ))
    else:
        print(json.dumps(tl.summary(), default=str))
        print(rep.table())
    return 0


def _is_path(s: str) -> bool:
    import os

    return os.path.exists(s)


if __name__ == "__main__":
    sys.exit(main())
