"""Cross-node causal timeline + automated root-cause attribution.

The diagnostic layer over the observability stack: merge N flight
rings (live rings over RPC, ``flight.json`` from black-box bundles, or
a completed simnet run) into one globally ordered per-height timeline
(timeline.py), then name the dominant cause of every slow height
(attribute.py).  Exposed as:

* ``python -m cometbft_tpu.postmortem`` — merge files/URLs or attach
  to a simnet scenario run (``__main__.py``);
* ``/debug/timeline`` on the pprof server — the local node's merged
  height timelines + verdicts (``debug_timeline``), with ``?peer=``
  fan-in; ``/debug/flight`` serves the raw ring export peers pull;
* ``timeline.json`` in watchdog black-box bundles
  (``bundle_timeline``, called by libs/health.write_bundle) — merged
  across ``COMETBFT_TPU_POSTMORTEM_PEERS`` when those rings answer,
  local-only otherwise;
* ``--postmortem`` on ``python -m cometbft_tpu.simnet`` — the
  attribution table for a scenario run.

docs/observability.md "Cross-node timelines" documents the merge
semantics, the skew model, and the attribution vocabulary.
"""

from __future__ import annotations

import os

from .attribute import (
    DEFAULT_BASELINE_LAG_S,
    Finding,
    REPORT_THRESHOLD,
    Report,
    WindowVerdict,
    attribute,
)
from .timeline import (
    Source,
    Timeline,
    fetch_ring,
    load_sources,
    merge,
    merge_ring_export,
    sources_from_obj,
)

_ENV_PEERS = "COMETBFT_TPU_POSTMORTEM_PEERS"


def report_from_ring(
    export: dict,
    baseline_lag_s: float = DEFAULT_BASELINE_LAG_S,
    threshold: float = REPORT_THRESHOLD,
) -> tuple[Timeline, Report]:
    """One ring export (e.g. a ScenarioResult.ring) -> (Timeline,
    Report)."""
    tl = merge_ring_export(export)
    return tl, attribute(
        tl, baseline_lag_s=baseline_lag_s, threshold=threshold
    )


def debug_timeline(peers=(), fetch_timeout: float = 2.0) -> dict:
    """The ``/debug/timeline`` pprof body: the local ring (split per
    origin when several nodes share the process) merged with any
    ``peers`` ring URLs that answer, plus the attribution report.
    Unreachable peers degrade to the local view, never an error.
    Peers are fetched CONCURRENTLY with one shared deadline — a bundle
    written during a partition must pay ~one timeout total, not one
    per dead peer."""
    import threading
    import time

    from ..libs import health as libhealth

    sources = sources_from_obj(libhealth.export_ring())
    peers = list(peers)
    results: list = [None] * len(peers)

    def _fetch(i: int, url: str) -> None:
        try:
            results[i] = ("ok", fetch_ring(url, timeout=fetch_timeout))
        except Exception as e:
            results[i] = ("err", repr(e)[:160])

    threads = [
        threading.Thread(
            target=_fetch, args=(i, url),
            name=f"pm-fetch-{i}", daemon=True,
        )
        for i, url in enumerate(peers)
    ]
    for t in threads:
        t.start()
    end = time.monotonic() + fetch_timeout + 0.5  # one SHARED deadline
    for t in threads:
        t.join(timeout=max(0.0, end - time.monotonic()))
    fetched, errors = [], {}
    for i, url in enumerate(peers):
        res = results[i]
        if res is None:
            errors[url] = "fetch timed out"
        elif res[0] == "ok":
            sources.extend(sources_from_obj(res[1], name=f"peer{i}"))
            fetched.append(url)
        else:
            errors[url] = res[1]
    tl = merge(sources)
    rep = attribute(tl)
    return {
        "timeline": tl.data,
        "report": rep.to_dict(),
        "peers_merged": fetched,
        "peer_errors": errors,
    }


def bundle_timeline() -> dict:
    """The ``timeline.json`` black-box-bundle artifact: merged across
    the operator-configured peer ring URLs when reachable, local-only
    otherwise (libs/health.write_bundle calls this under the
    COMETBFT_TPU_POSTMORTEM gate).  Short fetch timeout — a bundle
    write happens DURING an incident and must not hang on dead peers."""
    raw = os.environ.get(_ENV_PEERS, "")
    urls = [u.strip() for u in raw.split(",") if u.strip()]
    return debug_timeline(peers=urls, fetch_timeout=1.5)


__all__ = [
    "DEFAULT_BASELINE_LAG_S",
    "Finding",
    "REPORT_THRESHOLD",
    "Report",
    "Source",
    "Timeline",
    "WindowVerdict",
    "attribute",
    "bundle_timeline",
    "debug_timeline",
    "fetch_ring",
    "load_sources",
    "merge",
    "merge_ring_export",
    "report_from_ring",
    "sources_from_obj",
]
