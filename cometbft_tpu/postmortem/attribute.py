"""Root-cause attribution over a merged cross-node timeline.

For every **slow height** (committed in > 1 round, or commit latency at
or above the run's p99 and well above its median) — and once for the
whole run — a panel of detectors scores the causes the observability
stack can actually see, and the ranked result is the **verdict**:

    injected_drop       link faults ate messages (simnet drop faults)
    injected_latency    one-hop gossip lag far above the healthy floor
    injected_partition  a partition overlapped the window
    injected_churn      a node was killed/restarted in the window
    injected_crash      an armed crash point fired in the window
    gray_partition      a one-DIRECTIONAL sever overlapped the window
    slow_disk           a slow-but-alive disk fault overlapped the window
    peer_evicted        a node-side defense evicted a peer (suspicion /
                        statesync chunk rotation) in the window
    laggard_proposer    the proposal arrived long after its round opened
    slow_gossip_hop     one hop's lag dwarfs the window's typical lag
    verify_stall        the verify-coalescer breaker was open
    recompile_storm     steady-state XLA recompiles burned the window
    wal_fsync_outlier   one WAL fsync consumed a large latency share
    mempool_backlog     sampled txs committed in the window waited far
                        longer in the mempool than the run's typical
                        submit->commit wait (libs/txtrace rows)
    lock_contention     threads spent a large share of the window
                        blocked on one engine mutex (libs/lockprof
                        EV_LOCK wait rows name the hot lock and the
                        blocking holder's acquire site)
    cpu_saturated       one subsystem's GIL-bound Python burned most
                        of the window's wall time (libs/profile
                        EV_PROF sampling windows name the subsystem —
                        the commit was compute-gated, not waiting)

Scores live in [0, 1]; only findings at or above the report threshold
make the verdict, so a healthy run yields **no verdict at all** — the
contract the fault-matrix acceptance test pins: every faulty simnet
cell's top-ranked cause names the injected fault, the clean cell stays
silent.  All arithmetic is over ring-derived integers/floats, so the
same (seed, scenario) produces the identical report.
"""

from __future__ import annotations

import dataclasses

# findings below this score never make a verdict
REPORT_THRESHOLD = 0.25
# expected healthy one-hop gossip lag; the latency detector scores the
# observed p50 against multiples of this floor (the simnet default link
# is 2 ms +- 0.5 ms jitter; LAN hops sit well under it too).  Override
# per call for exotic nets.
DEFAULT_BASELINE_LAG_S = 0.005

# simnet FAULT_DROP detail high byte (link.py drop reasons): which
# drops are INJECTED link faults vs partition/churn side effects
_DROP_INJECTED = frozenset({0, 1, 2})  # random / channel / class
_DROP_PARTITION = 3
_DROP_DEAD = 4

_FAULT = "simnet.fault"
_BREAKER = "coalesce.breaker"
_RECOMPILE = "xla.recompile"
_FSYNC = "wal.fsync"
_LOCK = "sync.lock"
_PROF = "prof.window"
_WATCHDOG = "health.watchdog"


@dataclasses.dataclass
class Finding:
    cause: str
    score: float
    evidence: dict

    def to_dict(self) -> dict:
        return {
            "cause": self.cause,
            "score": round(self.score, 4),
            "evidence": self.evidence,
        }


@dataclasses.dataclass
class WindowVerdict:
    """One attribution window (a slow height, or the whole run)."""

    window: str  # "height:H" | "run"
    height: int | None
    rounds: int
    latency_s: float | None
    findings: list  # ranked Findings (all, incl. sub-threshold)
    threshold: float

    @property
    def verdict(self) -> Finding | None:
        top = self.findings[0] if self.findings else None
        return top if top is not None and top.score >= self.threshold else None

    def to_dict(self) -> dict:
        v = self.verdict
        return {
            "window": self.window,
            "height": self.height,
            "rounds": self.rounds,
            "latency_s": self.latency_s,
            "verdict": v.to_dict() if v else None,
            "findings": [
                f.to_dict() for f in self.findings
                if f.score >= self.threshold
            ],
        }


@dataclasses.dataclass
class Report:
    run: WindowVerdict
    slow_heights: list  # WindowVerdicts
    threshold: float
    baseline_lag_s: float

    def to_dict(self) -> dict:
        return {
            "threshold": self.threshold,
            "baseline_lag_s": self.baseline_lag_s,
            "run": self.run.to_dict(),
            "slow_heights": [w.to_dict() for w in self.slow_heights],
        }

    def table(self) -> str:
        """The attribution table the simnet ``--postmortem`` flag and
        the CLI print."""
        lines = [
            f"{'window':<12} {'rounds':>6} {'latency':>10}  verdict",
        ]

        def fmt(w: WindowVerdict) -> str:
            v = w.verdict
            lat = f"{w.latency_s * 1e3:.1f}ms" if w.latency_s else "-"
            if v is None:
                cause = "(no cause above threshold)"
            else:
                ev = ", ".join(
                    f"{k}={v.evidence[k]}"
                    for k in sorted(v.evidence)
                    if not isinstance(v.evidence[k], (dict, list))
                )
                cause = f"{v.cause} [{v.score:.2f}] {ev}"
            return f"{w.window:<12} {w.rounds:>6} {lat:>10}  {cause}"

        lines.append(fmt(self.run))
        for w in self.slow_heights:
            lines.append(fmt(w))
        return "\n".join(lines)


# ----------------------------------------------------------- detectors


def _partition_intervals(annotations: list, end_ns: int) -> list:
    """[(start_ns, end_ns)] partition windows from fault annotations
    (an unhealed partition runs to the end of the data)."""
    out = []
    open_ts = None
    for a in annotations:
        if a.get("event") != _FAULT:
            continue
        fname = a.get("fault_name")
        if fname == "partition":
            if open_ts is None:
                open_ts = a.get("ts", 0)
        elif fname == "heal" and open_ts is not None:
            out.append((open_ts, a.get("ts", 0)))
            open_ts = None
    if open_ts is not None:
        out.append((open_ts, end_ns))
    return out


def _fault_intervals(
    annotations: list, end_ns: int, fault_name: str
) -> list:
    """[(start_ns, end_ns, row)] for set/clear fault pairs of one
    gray-failure family (``oneway_sever``/``slow_disk``: ``detail`` > 0
    opens an episode, 0 — or a ``heal`` row — closes it; an unclosed
    episode runs to the end of the data).  Episodes are keyed per
    (src, dst) so concurrent faults of the same family on different
    nodes/links track independently — a clear on node 1 must not close
    node 2's still-active episode.  Only explicit ``detail=0`` rows
    close an episode: ``net.heal()`` emits one per open one-way sever
    before its ``heal`` row, and slow disks are NOT healed by it, so a
    bare ``heal`` must not close a still-charging disk fault."""
    out = []
    open_rows: dict = {}
    for a in annotations:
        if a.get("event") != _FAULT:
            continue
        if a.get("fault_name") == fault_name:
            # fault rows park src/dst (slow_disk: node) in the ring's
            # h/r columns, decoded as height/round
            key = (a.get("height"), a.get("round"))
            if a.get("detail", 0) > 0:
                open_rows.setdefault(key, a)
            elif key in open_rows:
                row = open_rows.pop(key)
                out.append((row.get("ts", 0), a.get("ts", 0), row))
    for row in open_rows.values():
        out.append((row.get("ts", 0), end_ns, row))
    out.sort(key=lambda t: t[0])
    return out


def _window_findings(
    *,
    t0_ns: int,
    end_ns: int,
    annotations: list,
    partitions: list,
    gray_intervals: list = (),
    slow_disk_intervals: list = (),
    lag_samples: list,
    gossip: dict | None,
    proposal_gap_s: float | None,
    median_gap_s: float | None,
    baseline_lag_s: float,
    tx_waits: list = (),
    tx_depths: list = (),
    median_tx_wait_s: float | None = None,
) -> list:
    """Score every cause over one window; returns ALL findings ranked
    by score (the caller applies the report threshold)."""
    findings: list[Finding] = []
    dur_s = max((end_ns - t0_ns) / 1e9, 1e-9)

    def in_window(a) -> bool:
        return t0_ns <= a.get("ts", 0) <= end_ns

    anns = [a for a in annotations if in_window(a)]

    # -- injected link drops (simnet fault plane)
    drops = [
        a for a in anns
        if a.get("event") == _FAULT
        and a.get("fault_name") == "drop"
        and (a.get("detail", 0) >> 8) in _DROP_INJECTED
    ]
    if drops:
        by_ch: dict[str, int] = {}
        for a in drops:
            ch = f"{a.get('detail', 0) & 0xFF:#04x}"
            by_ch[ch] = by_ch.get(ch, 0) + 1
        findings.append(Finding(
            "injected_drop",
            len(drops) / (len(drops) + 3.0),
            {"drops": len(drops), "by_channel": dict(sorted(by_ch.items()))},
        ))

    # -- partition overlap
    overlap_ns = 0
    for s, e in partitions:
        overlap_ns += max(0, min(e, end_ns) - max(s, t0_ns))
    if overlap_ns > 0:
        frac = min(1.0, overlap_ns / (end_ns - t0_ns + 1))
        findings.append(Finding(
            "injected_partition",
            0.6 + 0.35 * frac,
            {"overlap_s": round(overlap_ns / 1e9, 6)},
        ))

    # -- gray (one-directional) partition overlap
    gray_ns = 0
    gray_row = None
    for s, e, row in gray_intervals:
        ov = max(0, min(e, end_ns) - max(s, t0_ns))
        if ov > 0 and gray_row is None:
            gray_row = row
        gray_ns += ov
    if gray_ns > 0:
        frac = min(1.0, gray_ns / (end_ns - t0_ns + 1))
        findings.append(Finding(
            "gray_partition",
            0.6 + 0.35 * frac,
            {
                "overlap_s": round(gray_ns / 1e9, 6),
                # the sever rows park src/dst in the h/r columns
                "src": (gray_row or {}).get("height"),
                "dst": (gray_row or {}).get("round"),
            },
        ))

    # -- slow-but-alive disk overlap
    sd_ns = 0
    sd_row = None
    for s, e, row in slow_disk_intervals:
        ov = max(0, min(e, end_ns) - max(s, t0_ns))
        if ov > 0 and sd_row is None:
            sd_row = row
        sd_ns += ov
    if sd_ns > 0:
        # floor above laggard_proposer's 0.8 cap: a slow disk overlap
        # is a DIRECTLY injected/observed fault, and "the proposer was
        # late" is its symptom, not a competing root cause
        frac = min(1.0, sd_ns / (end_ns - t0_ns + 1))
        findings.append(Finding(
            "slow_disk",
            0.82 + 0.13 * frac,
            {
                "overlap_s": round(sd_ns / 1e9, 6),
                "node": (sd_row or {}).get("height"),
                "latency_ms": (sd_row or {}).get("detail"),
            },
        ))

    # -- a node-side defense acted (suspicion eviction / statesync
    # chunk-peer rotation): named, but scored BELOW the injected
    # faults — the defense is the response, rarely the root cause
    evictions = [
        a for a in anns
        if a.get("event") == _FAULT
        and a.get("fault_name") == "peer_evict"
    ]
    if evictions:
        findings.append(Finding(
            "peer_evicted",
            min(0.5, 0.25 + 0.05 * len(evictions)),
            {"evictions": len(evictions)},
        ))

    # -- churn / crash points
    kills = [
        a for a in anns
        if a.get("event") == _FAULT
        and a.get("fault_name") in ("kill", "restart")
    ]
    if kills:
        findings.append(Finding(
            "injected_churn",
            0.8,
            {
                "events": len(kills),
                "nodes": sorted({a.get("height", 0) for a in kills}),
            },
        ))
    crashes = [
        a for a in anns
        if a.get("event") == _FAULT
        and a.get("fault_name") == "crash_point"
    ]
    if crashes:
        findings.append(Finding(
            "injected_crash", 0.9, {"events": len(crashes)},
        ))

    # -- gossip latency far above the healthy floor
    if lag_samples:
        vs = sorted(lag_samples)
        p50 = vs[min(len(vs) - 1, len(vs) // 2)]
        score = (p50 - 2.0 * baseline_lag_s) / (8.0 * baseline_lag_s)
        if score > 0:
            findings.append(Finding(
                "injected_latency",
                min(1.0, score),
                {
                    "lag_p50_ms": round(p50 * 1e3, 3),
                    "baseline_ms": round(baseline_lag_s * 1e3, 3),
                    "hops": len(vs),
                },
            ))
        # -- one outlier hop (vs the window's own typical lag)
        mx = vs[-1]
        if mx > max(5.0 * p50, 4.0 * baseline_lag_s):
            worst = (gossip or {}).get("worst") or {}
            findings.append(Finding(
                "slow_gossip_hop",
                min(0.6, 0.2 * mx / max(p50, baseline_lag_s) / 5.0),
                {
                    "lag_max_ms": round(mx * 1e3, 3),
                    "lag_p50_ms": round(p50 * 1e3, 3),
                    "phase": worst.get("phase"),
                    "node": worst.get("node"),
                    "src": worst.get("src"),
                },
            ))

    # -- laggard proposer (relative to the run's typical proposal wait)
    if (
        proposal_gap_s is not None
        and median_gap_s is not None
        and proposal_gap_s > 3.0 * median_gap_s
        and proposal_gap_s > 0.2 * dur_s
    ):
        findings.append(Finding(
            "laggard_proposer",
            min(0.8, proposal_gap_s / (6.0 * median_gap_s + 1e-12) * 0.4),
            {
                "proposal_wait_ms": round(proposal_gap_s * 1e3, 3),
                "typical_ms": round(median_gap_s * 1e3, 3),
            },
        ))

    # -- verify-coalescer breaker open
    trips = [a for a in anns if a.get("event") == _BREAKER]
    if any(a.get("open") for a in trips):
        rearmed = any(not a.get("open") for a in trips)
        findings.append(Finding(
            "verify_stall",
            0.5 if rearmed else 0.85,
            {
                "trips": sum(1 for a in trips if a.get("open")),
                "rearmed": rearmed,
            },
        ))

    # -- recompile storm
    recompiles = [a for a in anns if a.get("event") == _RECOMPILE]
    if recompiles:
        findings.append(Finding(
            "recompile_storm",
            min(0.9, 0.3 * len(recompiles)),
            {"recompiles": len(recompiles)},
        ))

    # -- mempool backlog: sampled txs that committed IN this window
    # waited far longer from admission to commit than the run's
    # typical sampled tx — inclusion lagged while the chain ran, the
    # tx-plane signature of a storm-backlogged mempool (tx rows come
    # from libs/txtrace's deterministic sampling, so the comparison is
    # apples-to-apples across heights and nodes)
    if tx_waits and median_tx_wait_s:
        tw = sorted(tx_waits)
        p50 = tw[min(len(tw) - 1, len(tw) // 2)]
        ratio = p50 / median_tx_wait_s
        if ratio > 3.0:
            dp = sorted(tx_depths)
            findings.append(Finding(
                "mempool_backlog",
                min(0.85, 0.2 + 0.1 * ratio),
                {
                    "txs": len(tw),
                    "wait_p50_ms": round(p50 * 1e3, 3),
                    "typical_ms": round(median_tx_wait_s * 1e3, 3),
                    "depth_p50": (
                        dp[min(len(dp) - 1, len(dp) // 2)] if dp else None
                    ),
                },
            ))

    # -- WAL fsync outlier (wall-domain rings only; virtual merges drop
    # fsync rows because real disk time has no virtual meaning)
    fsyncs = [a for a in anns if a.get("event") == _FSYNC]
    if fsyncs:
        mx_s = max(a.get("dur_ns", 0) for a in fsyncs) / 1e9
        frac = mx_s / dur_s
        if frac > 0.15:
            findings.append(Finding(
                "wal_fsync_outlier",
                min(0.9, 2.0 * frac),
                {
                    "fsync_max_ms": round(mx_s * 1e3, 3),
                    "window_share": round(frac, 4),
                },
            ))

    # -- lock contention (wall-domain rings only, like fsync): slow
    # EV_LOCK wait rows in the window sum per lock; when the hottest
    # lock's blocked time is a large share of the window's wall time,
    # the commit chain was serialized behind it — the verdict names
    # the lock and the blocking holder's acquire site
    lock_waits = [
        a for a in anns
        if a.get("event") == _LOCK and a.get("kind_name") == "wait"
    ]
    if lock_waits:
        per_lock: dict[str, float] = {}
        site_of: dict[str, str] = {}
        for a in lock_waits:
            lk = a.get("lock", "?")
            per_lock[lk] = per_lock.get(lk, 0.0) + a.get("dur_ns", 0) / 1e9
            site_of.setdefault(lk, a.get("site", "?"))
        hot = max(per_lock, key=lambda k: per_lock[k])
        frac = per_lock[hot] / dur_s
        if frac > 0.15:
            findings.append(Finding(
                "lock_contention",
                min(0.9, 2.0 * frac),
                {
                    "lock": hot,
                    "holder_site": site_of.get(hot),
                    "wait_ms": round(per_lock[hot] * 1e3, 3),
                    "window_share": round(frac, 4),
                    "waits": len(lock_waits),
                },
            ))

    # -- CPU saturation (wall-domain rings only, like fsync/lock: the
    # sampler's on-CPU estimate is wall-measured, so virtual merges
    # drop EV_PROF rows): the sampling profiler's window rows sum
    # per-subsystem on-CPU time; when one subsystem's GIL-bound Python
    # consumed most of the window's wall clock, the commit was
    # compute-gated — the verdict names the subsystem (the profiler's
    # own sampler thread never counts)
    prof_rows = [
        a for a in anns
        if a.get("event") == _PROF and a.get("subsystem") != "sampler"
    ]
    if prof_rows:
        per_sub: dict[str, float] = {}
        for a in prof_rows:
            sub = a.get("subsystem", "?")
            per_sub[sub] = per_sub.get(sub, 0.0) + (
                a.get("oncpu_ns", 0) / 1e9
            )
        hot_sub = max(per_sub, key=lambda k: per_sub[k])
        frac = per_sub[hot_sub] / dur_s
        if frac > 0.6:
            findings.append(Finding(
                "cpu_saturated",
                min(0.9, 1.2 * frac),
                {
                    "subsystem": hot_sub,
                    "oncpu_ms": round(per_sub[hot_sub] * 1e3, 1),
                    "window_share": round(frac, 4),
                    "samples": sum(
                        a.get("samples", 0) for a in prof_rows
                    ),
                },
            ))

    findings.sort(key=lambda f: (-f.score, f.cause))
    return findings


# ----------------------------------------------------------- attribution


def _height_latency(hv: dict) -> float | None:
    """The height's network-wide latency: the slowest node's view."""
    lats = [c["latency_s"] for c in hv.get("commits", {}).values()]
    return max(lats) if lats else None


def _proposal_gap_s(hv: dict) -> float | None:
    p = hv.get("proposal")
    if p is None:
        return None
    start = hv.get("round_starts", {}).get(str(p["round"]))
    if start is None:
        start = hv.get("t0_ns")
    return max(0.0, (p["ts_ns"] - start) / 1e9)


def attribute(
    timeline,
    baseline_lag_s: float = DEFAULT_BASELINE_LAG_S,
    threshold: float = REPORT_THRESHOLD,
) -> Report:
    """Run the detector panel over a merged Timeline -> Report."""
    data = timeline.data
    heights = data["heights"]
    run = data["run"]
    annotations = run["annotations"]
    partitions = _partition_intervals(annotations, run["end_ns"])
    gray_intervals = _fault_intervals(
        annotations, run["end_ns"], "oneway_sever"
    )
    slow_disk_intervals = _fault_intervals(
        annotations, run["end_ns"], "slow_disk"
    )

    gaps = [g for g in (_proposal_gap_s(hv) for hv in heights)
            if g is not None]
    median_gap = sorted(gaps)[len(gaps) // 2] if gaps else None

    # sampled tx-lifecycle samples (absent on timelines built before
    # the tx plane, and on synthetic test Timelines)
    tx_s = getattr(timeline, "tx_samples", None) or {}
    tx_run = sorted(tx_s.get("run", []))
    median_tx_wait = tx_run[len(tx_run) // 2] if tx_run else None
    tx_heights = tx_s.get("heights", {})
    tx_depths = tx_s.get("depths", {})

    lats = [x for x in (_height_latency(hv) for hv in heights)
            if x is not None]
    lat_sorted = sorted(lats)
    p99 = (
        lat_sorted[min(len(lat_sorted) - 1, int(0.99 * len(lat_sorted)))]
        if lat_sorted else None
    )
    median_lat = (
        lat_sorted[len(lat_sorted) // 2] if lat_sorted else None
    )

    slow: list[WindowVerdict] = []
    for hv in heights:
        lat = _height_latency(hv)
        is_slow = hv["rounds"] > 1 or (
            lat is not None
            and p99 is not None
            and lat >= p99
            and median_lat is not None
            and lat > 1.2 * median_lat
        )
        if not is_slow:
            continue
        findings = _window_findings(
            t0_ns=hv["t0_ns"],
            end_ns=hv["end_ns"],
            annotations=annotations,
            partitions=partitions,
            gray_intervals=gray_intervals,
            slow_disk_intervals=slow_disk_intervals,
            lag_samples=timeline.lag_samples["heights"].get(
                hv["height"], []
            ),
            gossip=hv.get("gossip"),
            proposal_gap_s=_proposal_gap_s(hv),
            median_gap_s=median_gap,
            baseline_lag_s=baseline_lag_s,
            tx_waits=tx_heights.get(hv["height"], ()),
            tx_depths=tx_depths.get(hv["height"], ()),
            median_tx_wait_s=median_tx_wait,
        )
        slow.append(WindowVerdict(
            window=f"height:{hv['height']}",
            height=hv["height"],
            rounds=hv["rounds"],
            latency_s=lat,
            findings=findings,
            threshold=threshold,
        ))

    run_findings = _window_findings(
        t0_ns=run["t0_ns"],
        end_ns=run["end_ns"],
        annotations=annotations,
        partitions=partitions,
        gray_intervals=gray_intervals,
        slow_disk_intervals=slow_disk_intervals,
        lag_samples=timeline.lag_samples["run"],
        gossip=run.get("gossip"),
        proposal_gap_s=max(gaps) if gaps else None,
        median_gap_s=median_gap,
        baseline_lag_s=baseline_lag_s,
    )
    rounds_max = max((hv["rounds"] for hv in heights), default=1)
    run_verdict = WindowVerdict(
        window="run",
        height=None,
        rounds=rounds_max,
        latency_s=p99,
        findings=run_findings,
        threshold=threshold,
    )
    return Report(
        run=run_verdict,
        slow_heights=slow,
        threshold=threshold,
        baseline_lag_s=baseline_lag_s,
    )
