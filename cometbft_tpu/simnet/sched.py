"""Deterministic discrete-event scheduler + virtual clock.

The simnet plane runs EVERY moving part of an N-node net — message
deliveries, consensus timeouts, gossip ticks, blocksync pool steps,
scenario fault events — as events on ONE priority queue executed by ONE
thread in virtual time.  Determinism falls out of three rules:

* ordering: events execute by ``(due_ns, seq)`` — the monotone ``seq``
  breaks virtual-time ties in scheduling order, so two runs that
  schedule the same events execute them identically;
* randomness: every random draw (jitter, drops, reorder, vote pick)
  comes from a named child of one master ``random.Random(seed)`` —
  names hash through :func:`crc32`, never Python's per-process
  randomized ``hash()``, so ``--seed N`` reproduces across processes;
* time: components read the :class:`SimClock`, never the wall clock, so
  a timeout scheduled for +40 virtual ms fires after exactly the events
  that precede it, however long the host actually took.

``simnet.sched._mtx`` guards only heap push/pop (scenario authors may
arm events from the test thread before the run loop starts); it is
never held across a callback or another lock and is asserted edge-free
in tests/test_lint_graph.py like ``libs.trace._mtx``.
"""

from __future__ import annotations

import heapq
import itertools
import random
import zlib

from ..libs import sync as libsync


class SimClock:
    """Virtual time: monotonic ns since simulation start, plus a wall
    view anchored at ``base_wall_ns`` (so signed vote/proposal
    timestamps stay in the chain's epoch).  Duck-types the slice of the
    ``time`` module the consensus FSM reads (``time_ns``,
    ``monotonic``), so it drops into ``ConsensusState._clock``."""

    __slots__ = ("_now_ns", "base_wall_ns")

    def __init__(self, base_wall_ns: int = 1_700_000_000_000_000_000):
        self._now_ns = 0
        self.base_wall_ns = base_wall_ns

    @property
    def now_ns(self) -> int:
        return self._now_ns

    def advance_to(self, t_ns: int) -> None:
        if t_ns > self._now_ns:
            self._now_ns = t_ns

    # -- the time-module view ---------------------------------------------

    def time_ns(self) -> int:
        return self.base_wall_ns + self._now_ns

    def monotonic(self) -> float:
        return self._now_ns / 1e9

    def monotonic_ns(self) -> int:
        return self._now_ns

    def perf_counter(self) -> float:
        return self._now_ns / 1e9


def crc32(name: str) -> int:
    """Process-stable string hash for child-rng derivation (Python's
    ``hash(str)`` is salted per process and would break ``--seed``
    reproduction across runs)."""
    return zlib.crc32(name.encode())


class SimScheduler:
    """Seeded discrete-event loop core: a heap of ``(due_ns, seq, fn,
    args)``.  :meth:`pop_due` advances the clock to each event; the run
    loop (simnet/net.py) owns execution so it can interleave node inbox
    drains deterministically."""

    def __init__(self, seed: int, clock: SimClock | None = None):
        self.seed = seed
        self.clock = clock if clock is not None else SimClock()
        self.rng = random.Random(seed)
        self._heap: list[tuple[int, int, object, tuple]] = []
        self._seq = itertools.count()
        self._cancelled: set[int] = set()
        # heap push/pop only; never held across a callback or any other
        # lock (edge-free in lockorder.json)
        self._mtx = libsync.Mutex("simnet.sched._mtx")

    def sub_rng(self, name: str) -> random.Random:
        """A named child rng, stable across processes for one seed."""
        return random.Random((self.seed << 32) ^ crc32(name))

    # -- scheduling --------------------------------------------------------

    def call_at(self, t_ns: int, fn, *args) -> int:
        """Arm ``fn(*args)`` at virtual ``t_ns`` (clamped to now);
        returns a token usable with :meth:`cancel`."""
        with self._mtx:
            seq = next(self._seq)
            heapq.heappush(
                self._heap, (max(t_ns, self.clock.now_ns), seq, fn, args)
            )
            return seq

    def call_after(self, delay_ns: int, fn, *args) -> int:
        return self.call_at(self.clock.now_ns + max(0, int(delay_ns)), fn, *args)

    def cancel(self, token: int) -> None:
        """Lazy cancellation: the event stays heaped but is skipped."""
        with self._mtx:
            self._cancelled.add(token)

    # -- consumption (run loop in simnet/net.py) ---------------------------

    def pending(self) -> int:
        with self._mtx:
            return len(self._heap) - len(self._cancelled)

    def next_due_ns(self) -> int | None:
        with self._mtx:
            while self._heap and self._heap[0][1] in self._cancelled:
                _, seq, _, _ = heapq.heappop(self._heap)
                self._cancelled.discard(seq)
            return self._heap[0][0] if self._heap else None

    def pop_due(self) -> tuple[object, tuple] | None:
        """Pop the next live event, advancing the clock to its due
        time.  Returns ``(fn, args)`` or None when the heap is empty."""
        with self._mtx:
            while self._heap:
                due, seq, fn, args = heapq.heappop(self._heap)
                if seq in self._cancelled:
                    self._cancelled.discard(seq)
                    continue
                self.clock.advance_to(due)
                return fn, args
            return None
