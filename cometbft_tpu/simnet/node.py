"""Simnet node core: one full in-process node wired for the sim plane.

Mirrors the reference's node assembly (node/node.go) at test scale —
kvstore app, stores, executor, evidence pool, consensus state and the
consensus/evidence/blocksync reactors — but with every thread seam
closed: the consensus FSM is ``sim_driven`` (the scheduler pumps its
inbox), its ticker is the scheduler-backed :class:`SimTicker`, and the
reactors' per-peer routines run as virtual-time ticks (simnet/net.py).

``home=None`` keeps everything in memory (no WAL).  With a ``home``
the node gets FileDBs and a real consensus WAL, so churn scenarios can
kill a node hard and restart it through WAL catchup replay — the same
recovery path the crash-point subprocess tests exercise.
"""

from __future__ import annotations

import queue

from ..libs.service import BaseService


class SimTicker(BaseService):
    """Scheduler-backed TimeoutTicker: one pending timeout, newer
    (H,R,S) replaces older (ticker.go:95 semantics), fire enqueues the
    tock straight into the FSM inbox — no ticker/forwarder threads."""

    def __init__(self, sched, deliver):
        super().__init__("sim-ticker")
        self.sched = sched
        self._deliver = deliver
        self._pending = None
        self._gen = 0

    def schedule_timeout(self, ti) -> None:
        p = self._pending
        if p is not None and (ti.height, ti.round, ti.step) < (
            p.height, p.round, p.step
        ):
            return
        self._gen += 1
        self._pending = ti
        self.sched.call_after(
            int(ti.duration_s * 1e9), self._fire, self._gen
        )

    def _fire(self, gen: int) -> None:
        if gen != self._gen or self._pending is None:
            return  # superseded by a newer schedule
        if not self.is_running():
            # the owning FSM stopped (kill/crash): a stale tock must not
            # leak into a restarted node's fresh inbox
            return
        ti, self._pending = self._pending, None
        self._deliver(ti)


class SimListMempool:
    """Minimal reap-list mempool for tx injection (validator churn, the
    e2e ``--simnet`` load mode).  Implements exactly the
    BlockExecutor-facing slice of the mempool contract.

    When the tx-lifecycle plane (libs/txtrace) is enabled — bench
    ``20_tx_lifecycle`` drives the mempool_storm scenario with it on —
    push/update stamp admit/commit stages exactly like the real
    CListMempool, keyed on the same SHA-256 tx key, with the depth the
    tx saw at admission.  Keys are hashed ONLY while the plane is on
    (hashlib directly: simnet routes no hash plane), and every stamp
    reads the shared virtual clock through libs/health.now_ns, so the
    sampled latencies are exact and runs stay deterministic."""

    def __init__(self):
        self._txs: list[bytes] = []

    def push_tx(self, tx: bytes) -> None:
        from ..libs import txtrace as libtxtrace

        if libtxtrace.enabled():
            import hashlib

            libtxtrace.note_admit(
                hashlib.sha256(tx).digest(), len(self._txs)
            )
        self._txs.append(tx)

    def size(self) -> int:
        return len(self._txs)

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int):
        out, total = [], 0
        for tx in self._txs:
            if max_bytes >= 0 and total + len(tx) > max_bytes:
                break
            out.append(tx)
            total += len(tx)
        return out

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def update(self, height, txs, tx_results, *a, **k) -> None:
        from ..libs import txtrace as libtxtrace

        if libtxtrace.enabled():
            import hashlib

            for tx in txs:
                libtxtrace.note_commit(
                    hashlib.sha256(tx).digest(), height
                )
        committed = set(txs)
        self._txs = [t for t in self._txs if t not in committed]


def build_core(
    genesis,
    pv,
    config,
    home: str | None = None,
    app=None,
    with_evidence: bool = True,
    block_sync: bool = False,
    statesync: bool = False,
    now_fn=None,
    clock=None,
):
    """Assemble one node core.  Returns a dict of parts (the shape
    tests/helpers.make_consensus_node established, plus reactors).

    ``block_sync=True`` builds the node in catching-up mode: the
    consensus reactor starts with ``wait_sync`` and a BlocksyncReactor
    drives the pool until it switches to consensus.

    ``statesync=True`` builds a mid-run JOINER: consensus parks behind
    ``wait_sync`` and blocksync stays idle until the snapshot restore
    hands it a state (``switch_to_block_sync``) — the net's
    ``join_statesync`` drives the real statesync reactor/syncer over
    virtual links.  Every node carries a server-role StatesyncReactor
    regardless (answering snapshot/chunk requests from the app, like
    node.go does).
    """
    from .. import proxy
    from ..abci.kvstore import KVStoreApplication
    from ..blocksync.reactor import BlocksyncReactor
    from ..consensus import ConsensusState
    from ..consensus.reactor import ConsensusReactor
    from ..consensus.wal import WAL
    from ..evidence import EvidencePool
    from ..evidence.reactor import EvidenceReactor
    from ..libs import db as dbm
    from ..state import BlockExecutor, Store, make_genesis_state
    from ..statesync import StatesyncReactor
    from ..store import BlockStore
    from ..types.event_bus import EventBus

    app_db = None
    if home is None:
        if app is None:
            app_db = dbm.MemDB()
        state_db = dbm.MemDB()
        block_db = dbm.MemDB()
        wal = None
    else:
        import os

        os.makedirs(home, exist_ok=True)
        if app is None:
            app_db = dbm.FileDB(f"{home}/app.db")
        state_db = dbm.FileDB(f"{home}/state.db")
        block_db = dbm.FileDB(f"{home}/blocks.db")
        os.makedirs(f"{home}/cs.wal", exist_ok=True)
        wal = WAL(f"{home}/cs.wal/wal")
    app = app if app is not None else KVStoreApplication(app_db)
    conns = proxy.AppConns(proxy.local_client_creator(app))
    conns.start()
    state_store = Store(state_db)
    block_store = BlockStore(block_db)
    bus = EventBus()
    bus.start()
    state = state_store.load()
    if state is None:
        state = make_genesis_state(genesis)
        state_store.save(state)
    evidence_pool = None
    if with_evidence:
        evidence_db = dbm.MemDB() if home is None else dbm.FileDB(
            f"{home}/evidence.db"
        )
        evidence_pool = EvidencePool(evidence_db, state_store, block_store)
    mempool = SimListMempool()
    executor = BlockExecutor(
        state_store,
        conns.consensus,
        block_store=block_store,
        event_bus=bus,
        evidence_pool=evidence_pool,
        mempool=mempool,
    )
    cs = ConsensusState(
        config.consensus,
        state,
        executor,
        block_store,
        event_bus=bus,
        evidence_pool=evidence_pool,
        wal=wal,
        clock=clock,
    )
    cs.set_priv_validator(pv)
    cs.sim_driven = True
    # Pipelined-heights engine in INLINE mode: speculation and the
    # commit-writer job run synchronously on the FSM thread, so the
    # (seed, scenario) determinism pairs stay bit-identical — same
    # orderings as the serial chain — while the speculation protocol
    # and the new crash seams (cs-spec-exec, cs-pipeline-save,
    # cs-pipeline-fsync) stay reachable from simnet scenarios.
    from ..consensus.pipeline import CommitPipeline

    pipe = CommitPipeline(executor, cs.wal)
    pipe.inline = True
    pipe.enabled = True
    pipe.spec_enabled = conns.consensus.supports_speculation()
    pipe.note_base(state.last_block_height)
    executor.prune_gate = pipe.durable_height
    cs.pipeline = pipe

    consensus_reactor = ConsensusReactor(
        cs, wait_sync=block_sync or statesync
    )
    reactors: dict[str, object] = {"consensus": consensus_reactor}
    if evidence_pool is not None:
        reactors["evidence"] = EvidenceReactor(evidence_pool)
    # Every node carries a blocksync reactor — serving stored blocks to
    # catching-up peers even when it isn't syncing itself (node.go does
    # the same); only a ``block_sync=True`` node runs the pool.
    bsr = BlocksyncReactor(
        state,
        executor,
        block_store,
        block_sync=block_sync,
        consensus_reactor=consensus_reactor,
        min_recv_rate=0,  # virtual links have no byte clock to judge
        now_fn=now_fn,
    )
    bsr.sim_driven = True
    reactors["blocksync"] = bsr
    # Statesync server role on every node (snapshots come from the
    # app's ListSnapshots/LoadSnapshotChunk); a joiner's Syncer is
    # attached by SimNet.join_statesync.
    reactors["statesync"] = StatesyncReactor(conns.snapshot)
    if statesync:
        bsr.synced.clear()  # parked-for-statesync is NOT synced
    return dict(
        app=app,
        conns=conns,
        state_store=state_store,
        block_store=block_store,
        bus=bus,
        executor=executor,
        mempool=mempool,
        evidence_pool=evidence_pool,
        config=config,
        cs=cs,
        reactors=reactors,
        dbs=tuple(
            db
            for db in (app_db, state_db, block_db)
            if db is not None
        ),
    )


def drain_inbox(cs) -> None:
    """Drop everything queued for a killed node's FSM so a later
    restart starts from its WAL, not from stale in-memory messages."""
    try:
        while True:
            cs._queue.get_nowait()
    except queue.Empty:
        pass
