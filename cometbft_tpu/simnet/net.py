"""SimNet: the deterministic in-process network plane.

N full node cores (simnet/node.py) run REAL consensus/evidence/
blocksync reactors over seeded virtual links instead of TCP.  One
scheduler thread executes everything — deliveries, timeouts, gossip
ticks, scenario fault events — in virtual time, so a run is a pure
function of ``(seed, scenario)``: same commit heights, same round
counts, same flight-recorder event sequence, every time.

The plane implements the p2p peer/switch contract the reactors already
program against (:class:`SimPeer` ~ p2p.peer.Peer, :class:`SimHub` ~
p2p.switch.Switch), which is what buys catch-up gossip for free: the
consensus reactor's data/vote/maj23 catch-up paths — the machinery the
old ``wire_perfect_gossip`` test harness lacked, and whose absence was
the 2/16 byzantine-net liveness flake — run unmodified as virtual-time
ticks.

Fault vocabulary (scenario-drivable at any virtual time): per-link
latency/jitter/drop/reorder/bandwidth (simnet/link.py), partitions
(form/heal), peer churn (kill/restart mid-height with WAL replay),
message-class filters, and armed ``COMETBFT_TPU_FAIL`` crash points.
Every fault emits an ``EV_FAULT`` flight-recorder event, so a watchdog
black-box bundle from a scenario failure names the fault that was live.
"""

from __future__ import annotations

import collections
import os
import queue

from ..libs import health as libhealth
from ..libs import fail as libfail
from ..libs import netstats as libnetstats
from ..types import serialization as ser
from .link import (
    DROP_CHANNEL,
    DROP_CLASS,
    DROP_DEAD,
    DROP_PARTITION,
    DROP_RANDOM,
    Link,
    LinkConfig,
)
from .node import SimTicker, build_core, drain_inbox
from .sched import SimClock, SimScheduler

_ENV_LOG = "COMETBFT_TPU_SIMNET_LOG"

# virtual cadence of the sim-driven reactor routines
_BUSY_NS = 500_000  # re-tick delay after a productive gossip step
_GOSSIP_BURST = 16  # max productive gossip steps per tick event
_EVIDENCE_TICK_NS = 50_000_000
_BLOCKSYNC_TICK_NS = 50_000_000
_BLOCKSYNC_APPLIED_NS = 1_000_000

def _sim_log():
    """Logger for the sim-driven reactor ticks (lazy: honors whatever
    default logger was configured after import — the CLNT006 posture of
    the thread routines they replace)."""
    from ..libs import log as _log

    return _log.default_logger().with_module("simnet")


_DROP_TO_FAULT_DETAIL = {
    DROP_RANDOM: 0,
    DROP_CHANNEL: 1,
    DROP_CLASS: 2,
    DROP_PARTITION: 3,
    DROP_DEAD: 4,
}

# channel -> gossip phase recorded per delivered message (EV_GOSSIP;
# codes from libs/netstats.PHASE_CODES).  Channel-grain — the delivery
# plane never decodes payloads — which is exactly the granularity the
# postmortem latency attribution needs: a per-hop virtual lag sample
# for every message the links carried.
_CH_PHASE = {
    0x20: "state",  # consensus NewRoundStep/HasVote/maj23
    0x21: "block_part",  # consensus proposal + block parts
    0x22: "vote",  # consensus prevotes/precommits
    0x23: "state",  # consensus vote-set bits
    0x30: "tx",  # mempool
    0x38: "evidence",
    0x40: "block",  # blocksync
}


def make_genesis(n_vals: int, chain_id: str = "simnet-chain",
                 power: int = 10):
    """Deterministic genesis + priv-vals ordered to the ValidatorSet
    (the tests/helpers.make_genesis shape, packaged so the e2e harness
    and bench can build simnets without the test tree)."""
    from ..crypto.keys import Ed25519PrivKey
    from ..types import GenesisDoc, GenesisValidator, MockPV

    pvs = [
        MockPV(Ed25519PrivKey.from_seed(bytes([i + 1]) * 32))
        for i in range(n_vals)
    ]
    doc = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[
            GenesisValidator(pub_key=pv.get_pub_key(), power=power)
            for pv in pvs
        ],
    )
    vs = doc.validator_set()
    by_addr = {bytes(pv.get_pub_key().address()): pv for pv in pvs}
    ordered = [by_addr[v.address] for v in vs.validators]
    return doc, ordered


class SimPeer:
    """One directed peer handle: node ``owner``'s view of node
    ``remote``.  Implements the peer contract the reactors use
    (id/send/try_send/get/set/is_running) over the net's links."""

    sim_driven = True  # reactors skip their thread-per-peer routines
    outbound = True
    persistent = True

    __slots__ = ("net", "owner", "remote", "gossip_rng", "_data", "_running")

    def __init__(self, net: "SimNet", owner: int, remote: int, gossip_rng):
        self.net = net
        self.owner = owner
        self.remote = remote
        self.gossip_rng = gossip_rng
        self._data: dict[str, object] = {}
        self._running = True

    @property
    def id(self) -> str:
        return self.net.node_id(self.remote)

    def is_running(self) -> bool:
        return self._running and self.net.nodes[self.remote].alive

    def stop(self) -> None:
        self._running = False

    def send(self, ch_id: int, msg: bytes) -> bool:
        if not self._running:
            return False
        return self.net._send(self.owner, self.remote, ch_id, msg)

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        return self.send(ch_id, msg)

    def set(self, key: str, value) -> None:
        self._data[key] = value

    def get(self, key: str):
        return self._data.get(key)

    def __repr__(self) -> str:
        return f"SimPeer<{self.owner}->{self.remote}>"


class SimHub:
    """The switch stand-in one node's reactors are wired to: channel
    routing, peer table, broadcast fan-out (p2p/switch.go's surface,
    minus transports/threads)."""

    def __init__(self, net: "SimNet", idx: int):
        self.net = net
        self.idx = idx
        self.logger = None
        self.reactors: dict[str, object] = {}
        self._channel_to_reactor: dict[int, object] = {}
        self._peers: dict[str, SimPeer] = {}
        self._running = False

    def add_reactor(self, name: str, reactor) -> None:
        for desc in reactor.get_channels():
            self._channel_to_reactor[desc.id] = reactor
        self.reactors[name] = reactor
        reactor.set_switch(self)

    def start(self) -> None:
        self._running = True
        for reactor in self.reactors.values():
            reactor.start()

    def stop(self) -> None:
        self._running = False
        for peer in list(self._peers.values()):
            peer.stop()
        self._peers.clear()
        for reactor in self.reactors.values():
            if reactor.is_running():
                try:
                    reactor.stop()
                except Exception:
                    pass

    def is_running(self) -> bool:
        return self._running

    # -- peer table --------------------------------------------------------

    def admit(self, peer: SimPeer) -> None:
        self._peers[peer.id] = peer
        for reactor in self.reactors.values():
            reactor.init_peer(peer)
        for reactor in self.reactors.values():
            reactor.add_peer(peer)

    def drop(self, remote_id: str, reason) -> SimPeer | None:
        peer = self._peers.pop(remote_id, None)
        if peer is None:
            return None
        peer.stop()
        for reactor in self.reactors.values():
            try:
                reactor.remove_peer(peer, reason)
            except Exception:
                pass
        return peer

    def peers(self) -> list[SimPeer]:
        return list(self._peers.values())

    def num_peers(self) -> tuple[int, int]:
        return len(self._peers), 0

    def get_peer(self, peer_id: str) -> SimPeer | None:
        return self._peers.get(peer_id)

    # -- routing (Switch._on_peer_receive semantics) -----------------------

    def dispatch(self, ch_id: int, peer: SimPeer, msg: bytes) -> None:
        reactor = self._channel_to_reactor.get(ch_id)
        if reactor is None:
            self.stop_and_remove_peer(peer, f"unclaimed channel {ch_id:#x}")
            return
        try:
            reactor.receive(ch_id, peer, msg)
        except Exception as e:
            self.stop_and_remove_peer(peer, e)

    def stop_and_remove_peer(self, peer: SimPeer, reason) -> None:
        self.net._disconnect_pair(self.idx, peer.remote, reason)

    # -- broadcast ---------------------------------------------------------

    def broadcast(self, ch_id: int, msg: bytes) -> None:
        for peer in self._peers.values():
            peer.send(ch_id, msg)

    def try_broadcast(self, ch_id: int, msg: bytes) -> None:
        self.broadcast(ch_id, msg)


class SimNode:
    """One node slot: core (rebuilt across restarts), hub, liveness."""

    def __init__(self, net: "SimNet", idx: int, home: str | None):
        self.net = net
        self.idx = idx
        self.home = home
        self.alive = False
        self.core: dict | None = None
        self.hub: SimHub | None = None
        self.restarts = 0

    @property
    def cs(self):
        return self.core["cs"] if self.core else None

    @property
    def block_store(self):
        return self.core["block_store"] if self.core else None

    def height(self) -> int:
        return self.core["block_store"].height() if self.core else 0

    def boot(
        self, block_sync: bool = False, statesync: bool = False, app=None
    ) -> None:
        net = self.net
        if app is None and net.app_factory is not None:
            app = net.app_factory(self.idx)
        self.core = build_core(
            net.genesis,
            net.pvs[self.idx] if self.idx < len(net.pvs) else None,
            net.config,
            home=self.home,
            app=app,
            with_evidence=net.with_evidence,
            block_sync=block_sync,
            statesync=statesync,
            now_fn=net.clock.monotonic,
            clock=net.clock,
        )
        cs = self.core["cs"]
        cs.ticker = SimTicker(
            net.sched, lambda ti, idx=self.idx: net._tock(idx, ti)
        )
        cs.on_fatal = lambda e, idx=self.idx: net._on_node_fatal(idx, e)
        self.hub = SimHub(net, self.idx)
        for name, reactor in self.core["reactors"].items():
            self.hub.add_reactor(name, reactor)
        self.alive = True

    def start(self) -> None:
        self.hub.start()
        bsr = self.core["reactors"].get("blocksync")
        if bsr is not None and bsr.block_sync:
            self.net._schedule_blocksync_tick(self.idx, _BLOCKSYNC_TICK_NS)

    def shutdown(self, crash: bool) -> None:
        """Take the node down.  ``crash=True`` abandons the FSM where it
        stands (inbox dropped, no clean WAL close beyond the per-write
        flushes) — the restart path then exercises WAL catchup replay,
        the same recovery the crash-point subprocess tests pin."""
        self.alive = False
        if self.core is None:
            return
        cs = self.core["cs"]
        if crash:
            drain_inbox(cs)
        if self.hub is not None:
            self.hub.stop()  # stops reactors; consensus reactor stops cs
        for stopper in ("bus", "conns"):
            try:
                self.core[stopper].stop()
            except Exception:
                pass
        for db in self.core.get("dbs", ()):
            try:
                db.close()
            except Exception:
                pass
        if cs.wal is not None:
            try:
                cs.wal.close()
            except Exception:
                pass


class SimNet:
    """The deterministic N-node net + fault plane + run loop."""

    def __init__(
        self,
        n_nodes: int,
        seed: int = 0,
        config=None,
        genesis=None,
        pvs=None,
        home_root: str | None = None,
        with_evidence: bool = True,
        default_link: LinkConfig | None = None,
        topology: str | int = "mesh",
        reconnect_delay_ns: int = 500_000_000,
        app_factory=None,  # f(idx) -> ABCI app (None = per-node kvstore)
        late: tuple = (),  # node idxs NOT booted by start() — mid-run
        # joiners for the statesync_join scenario (join_statesync) or
        # manual node.boot()+start()+connect by the scenario author
    ):
        from ..config import test_config

        self.n = n_nodes
        self.seed = seed
        self.config = config if config is not None else test_config()
        if genesis is None:
            genesis, gen_pvs = make_genesis(n_nodes)
            pvs = pvs if pvs is not None else gen_pvs
        self.genesis = genesis
        self.pvs = pvs or []
        self.with_evidence = with_evidence
        self.clock = SimClock(base_wall_ns=genesis.genesis_time_ns)
        self.sched = SimScheduler(seed, self.clock)
        self.default_link = (
            default_link if default_link is not None else LinkConfig()
        )
        self.topology = topology
        self.reconnect_delay_ns = reconnect_delay_ns
        self.home_root = home_root
        self.app_factory = app_factory
        self.late = frozenset(late)
        self.nodes = [
            SimNode(
                self, i,
                None if home_root is None else f"{home_root}/node{i}",
            )
            for i in range(n_nodes)
        ]
        self._links: dict[tuple[int, int], Link] = {}
        self._adj: set[tuple[int, int]] = set()
        self._partition: dict[int, int] | None = None
        # gray-failure state: directions severed while the CONNECTION
        # stays up (asymmetric partition), and per-node virtual disk
        # latency charged at the libs/fail delay points.  Disk debt is
        # a per-node BUSY DEADLINE: while a node's virtual disk is
        # mid-fsync its FSM events (deliveries, tocks) defer to the
        # deadline — exactly a thread blocked in write_sync — so its
        # proposals/votes become visible to gossip that much later.
        self._oneway: set[tuple[int, int]] = set()
        self._slow_disk: dict[int, tuple[int, int]] = {}
        self._slow_disk_rng = None
        self._disk_busy = [0] * n_nodes  # virtual-ns busy deadlines
        self.stats = collections.Counter()
        self._log = os.environ.get(_ENV_LOG, "") in ("1", "on", "true")
        self._events_run = 0
        self._stopped = False

    # -- identity ----------------------------------------------------------

    def node_id(self, idx: int) -> str:
        return "%040x" % (idx + 1)

    def _idx_of(self, node_id: str) -> int:
        return int(node_id, 16) - 1

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Boot every node (late joiners excepted) and connect the
        topology among the booted set."""
        self._install_sig_cache()
        # slow-disk delay points (consensus/wal, store writes) route to
        # this net for the run's lifetime; stop() uninstalls
        libfail.set_delay_handler(self._on_delay_point)
        # Flight-ring integration: stamp ring rows from the SHARED
        # virtual clock (exact cross-node merge — the postmortem
        # layer's lossless case) and intern one origin per node so
        # every row decodes with the node that recorded it.  The
        # scheduler thread switches origin per event (_enter_node).
        self._prev_ring_clock = libhealth.set_clock(
            self.clock.time_ns, domain="virtual"
        )
        self._origin_ids = [
            libhealth.register_origin(f"node{i}") for i in range(self.n)
        ]
        for node in self.nodes:
            if node.idx in self.late:
                continue
            prev = self._enter_node(node.idx)
            try:
                node.boot()
            finally:
                self._exit_node(prev)
        for node in self.nodes:
            if node.idx not in self.late:
                node.start()
        for i, j in self._topology_edges():
            if i not in self.late and j not in self.late:
                self.connect(i, j)

    # -- origin bookkeeping (who records the current ring row) ---------

    def _enter_node(self, idx: int) -> int:
        prev = self._current_node
        self._current_node = idx
        libhealth.set_thread_origin(
            self._origin_ids[idx] if idx >= 0 else 0
        )
        return prev

    def _exit_node(self, prev: int) -> None:
        self._current_node = prev
        libhealth.set_thread_origin(
            self._origin_ids[prev] if prev >= 0 else 0
        )

    _SIG_CACHE_CAP = 200_000

    def _install_sig_cache(self) -> None:
        """Share single-signature verify verdicts across the N co-located
        nodes for the run's lifetime.  Verification is a pure function of
        (pubkey, message, signature), but every node independently
        verifies the SAME gossiped vote bytes — at N=100 that's 100
        identical ~ms-scale verifies per vote, and it dominates the
        simulation's wall clock.  Verdict-identical by construction;
        uninstalled in stop()."""
        from ..crypto import coalesce as crypto_coalesce

        cache: dict = {}
        self._sig_cache = cache
        orig = crypto_coalesce.verify_signature
        self._orig_verify_signature = orig
        cap = self._SIG_CACHE_CAP

        def cached_verify(pub_key, msg: bytes, sig: bytes) -> bool:
            key = (pub_key.bytes(), msg, sig)
            v = cache.get(key)
            if v is None:
                v = orig(pub_key, msg, sig)
                if len(cache) >= cap:
                    cache.clear()
                cache[key] = v
            return v

        crypto_coalesce.verify_signature = cached_verify

    def _topology_edges(self):
        n = self.n
        if self.topology == "mesh" or (
            isinstance(self.topology, int) and self.topology >= n - 1
        ):
            return [(i, j) for i in range(n) for j in range(i + 1, n)]
        k = 2 if self.topology == "ring" else max(1, int(self.topology))
        edges = set()
        for i in range(n):
            for d in range(1, k // 2 + 1):
                edges.add(tuple(sorted((i, (i + d) % n))))
            if k % 2:
                edges.add(tuple(sorted((i, (i + 1 + k // 2) % n))))
        return sorted(edges)

    def neighbors(self, i: int) -> list[int]:
        return sorted(
            {b for a, b in self._adj if a == i}
        )

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        libfail.set_delay_handler(None)
        for node in self.nodes:
            if node.alive:
                node.shutdown(crash=False)
        if getattr(self, "_prev_ring_clock", None) is not None:
            libhealth.set_clock(*self._prev_ring_clock)
            self._prev_ring_clock = None
        libhealth.set_thread_origin(0)
        if getattr(self, "_orig_verify_signature", None) is not None:
            from ..crypto import coalesce as crypto_coalesce

            crypto_coalesce.verify_signature = self._orig_verify_signature
            self._orig_verify_signature = None

    # -- links & topology --------------------------------------------------

    def _link(self, i: int, j: int) -> Link:
        link = self._links.get((i, j))
        if link is None:
            link = Link(
                self.default_link, self.sched.sub_rng(f"link-{i}-{j}")
            )
            self._links[(i, j)] = link
        return link

    def set_link(self, i: int, j: int, symmetric: bool = True, **kw) -> None:
        """Reconfigure the (i→j) link's faults (and j→i when
        ``symmetric``)."""
        pairs = [(i, j), (j, i)] if symmetric else [(i, j)]
        for a, b in pairs:
            link = self._link(a, b)
            link.cfg = link.cfg.with_(**kw)
        self._fault(libhealth.FAULT_LINK, i, j)

    def set_all_links(self, **kw) -> None:
        """Reconfigure the default link AND every live link."""
        self.default_link = self.default_link.with_(**kw)
        for link in self._links.values():
            link.cfg = link.cfg.with_(**kw)
        self._fault(libhealth.FAULT_LINK, 0, 0, detail=1)

    def connect(self, i: int, j: int) -> None:
        if i == j:
            return
        if self._partition is not None and (
            self._partition.get(i) != self._partition.get(j)
        ):
            return  # no tunneling under a partition; heal() reconnects
        for a, b in ((i, j), (j, i)):
            if (a, b) in self._adj:
                continue
            if not (self.nodes[a].alive and self.nodes[b].alive):
                continue
            self._adj.add((a, b))
            self._link(a, b)  # materialize link state
            peer = SimPeer(
                self, a, b, self.sched.sub_rng(f"gossip-{a}-{b}")
            )
            self.nodes[a].hub.admit(peer)
            self._schedule_consensus_ticks(a, peer)
            if "evidence" in self.nodes[a].hub.reactors:
                self._stagger_call(
                    f"ev-{a}-{b}", _EVIDENCE_TICK_NS,
                    self._evidence_tick, a, peer,
                )

    def _disconnect_pair(self, i: int, j: int, reason) -> None:
        """Peer eviction (reactor-initiated or scenario): both directions
        drop; persistent-peer semantics reconnect after a delay while
        both ends live."""
        dropped = False
        for a, b in ((i, j), (j, i)):
            if (a, b) in self._adj:
                self._adj.discard((a, b))
                node = self.nodes[a]
                if node.hub is not None:
                    node.hub.drop(self.node_id(b), reason)
                dropped = True
        if dropped and self.reconnect_delay_ns > 0:
            self.sched.call_after(
                self.reconnect_delay_ns, self._maybe_reconnect, i, j
            )

    def _maybe_reconnect(self, i: int, j: int) -> None:
        if self.nodes[i].alive and self.nodes[j].alive:
            self.connect(i, j)

    # -- faults ------------------------------------------------------------

    def _fault(self, kind: int, src: int = 0, dst: int = 0,
               detail: int = 0) -> None:
        # fault rows are NETWORK-plane annotations, not any one node's
        # view — record with origin cleared (src/dst ride in h/r)
        prev = libhealth.current_thread_origin()
        libhealth.set_thread_origin(0)
        try:
            libhealth.record(
                libhealth.EV_FAULT, height=src, round_=dst, a=kind,
                b=detail,
            )
        finally:
            libhealth.set_thread_origin(prev)
        if self._log:
            import sys

            print(
                f"[simnet t={self.clock.now_ns / 1e6:.1f}ms] fault "
                f"kind={kind} {src}->{dst} detail={detail}",
                file=sys.stderr,
            )

    def partition(self, *groups) -> None:
        """Split the net.  Cross-boundary CONNECTIONS are severed (a
        real partition kills the TCP link, and with it the peer's
        gossip mark state — the self-heal on reconnect depends on
        that), in-flight cross-boundary messages die, and no new
        connection forms across the boundary until :meth:`heal`.
        Nodes in no listed group land in their own singleton islands."""
        mapping: dict[int, int] = {}
        for g, members in enumerate(groups):
            for m in members:
                mapping[m] = g
        for i in range(self.n):
            if i not in mapping:
                mapping[i] = len(groups) + i
        self._partition = mapping
        for a, b in sorted(self._adj):
            if a < b and mapping.get(a) != mapping.get(b):
                self._sever_pair(a, b, "partitioned")
        self.stats["partitions"] += 1
        self._fault(libhealth.FAULT_PARTITION, detail=len(groups))

    def _sever_pair(self, i: int, j: int, reason) -> None:
        """Drop both directions with NO reconnect schedule (partition
        semantics; reactor-driven evictions use _disconnect_pair)."""
        for a, b in ((i, j), (j, i)):
            if (a, b) in self._adj:
                self._adj.discard((a, b))
                node = self.nodes[a]
                if node.hub is not None:
                    node.hub.drop(self.node_id(b), reason)

    def heal(self) -> None:
        """End the partition — full AND asymmetric — and re-form the
        base topology (fresh peers, fresh gossip state — the reconnect
        a healed TCP net performs)."""
        self._partition = None
        for a, b in sorted(self._oneway):
            self._fault(libhealth.FAULT_ONEWAY, a, b, detail=0)
        self._oneway.clear()
        self._fault(libhealth.FAULT_HEAL)
        for a, b in self._topology_edges():
            if self.nodes[a].alive and self.nodes[b].alive:
                self.connect(a, b)

    # -- gray failures: asymmetric severs + slow disks ---------------------

    def sever_oneway(self, src: int, dst: int) -> None:
        """Asymmetric (gray) partition: kill the ``src -> dst``
        DIRECTION while the reverse direction — and the connection both
        ends believe in — stays alive.  The half-dead peer still
        handshakes, still receives, still thinks it is gossiping; only
        its counterpart silently hears nothing.  Messages sent (or
        already in flight) on the dead direction are destroyed and
        classify as ``drop_partition``.  :meth:`heal` (or
        :meth:`restore_oneway`) restores the direction."""
        self._oneway.add((src, dst))
        self.stats["oneway_severs"] += 1
        self._fault(libhealth.FAULT_ONEWAY, src, dst, detail=1)

    def restore_oneway(self, src: int, dst: int) -> None:
        self._oneway.discard((src, dst))
        self._fault(libhealth.FAULT_ONEWAY, src, dst, detail=0)

    def set_slow_disk(
        self, idx: int, latency_ns: int, jitter_ns: int = 0
    ) -> None:
        """Slow-but-alive disk on node ``idx``: every WAL fsync and
        store write that node performs (the ``libs/fail`` delay points)
        charges ``latency_ns`` (± uniform ``jitter_ns``) of VIRTUAL
        time as disk debt — the node's outbound messages and its own
        next timeout fire that much later, exactly as if its FSM sat
        waiting on the volume.  ``latency_ns=0`` clears the fault.
        Deterministic: jitter draws come from a seeded child rng."""
        if self._slow_disk_rng is None:
            self._slow_disk_rng = self.sched.sub_rng("slow-disk")
        if latency_ns <= 0:
            self._slow_disk.pop(idx, None)
            self._fault(libhealth.FAULT_SLOW_DISK, src=idx, detail=0)
        else:
            self._slow_disk[idx] = (latency_ns, jitter_ns)
            self._fault(
                libhealth.FAULT_SLOW_DISK, src=idx,
                detail=max(1, latency_ns // 1_000_000),
            )

    def _on_delay_point(self, name: str) -> None:
        """libs/fail delay-point handler: push the current node's disk
        BUSY deadline out by the injected latency — its FSM events
        (deliveries, tocks) defer past the deadline, exactly a thread
        blocked in write_sync.  The laggard stays attributable through
        the slow_disk fault set/clear rows."""
        idx = self._current_node
        cfg = self._slow_disk.get(idx)
        if cfg is None:
            return
        latency_ns, jitter_ns = cfg
        lat = latency_ns
        if jitter_ns > 0:
            lat += int(self._slow_disk_rng.random() * jitter_ns)
        self._disk_busy[idx] = (
            max(self._disk_busy[idx], self.clock.now_ns) + lat
        )
        self.stats["disk_delay_ns"] += lat
        # no EV_FSYNC row here: the WAL's own instrumentation already
        # records one per fsync (wall-measured, dropped by virtual-
        # domain timeline merges), and a second virtual-duration row
        # would double-count the fsync in ring SLIs — attribution runs
        # on the slow_disk fault set/clear rows, not fsync rows

    def _disk_lag_ns(self, idx: int) -> int:
        """How far past ``now`` node ``idx``'s disk is still busy."""
        return max(0, self._disk_busy[idx] - self.clock.now_ns)

    def mark_storm(self, rate_tx_s: int) -> None:
        """Annotate the fault plane with a sustained mempool storm
        starting/stopping (rate 0 = stopped) — the scenario engine
        calls this around its load generator so postmortems and
        black-box bundles can name the pressure that was live."""
        self._fault(libhealth.FAULT_STORM, detail=max(0, rate_tx_s))

    # -- statesync joins (mid-run node bootstrap over the real path) -------

    _STATESYNC_TICK_NS = 20_000_000  # fetch/apply cadence (virtual)

    def join_statesync(
        self,
        idx: int,
        trust_height: int = 1,
        chunk_timeout_s: float = 1.0,
        serving: list | None = None,
    ):
        """Boot the (late) node ``idx`` mid-run and statesync it to the
        chain tip over the REAL path: snapshot discovery on channel
        0x60 → app offer → chunk fetch on 0x61 (with the per-peer
        failure/rotation plan, on the virtual clock) → light-client
        verification of the restored app hash against ``trust_height``
        via store-backed providers on the live peers → bootstrap →
        switch to blocksync → consensus.  Returns the Syncer (the
        scenario asserts on its rotation counters)."""
        from ..light import TrustOptions
        from ..light.provider import StoreBackedProvider
        from ..statesync import StateProvider, Syncer

        node = self.nodes[idx]
        if serving is None:
            serving = [
                i for i in range(self.n)
                if i != idx and self.nodes[i].alive
            ]
        if not serving:
            raise ValueError("statesync join needs at least one live peer")
        src = self.nodes[serving[0]]
        meta = src.block_store.load_block_meta(trust_height)
        if meta is None:
            raise ValueError(
                f"no block at trust height {trust_height} on the chain yet"
            )
        prev = self._enter_node(idx)
        try:
            node.boot(statesync=True)
            node.start()
        finally:
            self._exit_node(prev)
        chain_id = self.genesis.chain_id
        providers = [
            StoreBackedProvider(
                self.nodes[i].block_store,
                self.nodes[i].core["state_store"],
                chain_id,
            )
            for i in serving[:2]
        ]
        sp = StateProvider(
            chain_id,
            self.genesis,
            providers,
            TrustOptions(
                # virtual-epoch headers vs the light client's wall
                # clock: a decade-scale trusting period keeps every
                # simulated header inside it (verdicts stay a pure
                # function of the stores — deterministic)
                period_ns=10 * 365 * 24 * 3600 * 1_000_000_000,
                height=trust_height,
                hash=meta.block_id.hash,
            ),
            initial_height=self.genesis.initial_height,
        )
        reactor = node.core["reactors"]["statesync"]
        syncer = Syncer(
            node.core["conns"].snapshot,
            node.core["conns"].query,
            sp,
            reactor.request_chunk,
            chunk_timeout=chunk_timeout_s,
            now_fn=self.clock.monotonic,
        )
        reactor.syncer = syncer
        node.core["syncer"] = syncer
        node.statesync_state = {
            "phase": "discover", "snapshot": None,
            "offer_retries": 0, "finish_tries": 0,
        }
        for j in serving:
            self.connect(idx, j)
        self.sched.call_after(
            self._STATESYNC_TICK_NS, self._statesync_tick, idx
        )
        return syncer

    def _rebroadcast_snapshot_requests(self, idx: int) -> None:
        """Ask every connected peer for its current snapshots again
        (the on-add request only sees what existed at connect time)."""
        from ..statesync.messages import (
            SNAPSHOT_CHANNEL,
            SnapshotsRequestMessage,
        )
        from ..types import serialization as _ser

        hub = self.nodes[idx].hub
        if hub is None:
            return
        raw = _ser.dumps(SnapshotsRequestMessage())
        for peer in hub.peers():
            peer.try_send(SNAPSHOT_CHANNEL, raw)

    def _statesync_tick(self, idx: int) -> None:
        """One step of a joiner's restore state machine (discover →
        restore → finish → switched), re-armed until the handoff to
        blocksync; the real syncer does the work, this tick only pumps
        its non-blocking steps in virtual time."""
        from ..statesync.syncer import (
            AbortError,
            AppHashMismatchError,
            RejectFormatError,
            RetrySnapshotError,
            SyncError,
        )

        node = self.nodes[idx]
        if self._stopped or not node.alive:
            return
        st = node.statesync_state
        syncer = node.core["syncer"]
        prev = self._enter_node(idx)
        try:
            phase = st["phase"]
            if phase == "discover":
                snap = syncer.pool.best()
                if snap is None:
                    # periodic re-discovery: a snapshot that went stale
                    # (pruned by the app while we fetched) was rejected,
                    # and the live peers have NEWER ones to advertise
                    st["discover_ticks"] = st.get("discover_ticks", 0) + 1
                    if st["discover_ticks"] % 25 == 0:
                        self._rebroadcast_snapshot_requests(idx)
                else:
                    try:
                        # attempts=1: the provider retry loop sleeps
                        # REAL time, which would freeze the scheduler —
                        # this tick retries on the virtual clock instead
                        syncer.begin(snap, provider_attempts=1)
                        st["snapshot"] = snap
                        st["phase"] = "restore"
                        st["restore_start_ns"] = self.clock.now_ns
                        st["begin_tries"] = 0
                        # each snapshot gets its own RETRY_SNAPSHOT
                        # allowance (a fresh offer, a fresh app verdict)
                        st["offer_retries"] = 0
                    except RejectFormatError:
                        syncer.pool.reject_format(snap.format)
                    except (AbortError, AppHashMismatchError) as e:
                        self._on_node_fatal(idx, e)
                        return
                    except SyncError:
                        # young tip: the trusted app hash needs header
                        # H+1, which appears as the chain grows — keep
                        # ticking rather than rejecting a good
                        # snapshot (bounded, then re-discover)
                        st["begin_tries"] = st.get("begin_tries", 0) + 1
                        if st["begin_tries"] > 100:
                            st["begin_tries"] = 0
                            syncer.pool.reject(snap)
            elif phase == "restore":
                snap = st["snapshot"]
                budget_ns = int(
                    syncer.chunk_timeout * max(1, snap.chunks) * 4 * 1e9
                )
                if self.clock.now_ns - st["restore_start_ns"] > budget_ns:
                    # every serving peer exhausted its chances (a stale
                    # snapshot the apps pruned, or all chunk paths
                    # gray): reject and re-discover a fresh one
                    syncer.abort_restore()
                    syncer.pool.reject(snap)
                    st["phase"] = "discover"
                    self._rebroadcast_snapshot_requests(idx)
                    self.sched.call_after(
                        self._STATESYNC_TICK_NS, self._statesync_tick, idx
                    )
                    return
                try:
                    syncer.step_fetch()
                    if syncer.step_apply():
                        syncer.abort_restore()
                        st["phase"] = "finish"
                except RetrySnapshotError:
                    syncer.abort_restore()
                    st["offer_retries"] += 1
                    if st["offer_retries"] >= 3:
                        syncer.pool.reject(st["snapshot"])
                    st["phase"] = "discover"
                except (AbortError, AppHashMismatchError) as e:
                    self._on_node_fatal(idx, e)
                    return
                except SyncError:
                    syncer.abort_restore()
                    syncer.pool.reject(st["snapshot"])
                    st["phase"] = "discover"
            elif phase == "finish":
                try:
                    state, commit = syncer.finish(
                        st["snapshot"], provider_attempts=1
                    )
                except (AbortError, AppHashMismatchError) as e:
                    self._on_node_fatal(idx, e)
                    return
                except SyncError:
                    # young tip: the providers need blocks H+1/H+2 —
                    # keep ticking while the chain grows past them
                    st["finish_tries"] += 1
                    if st["finish_tries"] > 500:
                        self._on_node_fatal(
                            idx,
                            RuntimeError("statesync finish never verified"),
                        )
                        return
                else:
                    node.core["state_store"].bootstrap(state)
                    node.core["block_store"].save_seen_commit(commit)
                    bsr = node.core["reactors"]["blocksync"]
                    bsr.switch_to_block_sync(state)
                    st["phase"] = "switched"
                    self._schedule_blocksync_tick(
                        idx, _BLOCKSYNC_APPLIED_NS
                    )
                    return
        finally:
            self._exit_node(prev)
        self.sched.call_after(
            self._STATESYNC_TICK_NS, self._statesync_tick, idx
        )


    def kill(self, idx: int, crash: bool = True) -> None:
        """Churn: take node ``idx`` down mid-whatever.  In-flight
        messages to it die; links drop; a later :meth:`restart` replays
        its WAL (requires a ``home_root`` net)."""
        node = self.nodes[idx]
        if not node.alive:
            return
        for j in list(self.neighbors(idx)):
            for a, b in ((idx, j), (j, idx)):
                self._adj.discard((a, b))
                other = self.nodes[a]
                if other.hub is not None:
                    other.hub.drop(self.node_id(b), "peer killed")
        node.shutdown(crash=crash)
        self._disk_busy[idx] = 0  # a dead node's disk owes nothing
        self.stats["kills"] += 1
        self._fault(libhealth.FAULT_KILL, src=idx)

    def restart(self, idx: int, block_sync: bool = False) -> None:
        """Churn: bring a killed node back over its on-disk state (WAL
        catchup replay runs inside consensus start).  ``block_sync``
        reboots it in catching-up mode — the blocksync reactor fetches
        the missed blocks from peers before consensus takes over."""
        node = self.nodes[idx]
        if node.alive:
            return
        node.restarts += 1
        prev = self._enter_node(idx)
        try:
            node.boot(block_sync=block_sync)  # WAL replay records here
            node.start()
        finally:
            self._exit_node(prev)
        for j in range(self.n):
            if j != idx and self.nodes[j].alive and (
                (idx, j) in self._base_edges()
            ):
                self.connect(idx, j)
        self.stats["restarts"] += 1
        self._fault(libhealth.FAULT_RESTART, src=idx)
        prev = self._enter_node(idx)
        try:
            self.nodes[idx].cs.process_pending()
        finally:
            self._exit_node(prev)

    def _base_edges(self) -> set[tuple[int, int]]:
        out = set()
        for a, b in self._topology_edges():
            out.add((a, b))
            out.add((b, a))
        return out

    def arm_crash_point(self, idx: int, point: str) -> None:
        """Arm a COMETBFT_TPU_FAIL crash point for ONE sim node: when
        node ``idx``'s FSM reaches it, the node dies in-process (the
        commit-chain fail-stop path) instead of killing the pytest
        process.  Disarm with :meth:`disarm_crash_point`."""
        net = self

        class _SimCrash(Exception):
            pass

        def handler(name: str) -> None:
            cur = net._current_node
            if cur == idx:
                net._fault(libhealth.FAULT_CRASH, src=idx)
                raise _SimCrash(f"crash point {name} on node {idx}")

        libfail.set_target(point)
        libfail.set_handler(handler)

    def disarm_crash_point(self) -> None:
        libfail.set_target("")
        libfail.set_handler(None)

    # -- message plane -----------------------------------------------------

    _current_node: int = -1

    def _send(self, src: int, dst: int, ch: int, msg: bytes) -> bool:
        if self._stopped:
            return False
        if not (self.nodes[src].alive and self.nodes[dst].alive):
            return False
        if (src, dst) not in self._adj:
            return False
        # no cross-partition branch here: partition() SEVERS adjacency,
        # so a partitioned pair already failed the _adj check above;
        # in-flight messages racing a fresh partition are classified at
        # delivery time (_deliver)
        if (src, dst) in self._oneway:
            # asymmetric sever: the direction is dead but the sender
            # has no way to know — the wire ate it (gray partition)
            self._drop(DROP_PARTITION, src, dst, ch)
            return True
        link = self._link(src, dst)
        if link.cfg.drop_classes:
            try:
                cls = type(ser.loads(msg)).__name__
            except Exception:
                cls = "?"
            if cls in link.cfg.drop_classes:
                self._drop(DROP_CLASS, src, dst, ch)
                return True  # the wire ate it; the sender can't tell
        # slow-disk debt: a sender whose virtual disk is still busy
        # puts this message on the wire only after the disk returns
        lag = self._disk_lag_ns(src)
        deliver_at, dup_at, reason = link.plan(
            self.clock.now_ns + lag, ch, len(msg)
        )
        if reason is not None:
            self._drop(reason, src, dst, ch)
            return True
        self.stats["sent"] += 1
        # stamp the VIRTUAL wire-entry moment (incl. disk debt): the
        # per-hop gossip-lag rows measure the LINK, not the sender's
        # disk — the slow_disk postmortem detector owns that signal
        sent_ns = self.clock.now_ns + lag
        self.sched.call_at(
            deliver_at, self._deliver, src, dst, ch, msg, sent_ns
        )
        if dup_at is not None:
            self.stats["duplicated"] += 1
            self.sched.call_at(
                dup_at, self._deliver, src, dst, ch, msg, sent_ns
            )
        return True

    def _drop(self, reason: str, src: int, dst: int, ch: int) -> None:
        self.stats[reason] += 1
        self.stats["dropped"] += 1
        self._fault(
            libhealth.FAULT_DROP, src, dst,
            detail=(_DROP_TO_FAULT_DETAIL.get(reason, 0) << 8) | ch,
        )

    def _in_flight_drop_reason(self, src: int, dst: int) -> str:
        """An undeliverable in-flight message died either to a partition
        (full or one-directional) that formed under it or to endpoint
        churn/eviction."""
        if (src, dst) in self._oneway:
            return DROP_PARTITION
        if self._partition is not None and (
            self._partition.get(src) != self._partition.get(dst)
        ):
            return DROP_PARTITION
        return DROP_DEAD

    def _deliver(
        self, src: int, dst: int, ch: int, msg: bytes, sent_ns: int = 0
    ) -> None:
        node = self.nodes[dst]
        if (src, dst) in self._oneway:
            # a one-way sever that formed under an in-flight message
            # destroys it (the TCP stream it rode is half-dead)
            self._drop(DROP_PARTITION, src, dst, ch)
            return
        if self._stopped or not node.alive:
            self._drop(self._in_flight_drop_reason(src, dst), src, dst, ch)
            return
        busy = self._disk_busy[dst]
        if busy > self.clock.now_ns:
            # the receiver's FSM thread is blocked on its virtual disk:
            # processing (not the wire) waits for the deadline
            self.sched.call_at(
                busy, self._deliver, src, dst, ch, msg, sent_ns
            )
            return
        peer = node.hub.get_peer(self.node_id(src))
        if peer is None or not peer.is_running():
            self._drop(self._in_flight_drop_reason(src, dst), src, dst, ch)
            return
        self.stats["delivered"] += 1
        self.stats[f"delivered_ch_{ch:#04x}"] += 1
        prev = self._enter_node(dst)
        try:
            # per-hop gossip lag into the receiving node's flight ring:
            # the virtual-time analog of the netstamp EV_GOSSIP rows
            # (phase by channel; sender's origin parked in the round
            # column — the merge reads it back as the hop's src edge)
            phase = _CH_PHASE.get(ch)
            if phase is not None and sent_ns:
                libhealth.record(
                    libhealth.EV_GOSSIP,
                    0,
                    self._origin_ids[src],
                    libnetstats.PHASE_CODES.get(phase, 0),
                    self.clock.now_ns - sent_ns,
                )
            node.hub.dispatch(ch, peer, msg)
            if node.alive:
                node.cs.process_pending()
        finally:
            self._exit_node(prev)

    def inject(self, src: int, dst: int, ch: int, msg_bytes: bytes) -> bool:
        """Scenario-level send AS node ``src`` (byzantine behaviors):
        rides the same links/faults as organic traffic."""
        return self._send(src, dst, ch, msg_bytes)

    def _tock(self, idx: int, ti) -> None:
        node = self.nodes[idx]
        if self._stopped or not node.alive:
            return
        busy = self._disk_busy[idx]
        if busy > self.clock.now_ns:
            # FSM blocked on its virtual disk: the timeout fires when
            # the thread comes back (exactly a wedged receive loop)
            self.sched.call_at(busy, self._tock, idx, ti)
            return
        cs = node.cs
        try:
            cs._queue.put_nowait(("timeout", ti))
        except queue.Full:
            cs.process_pending()
            cs._queue.put_nowait(("timeout", ti))
        prev = self._enter_node(idx)
        try:
            cs.process_pending()
        finally:
            self._exit_node(prev)

    # -- sim-driven reactor routines ---------------------------------------

    def _stagger_call(self, name: str, period_ns: int, fn, *args) -> None:
        """First tick lands at a deterministic per-routine offset so N
        nodes' routines don't all fire on the same virtual instant."""
        offset = self.sched.sub_rng(f"stagger-{name}").randrange(
            max(1, period_ns)
        )
        self.sched.call_after(offset, fn, *args)

    def _schedule_consensus_ticks(self, idx: int, peer: SimPeer) -> None:
        cs_cfg = self.config.consensus
        gossip_ns = cs_cfg.peer_gossip_sleep_duration_ns
        maj23_ns = cs_cfg.peer_query_maj23_sleep_duration_ns
        for kind, period in ((0, gossip_ns), (1, gossip_ns), (2, maj23_ns)):
            self._stagger_call(
                f"cons-{idx}-{peer.remote}-{kind}", period,
                self._consensus_tick, idx, peer, kind,
            )

    def _consensus_tick(self, idx: int, peer: SimPeer, kind: int) -> None:
        node = self.nodes[idx]
        if self._stopped or not node.alive or not peer.is_running():
            return
        reactor = node.hub.reactors.get("consensus")
        if reactor is None or not reactor.is_running():
            return
        ps = peer.get("consensus_peer_state")
        busy = False
        if ps is not None:
            try:
                if kind == 2:
                    reactor._query_maj23_once(
                        peer, ps, reactor.cs.get_round_state()
                    )
                else:
                    # The thread routine loops back IMMEDIATELY after a
                    # productive step ('continue', no sleep) — one
                    # scheduler event per message would drown large nets,
                    # so a tick drains a burst before yielding.
                    step = (
                        reactor._gossip_data_once
                        if kind == 0
                        else reactor._gossip_votes_once
                    )
                    for _ in range(_GOSSIP_BURST):
                        if not step(peer, ps, reactor.cs.get_round_state()):
                            break
                        busy = True
            except Exception as e:
                # keep ticking, but say why (the thread routines log
                # these failures for the same reason — a persistent
                # exception here silently stalls gossip)
                _sim_log().debug(
                    "gossip tick failed; retrying",
                    node=idx, peer=peer.remote, kind=kind,
                    err=repr(e)[:120],
                )
        cs_cfg = self.config.consensus
        period = (
            cs_cfg.peer_gossip_sleep_duration_ns
            if kind < 2
            else cs_cfg.peer_query_maj23_sleep_duration_ns
        )
        self.sched.call_after(
            _BUSY_NS if busy else period,
            self._consensus_tick, idx, peer, kind,
        )

    def _evidence_tick(self, idx: int, peer: SimPeer) -> None:
        node = self.nodes[idx]
        if self._stopped or not node.alive or not peer.is_running():
            return
        reactor = node.hub.reactors.get("evidence")
        if reactor is None or not reactor.is_running():
            return
        try:
            reactor.gossip_step(peer, now_ns=self.clock.now_ns)
        except Exception as e:
            _sim_log().debug(
                "evidence gossip step failed; retrying next tick",
                node=idx, peer=peer.remote, err=repr(e)[:120],
            )
        self.sched.call_after(
            _EVIDENCE_TICK_NS, self._evidence_tick, idx, peer
        )

    def _schedule_blocksync_tick(self, idx: int, delay_ns: int) -> None:
        self.sched.call_after(delay_ns, self._blocksync_tick, idx)

    def _blocksync_tick(self, idx: int) -> None:
        node = self.nodes[idx]
        if self._stopped or not node.alive:
            return
        reactor = node.hub.reactors.get("blocksync")
        if reactor is None or not reactor.is_running():
            return
        prev = self._enter_node(idx)
        try:
            outcome = reactor._pool_step(self.clock.monotonic())
            node.cs.process_pending()
        except Exception as e:
            # local apply failure: the reference panics — fail-stop this
            # node only
            self._on_node_fatal(idx, e)
            return
        finally:
            self._exit_node(prev)
        if outcome == reactor.STEP_SWITCHED:
            return
        self._schedule_blocksync_tick(
            idx,
            _BLOCKSYNC_APPLIED_NS
            if outcome == reactor.STEP_APPLIED
            else _BLOCKSYNC_TICK_NS,
        )

    def _on_node_fatal(self, idx: int, err) -> None:
        self.stats["fatal"] += 1
        if self._log:
            import sys

            print(f"[simnet] node {idx} fail-stop: {err!r}", file=sys.stderr)
        self.kill(idx, crash=True)

    # -- run loop ----------------------------------------------------------

    def run(
        self,
        until=None,
        max_virtual_ms: float = 60_000.0,
        max_events: int = 5_000_000,
        check_every: int = 16,
    ) -> bool:
        """Execute events until ``until()`` is true or the virtual
        budget runs out.  Returns whether the condition was met."""
        deadline_ns = self.clock.now_ns + int(max_virtual_ms * 1e6)
        since_check = 0
        while True:
            if until is not None and since_check == 0 and until():
                return True
            due = self.sched.next_due_ns()
            if due is None or due > deadline_ns:
                self.clock.advance_to(deadline_ns)
                return bool(until()) if until is not None else False
            popped = self.sched.pop_due()
            if popped is None:
                continue
            fn, args = popped
            self._events_run += 1
            if self._events_run > max_events:
                raise RuntimeError(
                    f"simnet runaway: >{max_events} events executed"
                )
            fn(*args)
            since_check = (since_check + 1) % check_every
        # unreachable

    def run_until_height(
        self, height: int, nodes=None, max_virtual_ms: float = 60_000.0,
    ) -> bool:
        idxs = list(nodes) if nodes is not None else [
            n.idx for n in self.nodes
        ]

        def caught_up() -> bool:
            return all(
                self.nodes[i].alive and self.nodes[i].height() >= height
                for i in idxs
            )

        return self.run(until=caught_up, max_virtual_ms=max_virtual_ms)

    def heights(self) -> list[int]:
        return [n.height() for n in self.nodes]

    def assert_no_fork(self) -> None:
        """Safety invariant: every pair of nodes agrees at every common
        height (block id AND app hash)."""
        live = [n for n in self.nodes if n.core is not None]
        if len(live) < 2:
            return
        common = min(n.height() for n in live)
        for h in range(1, common + 1):
            metas = [n.block_store.load_block_meta(h) for n in live]
            ids = {m.block_id.hash for m in metas if m is not None}
            assert len(ids) == 1, f"FORK at height {h}: {ids}"
            hashes = {
                m.header.app_hash for m in metas if m is not None
            }
            assert len(hashes) == 1, f"app-hash fork at height {h}"
