"""CLI: run a simnet scenario and print its JSON summary.

    python -m cometbft_tpu.simnet --scenario byzantine_double_sign --seed 7

``--seed N`` makes the run bit-reproducible (same heights, rounds and
flight-recorder sequence every time) — the seed printed by a failing
CI/e2e run replays that exact schedule locally.  The default seed comes
from ``COMETBFT_TPU_SIMNET_SEED`` (0 if unset).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .scenarios import SCENARIOS, run_scenario

_ENV_SEED = "COMETBFT_TPU_SIMNET_SEED"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cometbft_tpu.simnet",
        description="deterministic fault-injection scenario runner",
    )
    ap.add_argument(
        "--scenario", default="healthy", choices=sorted(SCENARIOS),
    )
    ap.add_argument(
        "--seed", type=int,
        default=int(os.environ.get(_ENV_SEED, "0") or "0"),
        help="schedule seed; a failing run's seed reproduces it exactly",
    )
    ap.add_argument(
        "--nodes", type=int, default=None, help="node-count override"
    )
    ap.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    ap.add_argument(
        "--postmortem", action="store_true",
        help="print the cross-node timeline attribution table for the "
        "run (cometbft_tpu/postmortem)",
    )
    ap.add_argument(
        "--profile", action="store_true",
        help="run the sampling profiler (libs/profile) across the "
        "scenario and report scheduler-vs-verify-vs-engine wall "
        "shares — a simnet run executes on one scheduler thread, so "
        "shares are classified by frame module, not thread",
    )
    args = ap.parse_args(argv)
    if args.list:
        for name in sorted(SCENARIOS):
            print(name)
        return 0
    kw = {}
    if args.nodes is not None:
        kw["n_nodes"] = args.nodes
    before = None
    if args.profile:
        from ..libs import profile as libprofile

        libprofile.acquire()
        before = libprofile.snapshot_agg()
    try:
        result = run_scenario(args.scenario, args.seed, **kw)
    finally:
        if args.profile:
            shares = libprofile.module_shares(
                libprofile.delta_agg(before, libprofile.snapshot_agg())
            )
            libprofile.release()
    summary = result.summary()
    if args.profile:
        summary["profile"] = shares
    print(json.dumps(summary, default=str, indent=1))
    if args.postmortem and result.ring is not None:
        from ..postmortem import report_from_ring

        _tl, report = report_from_ring(result.ring)
        print(report.table())
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
