"""Scenario engine: scripted fault schedules + assertions over a SimNet.

A scenario is a seeded, self-checking run: it builds a net, arms timed
fault events (partitions, churn, byzantine behaviors, crash points),
runs the scheduler until its conditions hold (or the virtual budget
dies), and returns a :class:`ScenarioResult` carrying the evidence —
final heights, per-height commit latency/rounds, the fault-annotated
flight-recorder ring, and a determinism signature: two runs with the
same ``(seed, scenario)`` produce identical signatures (pinned by
tests/test_simnet.py).

Registry (``SCENARIOS`` / :func:`run_scenario` / ``python -m
cometbft_tpu.simnet``):

* ``healthy`` — clean-net baseline;
* ``byzantine_double_sign`` — a validator equivocates toward ONE honest
  peer; the resulting DuplicateVoteEvidence must travel the evidence
  reactor, re-verify on every pool, and land in a committed block;
* ``partition_heal`` — full split (liveness lost, rounds spin), heal,
  converge; then a minority split whose healed minority catches up
  through the reactor's catch-up gossip (the old perfect-gossip
  harness' missing piece — the 2/16 byzantine-net flake);
* ``crash_restart`` — an armed COMETBFT_TPU_FAIL crash point kills a
  node mid-commit; restart replays its WAL and rejoins;
* ``valset_churn`` — ``val:<pk>!<power>`` txs add a standby node to the
  validator set mid-run, then evict a genesis validator;
* ``blocksync_catchup`` — a churned node rejoins via blocksync while a
  serving peer dies mid-sync.

Gray-failure family (PR 13 — slow-but-alive and asymmetric faults):

* ``gray_partition`` — ONE direction of one link is severed while the
  connection stays up; the chain must keep committing via the live
  direction plus relay through the other peers, and heal() restores
  both directions;
* ``slow_disk`` — one validator's WAL fsyncs/store writes carry
  50–500 virtual ms of injected latency (libs/fail delay points on the
  sim clock): the chain slows but never stalls, and the laggard is
  attributable;
* ``statesync_join`` — a fresh node joins a grown chain mid-run over
  the REAL statesync path (snapshot offer → chunk fetch → light verify
  → switch to blocksync → consensus), surviving an injected
  chunk-peer failure via the fetch plan's rotation;
* ``mempool_storm`` — sustained tx pressure through commit churn: the
  chain keeps committing, committed txs drain from every mempool.
"""

from __future__ import annotations

import dataclasses
import shutil
import tempfile

from ..libs import health as libhealth
from .link import LinkConfig
from .net import SimNet, make_genesis

# ring events whose (order, payload) must be bit-identical across runs
# of one (seed, scenario); wall-stamped codes (wal.fsync) are excluded
DETERMINISM_CODES = (
    "consensus.step",
    "consensus.proposal",
    "consensus.vote",
    "consensus.commit",
    "simnet.fault",
)


def ring_signature() -> tuple:
    rows = []
    for r in libhealth.recorder().dump():
        if r["event"] not in DETERMINISM_CODES:
            continue
        d = dict(r)
        d.pop("ts", None)
        rows.append(tuple(sorted(d.items())))
    return tuple(rows)


def commit_metrics() -> dict:
    """Per-height commit latency + rounds-per-height quantiles from the
    ring's EV_COMMIT rows (all nodes interleaved)."""
    lat_ms, rounds = [], []
    for r in libhealth.recorder().dump():
        if r["event"] != "consensus.commit":
            continue
        lat_ms.append(r["dur_ns"] / 1e6)
        rounds.append(r["round"] + 1)

    def q(xs, p):
        if not xs:
            return None
        ys = sorted(xs)
        return round(ys[min(len(ys) - 1, int(p * len(ys)))], 3)

    return {
        "commits": len(lat_ms),
        "commit_ms": {"p50": q(lat_ms, 0.5), "p99": q(lat_ms, 0.99)},
        "rounds_per_height": {
            "mean": round(sum(rounds) / len(rounds), 3) if rounds else None,
            "p99": q(rounds, 0.99),
        },
    }


# Scenario rings are sized to hold a WHOLE run's event stream — with
# per-delivery EV_GOSSIP rows the default 4096 slots would wrap and
# evict the early heights' commits, starving the postmortem merge.
SCENARIO_RING = 1 << 16


@dataclasses.dataclass
class ScenarioResult:
    name: str
    seed: int
    ok: bool
    heights: list
    virtual_ms: float
    events_run: int
    stats: dict
    metrics: dict
    signature: tuple
    failures: list
    notes: dict
    # full flight-ring export (libs/health.export_ring shape) captured
    # before the run's ring is torn down — the input the cross-node
    # postmortem timeline (cometbft_tpu/postmortem) merges
    ring: dict | None = None

    def summary(self) -> dict:
        return {
            "scenario": self.name,
            "seed": self.seed,
            "ok": self.ok,
            "heights": self.heights,
            "virtual_ms": round(self.virtual_ms, 3),
            "events": self.events_run,
            "dropped": self.stats.get("dropped", 0),
            "failures": self.failures,
            **self.metrics,
            **self.notes,
        }


class _Run:
    """Shared scaffolding: recorder reset/enable, optional home root,
    net teardown, result assembly."""

    def __init__(self, name: str, seed: int, homes: bool = False):
        self.name = name
        self.seed = seed
        self.failures: list[str] = []
        self.notes: dict = {}
        self.home_root = (
            tempfile.mkdtemp(prefix=f"simnet-{name}-") if homes else None
        )
        self._prev_enabled = libhealth.enabled()
        self._prev_ring = libhealth.recorder().capacity
        libhealth.set_ring_capacity(SCENARIO_RING)
        libhealth.reset()
        libhealth.enable()
        self.net: SimNet | None = None

    def check(self, cond: bool, what: str) -> bool:
        if not cond:
            self.failures.append(what)
        return cond

    def finish(self) -> ScenarioResult:
        net = self.net
        try:
            if net is not None:
                try:
                    net.assert_no_fork()
                except AssertionError as e:
                    self.failures.append(str(e))
            res = ScenarioResult(
                name=self.name,
                seed=self.seed,
                ok=not self.failures,
                heights=net.heights() if net is not None else [],
                virtual_ms=(net.clock.now_ns / 1e6) if net is not None else 0,
                events_run=net._events_run if net is not None else 0,
                stats=dict(net.stats) if net is not None else {},
                metrics=commit_metrics(),
                signature=(
                    tuple(net.heights()) if net is not None else (),
                    ring_signature(),
                ),
                failures=self.failures,
                notes=self.notes,
                ring=libhealth.export_ring(),
            )
        finally:
            if net is not None:
                net.stop()
            from ..libs import fail as libfail

            libfail.set_target("")
            libfail.set_handler(None)
            if not self._prev_enabled:
                libhealth.disable()
            libhealth.set_ring_capacity(self._prev_ring)
            if self.home_root is not None:
                shutil.rmtree(self.home_root, ignore_errors=True)
        return res


# -------------------------------------------------------------- behaviors


def equivocate(net: SimNet, byz_idx: int, targets: list[int]) -> None:
    """Make node ``byz_idx`` double-sign: every non-nil prevote it emits
    is shadowed by a validly-signed CONFLICTING prevote delivered to
    ``targets`` only (so the rest of the net can learn of the
    equivocation only through evidence gossip)."""
    import copy

    from ..consensus.messages import VoteMessage
    from ..consensus.reactor import VOTE_CHANNEL
    from ..types import canonical
    from ..types import serialization as ser
    from ..types.block import BlockID, PartSetHeader

    cs = net.nodes[byz_idx].cs
    # cometlint: disable=CLNT011 -- simnet FSMs are sim_driven: no consensus routine exists, every read runs on the single scheduler thread
    pv = cs.priv_validator
    orig = cs._send_internal

    def send(msg, orig=orig):
        orig(msg)
        if not isinstance(msg, VoteMessage):
            return
        vote = msg.vote
        if vote.msg_type != canonical.PREVOTE_TYPE or vote.block_id.is_nil():
            return
        evil = copy.copy(vote)
        evil.block_id = BlockID(
            b"\xEE" * 32, PartSetHeader(total=1, hash=b"\xDD" * 32)
        )
        evil.signature = b""
        # cometlint: disable=CLNT011 -- simnet FSMs are sim_driven: the hooked _send_internal runs on the single scheduler thread
        pv.sign_vote(cs.state.chain_id, evil, sign_extension=False)
        raw = ser.dumps(VoteMessage(evil))
        for j in targets:
            net.inject(byz_idx, j, VOTE_CHANNEL, raw)

    cs._send_internal = send


def flood_invalid_votes(net: SimNet, byz_idx: int) -> None:
    """consensus/invalid_test.go behavior: shadow every own vote with
    malformed variants (garbage signature, out-of-set index, far-future
    round) toward every peer."""
    import copy

    from ..consensus.messages import VoteMessage
    from ..consensus.reactor import VOTE_CHANNEL
    from ..types import serialization as ser

    cs = net.nodes[byz_idx].cs
    orig = cs._send_internal

    def send(msg, orig=orig):
        orig(msg)
        if not isinstance(msg, VoteMessage):
            return
        base = msg.vote
        variants = []
        v1 = copy.copy(base)
        v1.signature = b"\xAB" * 64
        variants.append(v1)
        v2 = copy.copy(base)
        v2.validator_index = 99
        variants.append(v2)
        v3 = copy.copy(base)
        v3.round = base.round + 7
        variants.append(v3)
        for j in range(net.n):
            if j == byz_idx:
                continue
            for v in variants:
                net.inject(byz_idx, j, VOTE_CHANNEL, ser.dumps(VoteMessage(v)))

    cs._send_internal = send


def find_committed_evidence(net: SimNet, node_idx: int):
    """-> (height, [evidence]) of the first committed block carrying
    evidence on ``node_idx``, or None."""
    store = net.nodes[node_idx].block_store
    for h in range(2, store.height() + 1):
        blk = store.load_block(h)
        if blk is not None and blk.evidence:
            return h, list(blk.evidence)
    return None


# -------------------------------------------------------------- scenarios


def scenario_healthy(seed: int, n_nodes: int = 4, heights: int = 5,
                     link: LinkConfig | None = None,
                     topology="mesh", max_virtual_ms: float = 120_000.0,
                     **_):
    run = _Run("healthy", seed)
    net = run.net = SimNet(
        n_nodes, seed=seed, topology=topology,
        default_link=link if link is not None else LinkConfig(),
    )
    net.start()
    ok = net.run_until_height(heights, max_virtual_ms=max_virtual_ms)
    run.check(ok, f"net never reached height {heights}: {net.heights()}")
    return run.finish()


def scenario_byzantine_double_sign(seed: int, n_nodes: int = 4,
                                   heights: int = 5, **_):
    from ..evidence.reactor import EVIDENCE_CHANNEL
    from ..types.evidence import DuplicateVoteEvidence

    run = _Run("byzantine_double_sign", seed)
    net = run.net = SimNet(n_nodes, seed=seed)
    net.start()
    byz = n_nodes - 1
    witness = 1  # the only honest node shown the conflicting votes
    equivocate(net, byz, [witness])
    honest = [i for i in range(n_nodes) if i != byz]

    def done() -> bool:
        if not all(net.nodes[i].height() >= heights for i in honest):
            return False
        return find_committed_evidence(net, honest[0]) is not None

    ok = net.run_until_height(2, nodes=honest, max_virtual_ms=60_000)
    ok = net.run(until=done, max_virtual_ms=240_000) and ok
    run.check(ok, f"no evidence committed by {net.heights()}")
    found = find_committed_evidence(net, honest[0])
    if run.check(found is not None, "no committed evidence block"):
        h, evs = found
        ev = evs[0]
        byz_addr = bytes(net.pvs[byz].get_pub_key().address())
        run.check(
            isinstance(ev, DuplicateVoteEvidence), f"wrong type {type(ev)}"
        )
        run.check(
            bytes(ev.vote_a.validator_address) == byz_addr,
            "evidence names the wrong validator",
        )
        run.check(
            ev.vote_a.block_id != ev.vote_b.block_id,
            "votes do not conflict",
        )
        # the pool marks evidence committed on EVERY node that applied
        # the block — the end of the gossip->verify->commit pipeline
        committed_on = [
            i for i in honest
            if net.nodes[i].core["evidence_pool"].is_committed(ev)
        ]
        run.check(
            len(committed_on) == len(honest),
            f"evidence committed only on {committed_on}",
        )
        run.notes["evidence_height"] = h
    # the non-witness nodes can ONLY have learned via evidence/reactor
    ev_hops = net.stats.get(f"delivered_ch_{EVIDENCE_CHANNEL:#04x}", 0)
    run.check(ev_hops > 0, "evidence channel never carried a message")
    run.notes["evidence_channel_msgs"] = ev_hops
    return run.finish()


def scenario_partition_heal(seed: int, n_nodes: int = 4, **_):
    run = _Run("partition_heal", seed)
    net = run.net = SimNet(n_nodes, seed=seed)
    net.start()
    run.check(
        net.run_until_height(2, max_virtual_ms=60_000),
        f"no baseline progress {net.heights()}",
    )
    # phase 1: full split — BOTH halves lose quorum; rounds must spin
    # without a commit, and no fork may form
    h_split = max(net.heights())
    half = n_nodes // 2
    net.partition(range(half), range(half, n_nodes))
    net.run(max_virtual_ms=3_000)
    run.check(
        max(net.heights()) <= h_split + 1,
        f"committed through a full partition: {net.heights()}",
    )
    net.heal()
    target = h_split + 2
    run.check(
        net.run_until_height(target, max_virtual_ms=120_000),
        f"no convergence after full-split heal: {net.heights()}",
    )
    # phase 2: minority split — the majority side keeps committing; the
    # healed minority must CATCH UP (the reactor's catch-up gossip, the
    # machinery the perfect-gossip harness lacked)
    minority = 0
    net.partition([minority], range(1, n_nodes))
    h_before = net.nodes[minority].height()
    majority = list(range(1, n_nodes))
    run.check(
        net.run_until_height(
            h_before + 3, nodes=majority, max_virtual_ms=120_000
        ),
        f"majority stalled under minority split: {net.heights()}",
    )
    net.heal()
    target = max(net.heights()) + 1
    run.check(
        net.run_until_height(target, max_virtual_ms=120_000),
        f"minority never caught up after heal: {net.heights()}",
    )
    run.notes["minority_caught_up_from"] = h_before
    return run.finish()


def scenario_crash_restart(seed: int, n_nodes: int = 4,
                           crash_point: str = "cs-after-save-block", **_):
    run = _Run("crash_restart", seed, homes=True)
    net = run.net = SimNet(n_nodes, seed=seed, home_root=run.home_root)
    net.start()
    run.check(
        net.run_until_height(2, max_virtual_ms=60_000),
        f"no baseline progress {net.heights()}",
    )
    # a committed tx makes the app hash non-trivial, so the post-replay
    # convergence check below compares real execution state, not the
    # genesis zero-hash
    net.nodes[0].core["mempool"].push_tx(b"crash=restart")
    victim = 2
    net.arm_crash_point(victim, crash_point)
    died = net.run(
        until=lambda: not net.nodes[victim].alive, max_virtual_ms=60_000
    )
    run.check(died, f"crash point {crash_point} never fired")
    net.disarm_crash_point()
    h_dead = net.nodes[victim].height()
    survivors = [i for i in range(n_nodes) if i != victim]
    net.run_until_height(
        h_dead + 2, nodes=survivors, max_virtual_ms=120_000
    )
    net.restart(victim)  # WAL catchup replay inside consensus start
    target = max(net.heights()) + 2
    run.check(
        net.run_until_height(target, max_virtual_ms=240_000),
        f"crashed node never rejoined: {net.heights()}",
    )
    run.check(net.nodes[victim].restarts == 1, "restart not recorded")
    run.notes["crashed_at_height"] = h_dead
    # WAL-replay convergence: after the victim's catchup replay every
    # node must hold the SAME app hash at the last height they all
    # share — the restarted node's re-execution (WAL replay + ABCI
    # handshake) landed on the identical application state the
    # survivors committed.  The hex lands in notes so determinism
    # tests can pin it bit-identical across (seed, scenario) reruns.
    # the tx pushed at node 0 commits once node 0 proposes (round-robin,
    # no mempool gossip in simnet) — advance until the shared height's
    # header carries the resulting non-zero app hash, so the comparison
    # below can never pass vacuously on the genesis zero-hash
    def _tx_reflected() -> bool:
        blk = net.nodes[0].block_store.load_block(min(net.heights()))
        return blk is not None and any(blk.header.app_hash)

    run.check(
        net.run(until=_tx_reflected, max_virtual_ms=240_000),
        f"tx never reflected in a shared app hash: {net.heights()}",
    )
    h_sync = min(net.heights())
    hashes = {
        bytes(net.nodes[i].block_store.load_block(h_sync).header.app_hash)
        for i in range(n_nodes)
    }
    run.check(
        len(hashes) == 1,
        f"app hash diverged at height {h_sync} after replay: "
        f"{sorted(h.hex() for h in hashes)}",
    )
    run.notes["app_hash_height"] = h_sync
    run.notes["app_hash"] = min(hashes).hex()
    return run.finish()


def scenario_valset_churn(seed: int, heights_after: int = 4, **_):
    """4 genesis validators + 1 standby full node; a val-update tx adds
    the standby to the set (the 8_valset_update path end-to-end: tx →
    FinalizeBlock validator_updates → ValidatorSet churn → the new
    validator signs), then a second tx evicts a genesis validator."""
    from ..crypto.keys import Ed25519PrivKey
    from ..types import MockPV

    run = _Run("valset_churn", seed)
    genesis, pvs = make_genesis(4)
    standby_pv = MockPV(Ed25519PrivKey.from_seed(bytes([99]) * 32))
    net = run.net = SimNet(
        5, seed=seed, genesis=genesis, pvs=pvs + [standby_pv]
    )
    net.start()
    run.check(
        net.run_until_height(2, max_virtual_ms=60_000),
        f"no baseline progress {net.heights()}",
    )
    standby_pk = standby_pv.get_pub_key()
    add_tx = b"val:%s!10" % standby_pk.bytes().hex().encode()
    net.nodes[0].core["mempool"].push_tx(add_tx)

    def joined() -> bool:
        # cometlint: disable=CLNT011 -- simnet FSMs are sim_driven: predicates run on the single scheduler thread
        st = net.nodes[0].cs.state
        return st is not None and st.validators.has_address(
            bytes(standby_pk.address())
        )

    run.check(
        net.run(until=joined, max_virtual_ms=120_000),
        f"standby never joined the validator set: {net.heights()}",
    )
    h_joined = max(net.heights())
    run.notes["joined_at_height"] = h_joined
    # the chain must keep committing WITH the 5-validator set — the new
    # validator's signatures now count toward quorum
    run.check(
        net.run_until_height(h_joined + heights_after,
                             max_virtual_ms=240_000),
        f"stall after valset grew: {net.heights()}",
    )
    # the standby must actually be SIGNING now, not just listed: some
    # committed block's last_commit carries its signature
    standby_addr = bytes(standby_pk.address())
    store = net.nodes[0].block_store

    def standby_signed() -> bool:
        for h in range(h_joined, store.height() + 1):
            blk = store.load_block(h)
            if blk is None or blk.last_commit is None:
                continue
            for sig in blk.last_commit.signatures:
                if (
                    sig.signature
                    and bytes(sig.validator_address) == standby_addr
                ):
                    return True
        return False

    run.check(standby_signed(), "standby listed but never signed a commit")
    # now evict genesis validator 3 (power 0 = removal)
    evict_pk = pvs[3].get_pub_key()
    net.nodes[0].core["mempool"].push_tx(
        b"val:%s!0" % evict_pk.bytes().hex().encode()
    )

    def evicted() -> bool:
        # cometlint: disable=CLNT011 -- simnet FSMs are sim_driven: predicates run on the single scheduler thread
        st = net.nodes[0].cs.state
        return st is not None and not st.validators.has_address(
            bytes(evict_pk.address())
        )

    run.check(
        net.run(until=evicted, max_virtual_ms=120_000),
        "genesis validator never evicted",
    )
    h_evict = max(net.heights())
    run.check(
        net.run_until_height(h_evict + 2, max_virtual_ms=120_000),
        f"stall after eviction: {net.heights()}",
    )
    run.notes["evicted_at_height"] = h_evict
    # cometlint: disable=CLNT011 -- simnet FSMs are sim_driven: reads run on the single scheduler thread
    final_st = net.nodes[0].cs.state
    run.notes["final_valset_size"] = len(final_st.validators.validators)
    return run.finish()


def scenario_blocksync_catchup(seed: int, n_nodes: int = 4, **_):
    """Churn + blocksync: a killed node rejoins via the blocksync pool,
    losing one serving peer mid-sync, then switches to consensus and
    restores quorum."""
    run = _Run("blocksync_catchup", seed, homes=True)
    net = run.net = SimNet(n_nodes, seed=seed, home_root=run.home_root)
    net.start()
    run.check(
        net.run_until_height(2, max_virtual_ms=60_000),
        f"no baseline progress {net.heights()}",
    )
    straggler, lost_peer = 3, 1
    net.kill(straggler)
    survivors = [i for i in range(n_nodes) if i != straggler]
    run.check(
        net.run_until_height(7, nodes=survivors, max_virtual_ms=240_000),
        f"survivors stalled: {net.heights()}",
    )
    net.restart(straggler, block_sync=True)

    def mid_sync() -> bool:
        return net.nodes[straggler].height() >= 4

    run.check(
        net.run(until=mid_sync, max_virtual_ms=120_000),
        f"blocksync never progressed: {net.heights()}",
    )
    # peer loss mid-sync: 2 validators left — consensus halts, but the
    # pool re-picks and finishes from the remaining stores
    net.kill(lost_peer)
    bsr = net.nodes[straggler].core["reactors"]["blocksync"]
    run.check(
        net.run(until=lambda: bsr.synced.is_set(), max_virtual_ms=240_000),
        f"blocksync never switched to consensus: {net.heights()}",
    )
    run.notes["blocks_synced"] = bsr._n_synced
    run.check(bsr._n_synced > 0, "pool applied no blocks")
    # straggler back in consensus restores quorum (3/4) — the chain
    # must advance again
    live = [i for i in range(n_nodes) if net.nodes[i].alive]
    target = max(net.heights()) + 2
    run.check(
        net.run_until_height(target, nodes=live, max_virtual_ms=240_000),
        f"no progress after straggler rejoined: {net.heights()}",
    )
    net.restart(lost_peer)
    run.check(
        net.run_until_height(target, max_virtual_ms=240_000),
        f"lost peer never converged after restart: {net.heights()}",
    )
    return run.finish()


# ---------------------------------------------------- gray failures


def scenario_gray_partition(seed: int, n_nodes: int = 4,
                            heights_after: int = 3, **_):
    """Asymmetric (one-directional) partition: node 0's messages to
    node 1 vanish while 1 -> 0 stays alive and BOTH ends keep the
    connection.  Consensus must keep committing — node 1 still learns
    0's votes via relay through the other peers (the consensus
    reactor's ordinary vote gossip) — and heal() restores the severed
    direction."""
    run = _Run("gray_partition", seed)
    net = run.net = SimNet(n_nodes, seed=seed)
    net.start()
    run.check(
        net.run_until_height(2, max_virtual_ms=60_000),
        f"no baseline progress {net.heights()}",
    )
    h_sever = max(net.heights())
    net.sever_oneway(0, 1)
    # liveness THROUGH the gray failure: every node, including the
    # half-deaf node 1, keeps committing
    run.check(
        net.run_until_height(
            h_sever + heights_after, max_virtual_ms=240_000
        ),
        f"stall under one-way sever: {net.heights()}",
    )
    eaten = net.stats.get("drop_partition", 0)
    run.check(eaten > 0, "the dead direction never ate a message")
    run.notes["oneway_drops"] = eaten
    net.heal()
    target = max(net.heights()) + 2
    run.check(
        net.run_until_height(target, max_virtual_ms=120_000),
        f"no progress after heal: {net.heights()}",
    )
    # both directions live again: no NEW drop_partition classifications
    run.check(
        net.stats.get("drop_partition", 0) == eaten
        or net.stats.get("drop_partition", 0) <= eaten + 2,
        "dead-direction drops kept accruing after heal",
    )
    return run.finish()


def scenario_slow_disk(seed: int, n_nodes: int = 4, latency_ms: int = 120,
                       jitter_ms: int = 30, heights_after: int = 4, **_):
    """One validator's disk turns slow-but-alive: every WAL fsync and
    store write on node 1 charges ``latency_ms`` (± jitter) of virtual
    time (libs/fail delay points on the sim clock).  The chain SLOWS —
    the laggard's votes and proposals hit the wire late, its proposal
    rounds may expire — but must never stall; the laggard falls
    observably behind the committing quorum and catches back up once
    the disk heals."""
    run = _Run("slow_disk", seed, homes=True)
    net = run.net = SimNet(n_nodes, seed=seed, home_root=run.home_root)
    net.start()
    run.check(
        net.run_until_height(2, max_virtual_ms=60_000),
        f"no baseline progress {net.heights()}",
    )
    victim = 1
    survivors = [i for i in range(n_nodes) if i != victim]
    h_slow = max(net.heights())
    t_slow = net.clock.now_ns
    ms = 1_000_000
    net.set_slow_disk(victim, latency_ms * ms, jitter_ms * ms)
    # liveness claim: the CHAIN keeps committing (quorum without the
    # laggard; its proposal rounds expire and rotate) — measured on the
    # survivors, because the victim itself crawls at disk speed
    run.check(
        net.run_until_height(
            h_slow + heights_after, nodes=survivors,
            max_virtual_ms=600_000,
        ),
        f"chain STALLED under a slow disk: {net.heights()}",
    )
    slow_virtual_ms = (net.clock.now_ns - t_slow) / 1e6
    # the laggard is OBSERVABLE: it fell behind the committing quorum
    run.check(
        net.nodes[victim].height() < max(net.heights()),
        f"victim never lagged: {net.heights()}",
    )
    run.notes["victim_lag_heights"] = (
        max(net.heights()) - net.nodes[victim].height()
    )
    net.set_slow_disk(victim, 0)
    # recovery: the healed laggard catches back up to the tip first —
    # only THEN does the healthy-phase clock start, so the laggard's
    # catch-up rounds (its proposer slots expire until it reaches the
    # tip) are not charged to the healthy baseline the fault phase is
    # compared against
    run.check(
        net.run(
            until=lambda: (
                net.nodes[victim].height() >= max(net.heights())
            ),
            max_virtual_ms=240_000,
        ),
        f"laggard never caught up after the disk healed: {net.heights()}",
    )
    h_clear = max(net.heights())
    t_clear = net.clock.now_ns
    # ...and the whole net advances together
    run.check(
        net.run_until_height(h_clear + heights_after,
                             max_virtual_ms=240_000),
        f"no recovery after the disk healed: {net.heights()}",
    )
    clear_virtual_ms = (net.clock.now_ns - t_clear) / 1e6
    run.notes["slow_phase_ms_per_height"] = round(
        slow_virtual_ms / heights_after, 1
    )
    run.notes["healthy_phase_ms_per_height"] = round(
        clear_virtual_ms / heights_after, 1
    )
    # the fault must have COST something: real virtual latency charged
    # at the delay points (the wall-clock phase comparison above stays
    # a NOTE — whether one laggard's expired propose rounds slow the
    # survivors' 4-height window beyond cadence noise is seed-luck,
    # and the tier-1 smoke pins the slowdown at its fixed seed)
    run.notes["disk_delay_ms"] = round(
        net.stats.get("disk_delay_ns", 0) / 1e6, 1
    )
    run.check(
        net.stats.get("disk_delay_ns", 0) > 0,
        "slow disk charged no virtual latency at the delay points",
    )
    return run.finish()


def scenario_statesync_join(seed: int, n_nodes: int = 5,
                            pre_heights: int = 12,
                            tail_heights: int = 3,
                            snapshot_interval: int = 5, **_):
    """A fresh full node joins a grown chain mid-run through the real
    statesync path: snapshot discovery over channel 0x60, app offer,
    chunk fetch over 0x61 (surviving an injected chunk-peer failure
    via the fetch plan's peer rotation), light-client verification of
    the restored app hash against a height-1 trust root served by the
    live peers' stores, then blocksync to the tip and consensus
    follow.  The pre-snapshot blocks are never fetched — the proof the
    restore came from the snapshot, not replay."""
    import dataclasses

    from ..abci.kvstore import KVStoreApplication
    from ..config import test_config
    from ..statesync.messages import CHUNK_CHANNEL

    run = _Run("statesync_join", seed)
    joiner = n_nodes - 1
    genesis, pvs = make_genesis(n_nodes - 1)
    # Slower (latency-tolerant) consensus timeouts: the statesync
    # machinery runs on 100s-of-ms virtual timescales (chunk timeouts,
    # rotation backoff) — with millisecond heights the app would prune
    # the advertised snapshot mid-restore and turn the scenario into a
    # permanent stale-chase.  ~200 ms heights keep the snapshot window
    # (snapshot_interval * 2 heights) comfortably wider than one full
    # fetch-rotate-fetch cycle.
    ms = 1_000_000
    cfg = test_config()
    cfg.consensus = dataclasses.replace(
        cfg.consensus,
        timeout_propose_ns=150 * ms,
        timeout_propose_delta_ns=50 * ms,
        timeout_prevote_ns=80 * ms,
        timeout_prevote_delta_ns=40 * ms,
        timeout_precommit_ns=80 * ms,
        timeout_precommit_delta_ns=40 * ms,
        timeout_commit_ns=20 * ms,
        skip_timeout_commit=False,
        # match the gossip cadence to the slower heights: 5 ms ticks
        # against 200 ms heights would quadruple the event count for
        # zero protocol effect
        peer_gossip_sleep_duration_ns=20 * ms,
        peer_query_maj23_sleep_duration_ns=40 * ms,
    )
    net = run.net = SimNet(
        n_nodes, seed=seed, config=cfg, genesis=genesis, pvs=pvs,
        late=(joiner,),
        app_factory=lambda idx: KVStoreApplication(
            snapshot_interval=snapshot_interval
        ),
    )
    net.start()
    run.check(
        net.run_until_height(
            pre_heights, nodes=list(range(n_nodes - 1)),
            max_virtual_ms=600_000,
        ),
        f"chain never grew to {pre_heights}: {net.heights()}",
    )
    # gray chunk peer: node 0 answers snapshot offers but its chunk
    # RESPONSES vanish — the fetch plan must time out, charge node 0 a
    # failure, and rotate to the next serving peer
    net.set_link(0, joiner, symmetric=False,
                 drop_channels=frozenset({CHUNK_CHANNEL}))
    syncer = net.join_statesync(joiner, trust_height=1,
                                chunk_timeout_s=0.5)
    bsr = None

    def switched() -> bool:
        node = net.nodes[joiner]
        return (
            node.alive
            and node.statesync_state["phase"] == "switched"
        )

    run.check(
        net.run(until=switched, max_virtual_ms=600_000),
        f"statesync never switched to blocksync: "
        f"{net.nodes[joiner].statesync_state if net.nodes[joiner].core else None}",
    )
    if net.nodes[joiner].core is not None:
        bsr = net.nodes[joiner].core["reactors"]["blocksync"]
        run.check(
            net.run(
                until=lambda: bsr.synced.is_set(), max_virtual_ms=600_000
            ),
            f"blocksync tail never finished: {net.heights()}",
        )
        snap_h = net.nodes[joiner].statesync_state["snapshot"].height
        run.notes["snapshot_height"] = snap_h
        run.notes["blocks_synced"] = bsr._n_synced
        run.notes["chunk_peer_rotations"] = syncer.fetch_rotations()
        # the defense was exercised: at least one chunk-peer failure
        # survived via rotation
        run.check(
            syncer.fetch_rotations() >= 1,
            "no chunk-peer rotation happened (gray peer unexercised)",
        )
        # statesync, not replay: the early blocks were never fetched
        run.check(
            net.nodes[joiner].block_store.load_block(2) is None,
            "joiner fetched pre-snapshot blocks (blocksync-from-genesis?)",
        )
        run.check(
            net.nodes[joiner].block_store.height() >= snap_h,
            f"joiner below snapshot height: {net.heights()}",
        )
    # the joined node must now FOLLOW consensus with the validators
    target = max(net.heights()) + tail_heights
    run.check(
        net.run_until_height(target, max_virtual_ms=600_000),
        f"joiner does not follow consensus: {net.heights()}",
    )
    return run.finish()


def scenario_mempool_storm(seed: int, n_nodes: int = 4, rate: int = 2000,
                           burst: int = 10, storm_heights: int = 6, **_):
    """Sustained CheckTx-pressure analog through commit churn: a
    high-rate seeded load generator floods every node's mempool for
    the whole run.  The chain must keep committing, blocks must carry
    txs, and committed txs must drain from every mempool (the commit
    churn path) — pressure degrades throughput, never liveness."""
    from ..e2e.load import SimLoadGenerator, sim_load_report

    run = _Run("mempool_storm", seed)
    net = run.net = SimNet(n_nodes, seed=seed)
    net.start()
    run.check(
        net.run_until_height(2, max_virtual_ms=60_000),
        f"no baseline progress {net.heights()}",
    )
    gen = SimLoadGenerator(
        net, rate=rate, burst=burst, run_id=f"storm-{seed}"
    )
    net.mark_storm(rate)
    gen.start()
    h0 = max(net.heights())
    run.check(
        net.run_until_height(h0 + storm_heights, max_virtual_ms=600_000),
        f"chain stalled under the storm: {net.heights()}",
    )
    gen.stop()
    net.mark_storm(0)
    rep = sim_load_report(net, gen.run_id)
    run.notes["txs_sent"] = gen.sent
    run.notes["txs_committed"] = rep.txs
    run.notes["tx_latency_p50_ms"] = (
        round(rep.quantile(0.5) * 1e3, 1) if rep.latencies_s else None
    )
    run.check(rep.txs > 0, "no storm tx ever committed")
    # commit churn: committed txs must leave the mempools
    sizes = [
        n.core["mempool"].size() for n in net.nodes if n.core is not None
    ]
    run.notes["mempool_sizes"] = sizes
    run.check(
        all(s < gen.sent for s in sizes),
        f"mempools never drained: {sizes}",
    )
    return run.finish()


SCENARIOS = {
    "healthy": scenario_healthy,
    "byzantine_double_sign": scenario_byzantine_double_sign,
    "partition_heal": scenario_partition_heal,
    "crash_restart": scenario_crash_restart,
    "valset_churn": scenario_valset_churn,
    "blocksync_catchup": scenario_blocksync_catchup,
    "gray_partition": scenario_gray_partition,
    "slow_disk": scenario_slow_disk,
    "statesync_join": scenario_statesync_join,
    "mempool_storm": scenario_mempool_storm,
}


def run_scenario(name: str, seed: int, **kw) -> ScenarioResult:
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}"
        ) from None
    return fn(seed, **kw)
