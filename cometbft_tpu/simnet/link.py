"""Virtual links: the programmable fault vocabulary of the simnet.

Each DIRECTED node pair gets one :class:`Link` carrying a
:class:`LinkConfig` — per-link latency/jitter, drop and reorder
probability, a bandwidth cap, and message-class filters — plus its own
child rng, so editing one link's faults never perturbs another link's
random schedule (scenario events stay composable under one seed).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace


@dataclass(frozen=True)
class LinkConfig:
    """Fault parameters for one directed link (all virtual-time ns).

    ``drop_p``/``reorder_p`` are per-message probabilities;
    ``bandwidth_bps`` of 0 means uncapped; ``drop_channels`` silently
    eats whole p2p channels (e.g. blocksync 0x40); ``drop_classes``
    eats decoded message classes by name (e.g. "VoteMessage") — the
    scalpel for scenarios like "lose only block parts".
    """

    latency_ns: int = 2_000_000  # 2 ms one-hop base
    jitter_ns: int = 500_000
    drop_p: float = 0.0
    dup_p: float = 0.0
    reorder_p: float = 0.0
    reorder_window_ns: int = 20_000_000
    bandwidth_bps: int = 0
    drop_channels: frozenset[int] = field(default_factory=frozenset)
    drop_classes: frozenset[str] = field(default_factory=frozenset)

    def with_(self, **kw) -> "LinkConfig":
        return replace(self, **kw)


# delivery-plan outcomes (stats keys + EV_FAULT detail codes)
DROP_RANDOM = "drop_random"
DROP_CHANNEL = "drop_channel"
DROP_CLASS = "drop_class"
DROP_PARTITION = "drop_partition"
DROP_DEAD = "drop_dead"


class Link:
    """One directed link's live state: config + bandwidth busy horizon."""

    __slots__ = ("cfg", "rng", "busy_until_ns")

    def __init__(self, cfg: LinkConfig, rng):
        self.cfg = cfg
        self.rng = rng
        self.busy_until_ns = 0

    def plan(self, now_ns: int, ch_id: int, size: int):
        """Decide one message's fate.  Returns ``(deliver_at_ns,
        dup_at_ns | None, None)`` or ``(None, None, drop_reason)`` —
        ``dup_at_ns`` is a second delivery time when the link duplicated
        the message.  Consumes rng draws in a FIXED order regardless of
        outcome, so one dropped message doesn't shift the random
        schedule of every later one."""
        cfg = self.cfg
        r_drop = self.rng.random() if cfg.drop_p > 0 else 1.0
        r_dup = self.rng.random() if cfg.dup_p > 0 else 1.0
        r_jit = self.rng.random() if cfg.jitter_ns > 0 else 0.0
        r_reord = self.rng.random() if cfg.reorder_p > 0 else 1.0
        r_win = self.rng.random() if cfg.reorder_p > 0 else 0.0
        if ch_id in cfg.drop_channels:
            return None, None, DROP_CHANNEL
        if r_drop < cfg.drop_p:
            return None, None, DROP_RANDOM
        start = max(now_ns, self.busy_until_ns)
        if cfg.bandwidth_bps > 0:
            tx_ns = int(size * 8 * 1e9 / cfg.bandwidth_bps)
            self.busy_until_ns = start + tx_ns
            start += tx_ns
        deliver = start + cfg.latency_ns + int(r_jit * cfg.jitter_ns)
        if r_reord < cfg.reorder_p:
            deliver += int(r_win * cfg.reorder_window_ns)
        dup_at = None
        if r_dup < cfg.dup_p:
            # the copy trails the original by up to one reorder window
            dup_at = deliver + int(
                (r_dup / max(cfg.dup_p, 1e-12)) * cfg.reorder_window_ns
            )
        return deliver, dup_at, None
