"""Deterministic fault-injection network simulator (simnet).

N full in-process nodes — real consensus/evidence/blocksync reactors —
over seeded virtual links with programmable faults, driven by one
discrete-event scheduler in virtual time: every run is a pure function
of ``(seed, scenario)``.  See docs/simnet.md.
"""

from .link import Link, LinkConfig  # noqa: F401
from .net import SimNet, make_genesis  # noqa: F401
from .sched import SimClock, SimScheduler  # noqa: F401
