"""L3 block storage (reference: store/store.go)."""

from .block_store import BlockStore  # noqa: F401
