"""Persistent block store (reference: store/store.go:38-656).

Stores blocks *as part sets* (the gossip unit), plus per-height metadata,
the canonical commit for each block (extracted from the next block's
LastCommit), the latest seen commit, and extended commits when vote
extensions are enabled. A hash→height index serves lookups by block hash.

Key layout (fixed-width heights so raw-byte iteration is height order):
``BM:<h>`` meta | ``P:<h>:<i>`` part | ``C:<h>`` commit | ``SC`` seen
commit | ``EC:<h>`` extended commit | ``BH:<hash>`` height | ``BS`` state.
"""

from __future__ import annotations

import json
from ..libs import sync as libsync

from ..libs import db as dbm
from ..libs import fail as libfail
from ..types import serialization as ser
from ..types.block import Block, BlockID, BlockMeta, Commit
from ..types.part_set import Part, PartSet


def _h(prefix: bytes, height: int) -> bytes:
    return prefix + b"%020d" % height


class BlockStore:
    def __init__(self, db: dbm.DB):
        self.db = db
        self._mtx = libsync.RLock("store.block_store._mtx")
        raw = db.get(b"BS")
        if raw:
            st = json.loads(raw)
            self._base, self._height = st["base"], st["height"]
        else:
            self._base, self._height = 0, 0

    # -- bookkeeping -------------------------------------------------------

    def base(self) -> int:
        with self._mtx:
            return self._base

    def height(self) -> int:
        with self._mtx:
            return self._height

    def size(self) -> int:
        with self._mtx:
            return 0 if self._height == 0 else self._height - self._base + 1

    def _save_state(self, batch) -> None:
        batch.set(
            b"BS",
            json.dumps({"base": self._base, "height": self._height}).encode(),
        )

    # -- save --------------------------------------------------------------

    def save_block(
        self, block: Block, part_set: PartSet, seen_commit: Commit
    ) -> None:
        # slow-disk injection point (libs/fail delay_point): the simnet
        # gray-failure scenarios charge virtual latency here, modeling a
        # store volume that persists blocks slowly but successfully
        libfail.delay_point("store-write")
        with self._mtx:  # cometlint: disable=CLNT009 -- block persistence is atomic under the store mutex; once per height
            self._save_block_locked(block, part_set, seen_commit, None)

    def save_block_with_extended_commit(
        self, block: Block, part_set: PartSet, seen_ext_commit
    ) -> None:
        with self._mtx:  # cometlint: disable=CLNT009 -- extended-commit save shares save_block's atomicity contract
            self._save_block_locked(
                block, part_set, seen_ext_commit.to_commit(), seen_ext_commit
            )

    def _save_block_locked(
        self, block, part_set, seen_commit, ext_commit
    ) -> None:
        height = block.header.height
        if self._height > 0 and height != self._height + 1:
            raise ValueError(
                f"cannot save block {height}, expected {self._height + 1}"
            )
        batch = self.db.new_batch()
        block_id = BlockID(block.hash(), part_set.header)
        meta = BlockMeta(
            block_id=block_id,
            block_size=sum(len(p.bytes_) for p in part_set.parts),
            header=block.header,
            num_txs=len(block.data.txs),
        )
        batch.set(_h(b"BM:", height), ser.dumps(meta))
        for part in part_set.parts:
            batch.set(
                _h(b"P:", height) + b":%06d" % part.index, ser.dumps(part)
            )
        if block.last_commit is not None:
            batch.set(_h(b"C:", height - 1), ser.dumps(block.last_commit))
        batch.set(b"SC", ser.dumps(seen_commit))
        if ext_commit is not None:
            batch.set(_h(b"EC:", height), ser.dumps(ext_commit))
        batch.set(b"BH:" + block_id.hash, b"%d" % height)
        if self._base == 0:
            self._base = height
        self._height = height
        libsync.lockset_note("BlockStore._height")
        self._save_state(batch)
        batch.write_sync()

    def save_seen_commit(self, seen_commit: Commit) -> None:
        self.db.set_sync(b"SC", ser.dumps(seen_commit))

    # -- load --------------------------------------------------------------

    def load_block_meta(self, height: int) -> BlockMeta | None:
        raw = self.db.get(_h(b"BM:", height))
        return ser.loads(raw) if raw else None

    def load_block_part(self, height: int, index: int) -> Part | None:
        raw = self.db.get(_h(b"P:", height) + b":%06d" % index)
        return ser.loads(raw) if raw else None

    def load_block(self, height: int) -> Block | None:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        buf = []
        for i in range(meta.block_id.part_set_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            buf.append(part.bytes_)
        return ser.loads(b"".join(buf))

    def load_block_by_hash(self, block_hash: bytes) -> Block | None:
        raw = self.db.get(b"BH:" + block_hash)
        return self.load_block(int(raw)) if raw else None

    def load_block_meta_by_hash(self, block_hash: bytes):
        """Meta-only hash lookup: one small read via the BH: index —
        header consumers must not pay the O(parts) full-block reassembly."""
        raw = self.db.get(b"BH:" + block_hash)
        return self.load_block_meta(int(raw)) if raw else None

    def load_block_commit(self, height: int) -> Commit | None:
        """The canonical commit FOR block ``height`` (from block height+1)."""
        raw = self.db.get(_h(b"C:", height))
        return ser.loads(raw) if raw else None

    def load_seen_commit(self) -> Commit | None:
        raw = self.db.get(b"SC")
        return ser.loads(raw) if raw else None

    def load_block_extended_commit(self, height: int):
        raw = self.db.get(_h(b"EC:", height))
        return ser.loads(raw) if raw else None

    # -- prune -------------------------------------------------------------

    def delete_block(self, height: int) -> None:
        """Remove the block at ``height`` — only the TIP may be removed
        (rollback --hard).

        The NEW tip's canonical commit (``C:<height-1>``) must survive —
        it arrived inside the deleted block as its LastCommit and becomes
        the new seen commit, so a restarted node can still reconstruct
        rs.last_commit and propose."""
        with self._mtx:  # cometlint: disable=CLNT009 -- delete_block rewrites base/height atomically; rare rollback path
            if height != self._height:
                raise ValueError(
                    f"can only delete the tip block ({self._height}), "
                    f"got {height}"
                )
            meta = self.load_block_meta(height)
            block = self.load_block(height)
            batch = self.db.new_batch()
            if meta is not None:
                for i in range(meta.block_id.part_set_header.total):
                    batch.delete(_h(b"P:", height) + b":%06d" % i)
                batch.delete(b"BH:" + meta.block_id.hash)
            batch.delete(_h(b"BM:", height))
            batch.delete(_h(b"EC:", height))
            if block is not None and block.last_commit is not None:
                batch.set(b"SC", ser.dumps(block.last_commit))
            self._height = height - 1
            if self._base > self._height:
                self._base = self._height
            self._save_state(batch)
            batch.write()

    def prune_blocks(self, retain_height: int) -> int:
        """Delete blocks below ``retain_height``; returns number pruned
        (store/store.go:293). Keeps the commit chain above the new base."""
        with self._mtx:  # cometlint: disable=CLNT009 -- pruning updates base/height atomically; operator-paced
            if retain_height <= self._base:
                return 0
            if retain_height > self._height:
                raise ValueError("cannot prune beyond store height")
            pruned = 0
            batch = self.db.new_batch()
            for height in range(self._base, retain_height):
                meta = self.load_block_meta(height)
                if meta is None:
                    continue
                batch.delete(_h(b"BM:", height))
                batch.delete(b"BH:" + meta.block_id.hash)
                batch.delete(_h(b"C:", height - 1))
                for i in range(meta.block_id.part_set_header.total):
                    batch.delete(_h(b"P:", height) + b":%06d" % i)
                batch.delete(_h(b"EC:", height))
                pruned += 1
            self._base = retain_height
            self._save_state(batch)
            batch.write_sync()
            return pruned
