"""Load generation + latency report (reference: test/loadtime).

The generator posts self-describing transactions
(``load:<run_id>:<seq>:<send_time_ns>:<padding>``) through
``broadcast_tx_async`` over N connections at a target rate — the shape
tm-load-test drives. The report walks committed blocks over RPC and
computes per-tx latency as block_time - send_time (loadtime's
block-timestamp method: report/report.go), so it needs no clock on the
node, only that generator and reporter share one.
"""

from __future__ import annotations

import base64
import threading
from ..libs import sync as libsync
import time
from dataclasses import dataclass, field

from ..rpc.client import HTTPClient
from ..rpc.decoding import parse_rfc3339

TX_PREFIX = b"load:"


def make_tx(
    run_id: str, seq: int, size: int = 64, now_ns: int | None = None
) -> bytes:
    """``now_ns`` overrides the embedded send stamp (the simnet tier
    stamps virtual time so latency math stays on one clock)."""
    if now_ns is None:
        now_ns = time.time_ns()
    body = b"load:%s:%d:%d:" % (run_id.encode(), seq, now_ns)
    pad = max(0, size - len(body))
    # kvstore txs are key=value; key must be unique per tx so each lands
    return body + b"x" * pad + b"=1"


def parse_tx(tx: bytes) -> tuple[str, int, int] | None:
    """-> (run_id, seq, send_time_ns) for load txs, else None."""
    if not tx.startswith(TX_PREFIX):
        return None
    try:
        parts = tx.split(b":", 4)
        return parts[1].decode(), int(parts[2]), int(parts[3])
    except (IndexError, ValueError):
        return None


class LoadGenerator:
    """Posts load txs at ``rate`` tx/s split across ``connections``
    worker threads (tm-load-test's -r / -c knobs)."""

    def __init__(
        self,
        endpoints: list[str],
        rate: int = 100,
        connections: int = 1,
        tx_size: int = 64,
        run_id: str | None = None,
    ):
        self.endpoints = endpoints
        self.rate = rate
        self.connections = connections
        self.tx_size = tx_size
        self.run_id = run_id or f"r{int(time.time()) % 100000}"
        self.sent = 0
        self.errors = 0
        self._seq = 0
        self._mtx = libsync.Mutex("e2e.load._mtx")
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _next_seq(self) -> int:
        with self._mtx:
            self._seq += 1
            return self._seq

    def _worker(self, idx: int) -> None:
        client = HTTPClient(self.endpoints[idx % len(self.endpoints)])
        interval = self.connections / max(self.rate, 1)
        next_at = time.monotonic()
        while not self._stop.is_set():
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(next_at - now, 0.05))
                continue
            next_at += interval
            tx = make_tx(self.run_id, self._next_seq(), self.tx_size)
            try:
                client.call(
                    "broadcast_tx_async",
                    tx=base64.b64encode(tx).decode(),
                )
                with self._mtx:
                    self.sent += 1
            except Exception:
                with self._mtx:
                    self.errors += 1
                time.sleep(0.2)

    def start(self) -> None:
        self._stop.clear()
        self._threads = [
            threading.Thread(
                target=self._worker, args=(i,), daemon=True,
                name=f"load-{i}",
            )
            for i in range(self.connections)
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(2.0)

    def run_for(self, seconds: float) -> None:
        self.start()
        time.sleep(seconds)
        self.stop()


@dataclass
class LoadReport:
    """Latency stats from block timestamps (report/report.go)."""

    run_id: str
    txs: int = 0
    blocks: int = 0
    first_height: int = 0
    last_height: int = 0
    latencies_s: list = field(default_factory=list)

    @property
    def mean_s(self) -> float:
        return (
            sum(self.latencies_s) / len(self.latencies_s)
            if self.latencies_s
            else 0.0
        )

    def quantile(self, q: float) -> float:
        if not self.latencies_s:
            return 0.0
        xs = sorted(self.latencies_s)
        return xs[min(int(q * len(xs)), len(xs) - 1)]

    def summary(self) -> dict:
        return {
            "run_id": self.run_id,
            "txs": self.txs,
            "blocks": self.blocks,
            "heights": [self.first_height, self.last_height],
            "latency_mean_s": round(self.mean_s, 3),
            "latency_p50_s": round(self.quantile(0.5), 3),
            "latency_p99_s": round(self.quantile(0.99), 3),
            "latency_max_s": round(max(self.latencies_s or [0.0]), 3),
        }


def block_interval_stats(
    endpoint: str, from_height: int = 1, to_height: int | None = None
) -> dict:
    """Block-production statistics over committed headers
    (test/e2e/runner/benchmark.go:14-45: mean/std/min/max interval)."""
    client = HTTPClient(endpoint)
    if to_height is None:
        to_height = int(
            client.call("status")["sync_info"]["latest_block_height"]
        )
    times = []
    for h in range(from_height, to_height + 1):
        hdr = client.call("header", height=h)["header"]
        times.append(parse_rfc3339(hdr["time"]) / 1e9)
    intervals = [b - a for a, b in zip(times, times[1:])]
    if not intervals:
        return {"blocks": len(times), "intervals": 0}
    mean = sum(intervals) / len(intervals)
    var = sum((x - mean) ** 2 for x in intervals) / len(intervals)
    return {
        "blocks": len(times),
        "intervals": len(intervals),
        "interval_mean_s": round(mean, 3),
        "interval_std_s": round(var**0.5, 3),
        "interval_min_s": round(min(intervals), 3),
        "interval_max_s": round(max(intervals), 3),
    }


class EventLoadMonitor:
    """Per-tx commit latency via a WebSocket Tx-event subscription.

    The block-walk report (:func:`load_report`) measures latency against
    BLOCK timestamps — the proposer's clock, quantized to commit times.
    This monitor subscribes to ``tm.event = 'Tx'`` (the reference's
    loadtime does the same through rpc/client Subscribe,
    rpc/client/http/http.go:790) and records latency when the node
    DELIVERS the commit event: send -> observed-committed on one clock,
    including event-delivery lag, per tx rather than per block.

    Use around a LoadGenerator run::

        mon = EventLoadMonitor(endpoint, run_id)   # subscribes now
        gen.run_for(8)
        rep = mon.finish(drain_s=3.0)              # LoadReport
    """

    def __init__(self, endpoint: str, run_id: str):
        from ..rpc.client import WSClient

        self.run_id = run_id
        self._ws = WSClient(endpoint)
        self._sub = self._ws.subscribe("tm.event = 'Tx'")
        self._report = LoadReport(run_id=run_id)
        self._stop = threading.Event()
        self._heights: set[int] = set()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self) -> None:
        while not self._stop.is_set():
            ev = self._sub.recv(timeout=0.3)
            if ev is None:
                continue
            try:
                txr = ev["data"]["value"]["TxResult"]
                tx = base64.b64decode(txr["tx"])
                height = int(txr["height"])
            except (KeyError, ValueError):
                continue
            parsed = parse_tx(tx)
            if parsed is None or parsed[0] != self.run_id:
                continue
            now_ns = time.time_ns()
            rep = self._report
            rep.txs += 1
            rep.latencies_s.append((now_ns - parsed[2]) / 1e9)
            if height not in self._heights:
                self._heights.add(height)
                rep.blocks += 1
            rep.last_height = max(rep.last_height, height)
            rep.first_height = (
                height
                if not rep.first_height
                else min(rep.first_height, height)
            )

    def finish(self, drain_s: float = 3.0) -> LoadReport:
        """Allow in-flight commits to surface, then close and report."""
        time.sleep(drain_s)
        self._stop.set()
        self._thread.join(2.0)
        try:
            self._ws.close()
        except Exception:
            pass
        return self._report


class SimLoadGenerator:
    """Load generation for the ``--simnet`` tier: txs are pushed into
    the sim nodes' mempools on VIRTUAL-time ticks (no sockets, no
    threads), stamped with the net's virtual clock, at ``rate`` tx/s of
    virtual time round-robined across ``targets``.  Deterministic under
    the net's seed like everything else on the scheduler."""

    def __init__(self, net, rate: int = 100, tx_size: int = 64,
                 run_id: str = "simload", targets: list[int] | None = None,
                 burst: int = 1):
        self.net = net
        self.rate = max(1, rate)
        self.tx_size = tx_size
        self.run_id = run_id
        self.targets = (
            list(targets) if targets is not None
            else [n.idx for n in net.nodes]
        )
        self.sent = 0
        self._seq = 0
        self._stopped = False
        # storm mode: ``burst`` txs pushed per tick, so a sustained
        # thousands-of-tx/s mempool storm costs rate/burst scheduler
        # events per virtual second instead of one event per tx — the
        # pressure is identical (the mempool sees the same tx stream
        # per virtual instant), the event heap stays tractable
        self.burst = max(1, burst)
        self._interval_ns = int(self.burst * 1e9 / self.rate)

    def start(self) -> None:
        self._stopped = False
        self.net.sched.call_after(self._interval_ns, self._tick)

    def stop(self) -> None:
        self._stopped = True

    def _tick(self) -> None:
        if self._stopped:
            return
        for _ in range(self.burst):
            # rotate past dead targets: a killed node must cost ITS
            # txs, not wedge the whole generator on one slot
            for _ in range(len(self.targets)):
                idx = self.targets[self._seq % len(self.targets)]
                self._seq += 1
                node = self.net.nodes[idx]
                if node.alive and node.core is not None:
                    node.core["mempool"].push_tx(
                        make_tx(
                            self.run_id, self._seq, self.tx_size,
                            now_ns=self.net.clock.time_ns(),
                        )
                    )
                    self.sent += 1
                    break
        self.net.sched.call_after(self._interval_ns, self._tick)


def sim_load_report(net, run_id: str, node_idx: int = 0) -> LoadReport:
    """Block-walk latency report over a sim node's store (the
    :func:`load_report` method without RPC: block time − send time,
    both on the net's virtual clock)."""
    store = net.nodes[node_idx].block_store
    rep = LoadReport(run_id=run_id)
    for h in range(1, store.height() + 1):
        blk = store.load_block(h)
        if blk is None:
            continue
        counted = False
        for tx in blk.data.txs:
            parsed = parse_tx(tx)
            if parsed is None or parsed[0] != run_id:
                continue
            rep.txs += 1
            counted = True
            rep.latencies_s.append((blk.header.time_ns - parsed[2]) / 1e9)
        if counted:
            rep.blocks += 1
            rep.last_height = h
            if not rep.first_height:
                rep.first_height = h
    return rep


def load_report(
    endpoint: str,
    run_id: str,
    from_height: int = 1,
    to_height: int | None = None,
) -> LoadReport:
    """Walk committed blocks over RPC; latency = block time - send time.

    The offline/post-hoc method (works on a dead-but-queryable chain);
    prefer :class:`EventLoadMonitor` for live runs — it measures real
    per-tx commit latency on one clock via Tx events."""
    client = HTTPClient(endpoint)
    if to_height is None:
        to_height = int(
            client.call("status")["sync_info"]["latest_block_height"]
        )
    rep = LoadReport(run_id=run_id)
    for h in range(from_height, to_height + 1):
        blk = client.call("block", height=h)
        header = blk["block"]["header"]
        block_time_ns = parse_rfc3339(header["time"])
        txs = blk["block"]["data"]["txs"] or []
        counted = False
        for tx_b64 in txs:
            parsed = parse_tx(base64.b64decode(tx_b64))
            if parsed is None or parsed[0] != run_id:
                continue
            rep.txs += 1
            counted = True
            rep.latencies_s.append((block_time_ns - parsed[2]) / 1e9)
        if counted:
            rep.blocks += 1
            rep.last_height = h
            if not rep.first_height:
                rep.first_height = h
    return rep
