"""E2E harness: process-level testnets, load generation, perturbations.

Reference analogs: test/e2e/runner (docker-compose testnets with
{disconnect, kill, pause, restart} perturbations — runner/perturb.go:16-31)
and test/loadtime (tm-load-test based latency reports). Containers are
replaced by OS processes: each node is a ``cometbft-tpu start`` child
with its own home dir, so SIGKILL/SIGSTOP give the same crash/pause
semantics docker kill/pause give the reference.
"""

from .load import (
    EventLoadMonitor,
    LoadGenerator,
    LoadReport,
    load_report,
)
from .runner import ProcessNode, Testnet

__all__ = [
    "EventLoadMonitor",
    "LoadGenerator",
    "LoadReport",
    "load_report",
    "ProcessNode",
    "Testnet",
]
