"""Process-level testnet runner with perturbations
(reference: test/e2e/runner — main.go orchestration, perturb.go:16-31
{disconnect, kill, pause, restart}, tests/ invariant checks).

Containers are replaced by child processes of ``cometbft-tpu start``:

  kill    -> SIGKILL + restart          (docker kill / start)
  pause   -> SIGSTOP ... SIGCONT        (docker pause / unpause)
  restart -> SIGTERM + restart          (docker restart)

Disconnect-style network faults belong to the in-process tier
(FuzzedConnection, tests/test_fault_injection.py) where the transport is
reachable; an OS process's TCP stack isn't, without root.

Invariant checks after perturbations mirror test/e2e/tests/block_test.go:
all nodes agree on the app hash at every common height, and heights
keep advancing.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from ..rpc.client import HTTPClient


class ProcessNode:
    """One ``cometbft-tpu start`` child process + its home dir."""

    def __init__(self, home: str, rpc_addr: str, env: dict | None = None):
        self.home = home
        self.rpc_addr = rpc_addr
        self.env = env if env is not None else dict(os.environ)
        self.proc: subprocess.Popen | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        assert self.proc is None or self.proc.poll() is not None
        # Logs go to a file, not a pipe: an undrained 64 KB pipe buffer
        # would freeze a chatty node mid-run (the docker tier's log-driver
        # role). Append mode keeps pre-restart history.
        self.log_path = os.path.join(self.home, "node.log")
        self._log_f = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "cometbft_tpu.cmd",
                "--home",
                self.home,
                "start",
            ],
            stdout=self._log_f,
            stderr=subprocess.STDOUT,
            env=self.env,
        )

    def stop(self, timeout: float = 10.0) -> None:
        if self.proc is None or self.proc.poll() is not None:
            self._close_log()
            return
        self.proc.terminate()
        try:
            self.proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.communicate(timeout=timeout)
        self._close_log()

    def _close_log(self) -> None:
        f = getattr(self, "_log_f", None)
        if f is not None and not f.closed:
            f.close()

    def log_tail(self, n_bytes: int = 4000) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(0, f.tell() - n_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    # -- perturbations (perturb.go:16-31) ----------------------------------

    def kill(self) -> None:
        """SIGKILL: no cleanup, no flushes — crash semantics."""
        assert self.proc is not None
        self.proc.kill()
        self.proc.communicate(timeout=10)
        self._close_log()

    def pause(self) -> None:
        """SIGSTOP: the node freezes mid-whatever (docker pause)."""
        assert self.proc is not None and self.proc.poll() is None
        os.kill(self.proc.pid, signal.SIGSTOP)

    def unpause(self) -> None:
        os.kill(self.proc.pid, signal.SIGCONT)

    def restart(self) -> None:
        self.stop()
        self.start()

    def upgrade(self, version: str, config_mutator=None) -> None:
        """The ``upgrade`` perturbation (runner/perturb.go:16-31): clean
        stop, swap the "image" — here the advertised software version
        (env override) plus optional config changes the new version
        ships — and start over the SAME data dir. Chain continuity is
        the caller's invariant: the node must handshake-replay its
        store, rejoin, and keep signing."""
        self.stop()
        self.env = dict(self.env)
        self.env["COMETBFT_TPU_SOFTWARE_VERSION"] = version
        if config_mutator is not None:
            from ..config_file import load_toml, save_toml

            path = os.path.join(self.home, "config", "config.toml")
            cfg = load_toml(path)
            cfg.base.home = self.home
            config_mutator(cfg)
            save_toml(cfg, path)
        self.start()

    def advertised_version(self) -> str:
        return self.client().call("status")["node_info"]["version"]

    # -- observation -------------------------------------------------------

    def client(self) -> HTTPClient:
        return HTTPClient(self.rpc_addr)

    def height(self) -> int:
        st = self.client().call("status")
        return int(st["sync_info"]["latest_block_height"])

    def app_hash_at(self, height: int) -> str:
        blk = self.client().call("block", height=height)
        return blk["block"]["header"]["app_hash"]

    def wait_rpc(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                self.client().call("health")
                return True
            except Exception:
                time.sleep(0.3)
        return False

    def wait_height(self, target: int, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if self.height() >= target:
                    return True
            except Exception:
                pass
            time.sleep(0.3)
        return False


class Testnet:
    """N ProcessNodes over home dirs laid out by ``cometbft-tpu testnet``
    (cmd/__main__.py cmd_testnet; reference testnet.go)."""

    __test__ = False  # not a pytest class despite the name

    def __init__(self, out_dir: str, n_vals: int, starting_port: int):
        self.out_dir = out_dir
        self.nodes = [
            ProcessNode(
                home=os.path.join(out_dir, f"node{i}"),
                rpc_addr=f"tcp://127.0.0.1:{starting_port + 2 * i + 1}",
            )
            for i in range(n_vals)
        ]

    @classmethod
    def generate(
        cls, out_dir: str, n_vals: int, starting_port: int
    ) -> "Testnet":
        from ..cmd.__main__ import main as cli_main

        rc = cli_main(
            [
                "testnet",
                "--v",
                str(n_vals),
                "--o",
                out_dir,
                "--starting-port",
                str(starting_port),
            ]
        )
        if rc != 0:
            raise RuntimeError("testnet generation failed")
        return cls(out_dir, n_vals, starting_port)

    @classmethod
    def generate_randomized(
        cls, out_dir: str, seed: int, starting_port: int
    ) -> "Testnet":
        """Seeded randomized-manifest generator (the reference's
        ``e2e generator``, test/e2e/README.md:36-60 + pkg/testnet.go):
        draws validator count, consensus timeouts, topology (full mesh
        vs ring of persistent peers, PEX on/off), storage backend and
        block-production mode from ``seed``, writes the manifest next to
        the node homes for reproduction, and post-edits each generated
        config accordingly."""
        import json
        import random

        from ..config_file import load_toml, save_toml

        rng = random.Random(seed)
        n_vals = rng.choice([2, 3, 4])
        manifest = {
            "seed": seed,
            "validators": n_vals,
            "topology": rng.choice(["mesh", "ring"]),
            "pex": rng.random() < 0.5,
            "db_backend": rng.choice(["file", "native"]),
            "timeout_commit_ms": rng.choice([100, 250, 500]),
            "timeout_propose_ms": rng.choice([400, 800]),
            "create_empty_blocks": rng.random() < 0.8,
        }
        net = cls.generate(out_dir, n_vals, starting_port)
        with open(os.path.join(out_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        ms = 1_000_000
        for i, node in enumerate(net.nodes):
            path = os.path.join(node.home, "config", "config.toml")
            cfg = load_toml(path)
            cfg.base.home = node.home
            cfg.base.db_backend = manifest["db_backend"]
            cfg.p2p.pex = manifest["pex"]
            if manifest["topology"] == "ring":
                # keep only the next node as a persistent peer; gossip
                # still reaches everyone around the ring
                peers = cfg.p2p.persistent_peers.split(",")
                cfg.p2p.persistent_peers = peers[i % len(peers)]
            import dataclasses

            cfg.consensus = dataclasses.replace(
                cfg.consensus,
                timeout_commit_ns=manifest["timeout_commit_ms"] * ms,
                timeout_propose_ns=manifest["timeout_propose_ms"] * ms,
                create_empty_blocks=manifest["create_empty_blocks"],
            )
            save_toml(cfg, path)
        net.manifest = manifest
        return net

    def start(self) -> None:
        for n in self.nodes:
            n.start()

    def stop(self) -> None:
        for n in self.nodes:
            try:
                n.stop()
            except Exception:
                pass

    def live_nodes(self) -> list[ProcessNode]:
        return [
            n
            for n in self.nodes
            if n.proc is not None and n.proc.poll() is None
        ]

    def wait_all_height(self, target: int, timeout: float = 90.0) -> bool:
        deadline = time.monotonic() + timeout
        return all(
            n.wait_height(target, max(deadline - time.monotonic(), 0.1))
            for n in self.live_nodes()
        )

    # -- invariants (test/e2e/tests/block_test.go) -------------------------

    def check_app_hash_agreement(self, up_to: int | None = None) -> None:
        """Every node reports the same app hash at every common height."""
        nodes = self.live_nodes()
        if len(nodes) < 2:
            return
        common = min(n.height() for n in nodes)
        if up_to is not None:
            common = min(common, up_to)
        for h in range(1, common + 1):
            hashes = {n.app_hash_at(h) for n in nodes}
            if len(hashes) != 1:
                raise AssertionError(
                    f"app hash divergence at height {h}: {hashes}"
                )

    def check_progress(self, blocks: int = 2, timeout: float = 60.0) -> None:
        """Chain must advance ``blocks`` beyond the current max height."""
        start = max(n.height() for n in self.live_nodes())
        if not self.wait_all_height(start + blocks, timeout):
            # diagnostics only: a node whose RPC is hung (often the very
            # reason progress stalled) must not turn the curated error
            # into a raw network traceback
            def safe_height(n):
                try:
                    return n.height()
                except Exception:
                    return -1

            nodes = self.live_nodes()
            heights = [safe_height(n) for n in nodes]
            lagger = nodes[heights.index(min(heights))]
            raise AssertionError(
                f"no progress: stuck at {heights} (wanted {start + blocks};"
                f" -1 = RPC unreachable)\n"
                f"--- slowest node log tail ({lagger.home}) ---\n"
                f"{lagger.log_tail(3000)}"
            )
