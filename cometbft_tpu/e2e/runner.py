"""Process-level testnet runner with perturbations
(reference: test/e2e/runner — main.go orchestration, perturb.go:16-31
{disconnect, kill, pause, restart}, tests/ invariant checks).

Containers are replaced by child processes of ``cometbft-tpu start``:

  kill    -> SIGKILL + restart          (docker kill / start)
  pause   -> SIGSTOP ... SIGCONT        (docker pause / unpause)
  restart -> SIGTERM + restart          (docker restart)

The ``disconnect`` perturbation (perturb.go's docker network
disconnect) is realized WITHOUT root: a relayed testnet routes every
inter-node TCP link through an in-runner :class:`LinkRelay` the runner
can sever (drop live connections, refuse new ones) and heal. PEX is
disabled in relayed nets so nodes only ever dial the configured
(relayed) addresses — a learned direct address would tunnel under the
partition. Finer link faults (drop/duplicate/reorder of individual
messages) remain in the in-process tier (FuzzedConnection,
tests/test_fault_injection.py).

Invariant checks after perturbations mirror test/e2e/tests/block_test.go:
all nodes agree on the app hash at every common height, and heights
keep advancing.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
from ..libs import sync as libsync
import time

from ..rpc.client import HTTPClient


class LinkRelay:
    """Severable TCP forwarder for ONE directed peer link.

    The process-tier analog of `docker network disconnect`
    (test/e2e/runner/perturb.go:16-31): while severed, established
    connections are torn down and new dials are accepted-then-closed, so
    the dialer sees a live listener with a dead peer — the same
    observable as a dropped container link, without root.
    """

    def __init__(self, target_host: str, target_port: int):
        self._target = (target_host, target_port)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(16)
        self.port = self._lsock.getsockname()[1]
        self._severed = threading.Event()
        self._closed = False
        self._conns: set[socket.socket] = set()
        self._mtx = libsync.Mutex("e2e.runner._mtx")
        threading.Thread(
            target=self._accept_loop, name=f"relay-{self.port}", daemon=True
        ).start()

    def _accept_loop(self) -> None:
        while True:
            try:
                client, _ = self._lsock.accept()
            except OSError:
                return  # listener closed
            if self._severed.is_set():
                client.close()
                continue
            try:
                upstream = socket.create_connection(self._target, timeout=5)
            except OSError:
                client.close()
                continue
            with self._mtx:
                # re-check under the same lock sever() snapshots with: a
                # dial that raced past the first check must not survive
                # the partition
                if self._severed.is_set():
                    client.close()
                    upstream.close()
                    continue
                self._conns.update((client, upstream))
            for a, b in ((client, upstream), (upstream, client)):
                threading.Thread(
                    target=self._pump, args=(a, b), daemon=True
                ).start()

    def _pump(self, src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.close()
                except OSError:
                    pass
            with self._mtx:
                self._conns.discard(src)
                self._conns.discard(dst)

    def sever(self) -> None:
        self._severed.set()
        with self._mtx:
            conns = list(self._conns)
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass

    def heal(self) -> None:
        self._severed.clear()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.sever()
        try:
            self._lsock.close()
        except OSError:
            pass


class ProcessNode:
    """One ``cometbft-tpu start`` child process + its home dir."""

    def __init__(self, home: str, rpc_addr: str, env: dict | None = None):
        self.home = home
        self.rpc_addr = rpc_addr
        self.env = env if env is not None else dict(os.environ)
        self.proc: subprocess.Popen | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        assert self.proc is None or self.proc.poll() is not None
        # Logs go to a file, not a pipe: an undrained 64 KB pipe buffer
        # would freeze a chatty node mid-run (the docker tier's log-driver
        # role). Append mode keeps pre-restart history.
        self.log_path = os.path.join(self.home, "node.log")
        self._log_f = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "cometbft_tpu.cmd",
                "--home",
                self.home,
                "start",
            ],
            stdout=self._log_f,
            stderr=subprocess.STDOUT,
            env=self.env,
        )

    def stop(self, timeout: float = 10.0) -> None:
        if self.proc is None or self.proc.poll() is not None:
            self._close_log()
            return
        self.proc.terminate()
        try:
            self.proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.communicate(timeout=timeout)
        self._close_log()

    def _close_log(self) -> None:
        f = getattr(self, "_log_f", None)
        if f is not None and not f.closed:
            f.close()

    def log_tail(self, n_bytes: int = 4000) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(0, f.tell() - n_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    # -- perturbations (perturb.go:16-31) ----------------------------------

    def kill(self) -> None:
        """SIGKILL: no cleanup, no flushes — crash semantics."""
        assert self.proc is not None
        self.proc.kill()
        self.proc.communicate(timeout=10)
        self._close_log()

    def pause(self) -> None:
        """SIGSTOP: the node freezes mid-whatever (docker pause)."""
        assert self.proc is not None and self.proc.poll() is None
        os.kill(self.proc.pid, signal.SIGSTOP)

    def unpause(self) -> None:
        os.kill(self.proc.pid, signal.SIGCONT)

    def restart(self) -> None:
        self.stop()
        self.start()

    def upgrade(self, version: str, config_mutator=None) -> None:
        """The ``upgrade`` perturbation (runner/perturb.go:16-31): clean
        stop, swap the "image" — here the advertised software version
        (env override) plus optional config changes the new version
        ships — and start over the SAME data dir. Chain continuity is
        the caller's invariant: the node must handshake-replay its
        store, rejoin, and keep signing."""
        self.stop()
        self.env = dict(self.env)
        self.env["COMETBFT_TPU_SOFTWARE_VERSION"] = version
        if config_mutator is not None:
            from ..config_file import load_toml, save_toml

            path = os.path.join(self.home, "config", "config.toml")
            cfg = load_toml(path)
            cfg.base.home = self.home
            config_mutator(cfg)
            save_toml(cfg, path)
        self.start()

    def advertised_version(self) -> str:
        return self.client().call("status")["node_info"]["version"]

    # -- observation -------------------------------------------------------

    def client(self) -> HTTPClient:
        return HTTPClient(self.rpc_addr)

    def height(self) -> int:
        st = self.client().call("status")
        return int(st["sync_info"]["latest_block_height"])

    def app_hash_at(self, height: int) -> str:
        blk = self.client().call("block", height=height)
        return blk["block"]["header"]["app_hash"]

    def wait_rpc(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                self.client().call("health")
                return True
            except Exception:
                time.sleep(0.3)
        return False

    def wait_height(self, target: int, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if self.height() >= target:
                    return True
            except Exception:
                pass
            time.sleep(0.3)
        return False


class Testnet:
    """N ProcessNodes over home dirs laid out by ``cometbft-tpu testnet``
    (cmd/__main__.py cmd_testnet; reference testnet.go)."""

    __test__ = False  # not a pytest class despite the name

    def __init__(self, out_dir: str, n_vals: int, starting_port: int):
        self.out_dir = out_dir
        self.starting_port = starting_port
        self.relays: dict[tuple[int, int], LinkRelay] = {}
        self.nodes = [
            ProcessNode(
                home=os.path.join(out_dir, f"node{i}"),
                rpc_addr=f"tcp://127.0.0.1:{starting_port + 2 * i + 1}",
            )
            for i in range(n_vals)
        ]

    @classmethod
    def generate(
        cls, out_dir: str, n_vals: int, starting_port: int
    ) -> "Testnet":
        from ..cmd.__main__ import main as cli_main

        rc = cli_main(
            [
                "testnet",
                "--v",
                str(n_vals),
                "--o",
                out_dir,
                "--starting-port",
                str(starting_port),
            ]
        )
        if rc != 0:
            raise RuntimeError("testnet generation failed")
        return cls(out_dir, n_vals, starting_port)

    @classmethod
    def generate_randomized(
        cls, out_dir: str, seed: int, starting_port: int
    ) -> "Testnet":
        """Seeded randomized-manifest generator (the reference's
        ``e2e generator``, test/e2e/README.md:36-60 + pkg/testnet.go):
        draws validator count, consensus timeouts, topology (full mesh
        vs ring of persistent peers, PEX on/off), storage backend and
        block-production mode from ``seed``, writes the manifest next to
        the node homes for reproduction, and post-edits each generated
        config accordingly."""
        import json
        import random

        from ..config_file import load_toml, save_toml

        rng = random.Random(seed)
        n_vals = rng.choice([2, 3, 4])
        manifest = {
            "seed": seed,
            "validators": n_vals,
            "topology": rng.choice(["mesh", "ring"]),
            "pex": rng.random() < 0.5,
            "db_backend": rng.choice(["file", "native"]),
            "timeout_commit_ms": rng.choice([100, 250, 500]),
            "timeout_propose_ms": rng.choice([400, 800]),
            "create_empty_blocks": rng.random() < 0.8,
        }
        net = cls.generate(out_dir, n_vals, starting_port)
        with open(os.path.join(out_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        ms = 1_000_000
        for i, node in enumerate(net.nodes):
            path = os.path.join(node.home, "config", "config.toml")
            cfg = load_toml(path)
            cfg.base.home = node.home
            cfg.base.db_backend = manifest["db_backend"]
            cfg.p2p.pex = manifest["pex"]
            if manifest["topology"] == "ring":
                # keep only the next node as a persistent peer; gossip
                # still reaches everyone around the ring
                peers = cfg.p2p.persistent_peers.split(",")
                cfg.p2p.persistent_peers = peers[i % len(peers)]
            import dataclasses

            cfg.consensus = dataclasses.replace(
                cfg.consensus,
                timeout_commit_ns=manifest["timeout_commit_ms"] * ms,
                timeout_propose_ns=manifest["timeout_propose_ms"] * ms,
                create_empty_blocks=manifest["create_empty_blocks"],
            )
            save_toml(cfg, path)
        net.manifest = manifest
        return net

    @classmethod
    def generate_relayed(
        cls, out_dir: str, n_vals: int, starting_port: int
    ) -> "Testnet":
        """A testnet whose every inter-node p2p link runs through a
        severable :class:`LinkRelay` — the `disconnect` perturbation's
        substrate. One relay per DIRECTED pair (i dials j), so a single
        node can be partitioned without touching third-party links. PEX
        is disabled: learned direct addresses would bypass the relays.
        """
        from ..config_file import load_toml, save_toml

        net = cls.generate(out_dir, n_vals, starting_port)
        port_to_idx = {
            starting_port + 2 * j: j for j in range(n_vals)
        }
        for i, node in enumerate(net.nodes):
            path = os.path.join(node.home, "config", "config.toml")
            cfg = load_toml(path)
            cfg.base.home = node.home
            cfg.p2p.pex = False
            rewritten = []
            for entry in cfg.p2p.persistent_peers.split(","):
                if not entry:
                    continue
                pid, addr = entry.split("@", 1)
                host, port_s = addr.rsplit(":", 1)
                j = port_to_idx[int(port_s)]
                relay = net.relays.get((i, j))
                if relay is None:
                    relay = LinkRelay(host, int(port_s))
                    net.relays[(i, j)] = relay
                rewritten.append(f"{pid}@127.0.0.1:{relay.port}")
            cfg.p2p.persistent_peers = ",".join(rewritten)
            save_toml(cfg, path)
        return net

    def partition(self, idx: int) -> None:
        """Sever every link to/from node ``idx`` (perturb.go disconnect)."""
        for (i, j), relay in self.relays.items():
            if idx in (i, j):
                relay.sever()

    def heal(self, idx: int) -> None:
        """Re-enable node ``idx``'s links (the reference reconnects after
        10 s; healing is the caller's schedule here)."""
        for (i, j), relay in self.relays.items():
            if idx in (i, j):
                relay.heal()

    def start(self) -> None:
        for n in self.nodes:
            n.start()

    def stop(self) -> None:
        for n in self.nodes:
            try:
                n.stop()
            except Exception:
                pass
        for relay in self.relays.values():
            relay.close()

    def live_nodes(self) -> list[ProcessNode]:
        return [
            n
            for n in self.nodes
            if n.proc is not None and n.proc.poll() is None
        ]

    def wait_all_height(self, target: int, timeout: float = 90.0) -> bool:
        deadline = time.monotonic() + timeout
        return all(
            n.wait_height(target, max(deadline - time.monotonic(), 0.1))
            for n in self.live_nodes()
        )

    # -- invariants (test/e2e/tests/block_test.go) -------------------------

    def check_app_hash_agreement(self, up_to: int | None = None) -> None:
        """Every node reports the same app hash at every common height."""
        nodes = self.live_nodes()
        if len(nodes) < 2:
            return
        common = min(n.height() for n in nodes)
        if up_to is not None:
            common = min(common, up_to)
        for h in range(1, common + 1):
            hashes = {n.app_hash_at(h) for n in nodes}
            if len(hashes) != 1:
                raise AssertionError(
                    f"app hash divergence at height {h}: {hashes}"
                )

    def check_progress(self, blocks: int = 2, timeout: float = 60.0) -> None:
        """Chain must advance ``blocks`` beyond the current max height."""
        start = max(n.height() for n in self.live_nodes())
        if not self.wait_all_height(start + blocks, timeout):
            # diagnostics only: a node whose RPC is hung (often the very
            # reason progress stalled) must not turn the curated error
            # into a raw network traceback
            def safe_height(n):
                try:
                    return n.height()
                except Exception:
                    return -1

            nodes = self.live_nodes()
            heights = [safe_height(n) for n in nodes]
            lagger = nodes[heights.index(min(heights))]
            raise AssertionError(
                f"no progress: stuck at {heights} (wanted {start + blocks};"
                f" -1 = RPC unreachable)\n"
                f"--- slowest node log tail ({lagger.home}) ---\n"
                f"{lagger.log_tail(3000)}"
            )


# -- simnet mode (no sockets, no subprocesses) ---------------------------
#
# The process tier above runs REAL nodes and real TCP — slow,
# wall-clock, nondeterministic. `--simnet` runs the same scenario
# intents on the deterministic in-process plane (cometbft_tpu/simnet):
# seeded virtual links, scripted faults, bit-reproducible runs. A
# failing CI run prints its seed; `--seed N` replays that exact
# schedule locally. Default seed: COMETBFT_TPU_SIMNET_SEED.


def run_simnet_load(
    seed: int, n_nodes: int = 4, rate: int = 200, heights: int = 6,
    burst: int = 1,
) -> dict:
    """Scenario-less simnet load run: N validators, a virtual-rate tx
    stream, a block-walk latency report — the loadtime shape without a
    socket in sight.  ``burst`` > 1 is the sustained mempool-STORM
    mode: burst txs per tick at the same aggregate rate, so storms in
    the thousands of tx/s stay tractable on the event heap."""
    from ..simnet import SimNet
    from .load import SimLoadGenerator, sim_load_report

    net = SimNet(n_nodes, seed=seed)
    try:
        net.start()
        gen = SimLoadGenerator(
            net, rate=rate, burst=burst, run_id=f"sim{seed}"
        )
        if burst > 1:
            net.mark_storm(rate)
        gen.start()
        ok = net.run_until_height(heights, max_virtual_ms=240_000)
        gen.stop()
        net.run(max_virtual_ms=500)  # let in-flight commits land
        net.assert_no_fork()
        rep = sim_load_report(net, gen.run_id)
        return {
            "ok": ok and rep.txs > 0,
            "seed": seed,
            "node_heights": net.heights(),
            "sent": gen.sent,
            # rep.summary()'s "heights" = [first, last] height carrying
            # load txs (the loadtime report shape), NOT node heights
            **rep.summary(),
        }
    finally:
        net.stop()


def main(argv=None) -> int:
    """CLI: ``python -m cometbft_tpu.e2e.runner --simnet ...``."""
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="python -m cometbft_tpu.e2e.runner")
    ap.add_argument(
        "--simnet", action="store_true",
        help="run on the deterministic in-process simnet plane",
    )
    ap.add_argument("--scenario", default="healthy")
    ap.add_argument(
        "--seed", type=int,
        default=int(os.environ.get("COMETBFT_TPU_SIMNET_SEED", "0") or "0"),
        help="schedule seed — reproduces a failing run bit-identically",
    )
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument(
        "--load", type=int, default=0, metavar="RATE",
        help="simnet load mode: tx/s of virtual time instead of a "
        "fault scenario",
    )
    ap.add_argument(
        "--burst", type=int, default=1, metavar="N",
        help="txs pushed per load tick (storm mode: thousands of tx/s "
        "at rate/burst scheduler events per virtual second)",
    )
    args = ap.parse_args(argv)
    if not args.simnet:
        ap.error(
            "the process tier is driven from pytest "
            "(tests/test_e2e_harness.py); the CLI runs --simnet only"
        )
    if args.load:
        out = run_simnet_load(
            args.seed, n_nodes=args.nodes or 4, rate=args.load,
            burst=args.burst,
        )
        print(json.dumps(out, default=str, indent=1))
        return 0 if out["ok"] else 1
    from ..simnet.scenarios import run_scenario

    kw = {}
    if args.nodes is not None:
        kw["n_nodes"] = args.nodes
    result = run_scenario(args.scenario, args.seed, **kw)
    print(json.dumps(result.summary(), default=str, indent=1))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
