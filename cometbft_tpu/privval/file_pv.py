"""File-backed private validator with double-sign protection (reference:
privval/file.go:47-466).

Two files: the key file (address + ed25519 keypair) and the *last-sign
state* file, fsynced BEFORE every signature is released. ``check_hrs``
(file.go:100) refuses to sign at a (height, round, step) lower than the
last signed one; at the SAME HRS it re-signs only when the sign bytes are
identical or differ solely in timestamp (crash-replay re-signing,
file.go:373-408) — the mechanism that makes WAL replay safe.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass, field

from ..crypto.keys import Ed25519PrivKey
from ..types import canonical
from ..types.proto import read_fields
from ..types.vote import Proposal, Vote
from ..types.priv_validator import PrivValidator

# step numbers in the sign state (file.go:32-36)
STEP_PROPOSAL = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote: Vote) -> int:
    if vote.msg_type == canonical.PREVOTE_TYPE:
        return STEP_PREVOTE
    if vote.msg_type == canonical.PRECOMMIT_TYPE:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type {vote.msg_type}")


class DoubleSignError(Exception):
    pass


@dataclass(slots=True)
class LastSignState:
    height: int = 0
    round: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """file.go:100 CheckHRS. Returns True if this exact HRS was already
        signed (caller must then compare sign bytes); raises on regression.
        """
        if self.height > height:
            raise DoubleSignError(f"height regression: {self.height} > {height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}: "
                    f"{self.round} > {round_}"
                )
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at {height}/{round_}: "
                        f"{self.step} > {step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no sign bytes for same HRS")
                    return True
        return False

    def save(self) -> None:
        if not self.file_path:
            return
        data = json.dumps(
            {
                "height": self.height,
                "round": self.round,
                "step": self.step,
                "signature": self.signature.hex(),
                "signbytes": self.sign_bytes.hex(),
            },
            indent=2,
        )
        # Atomic + durable: temp file, fsync, rename (a torn sign-state
        # file would disable double-sign protection).
        d = os.path.dirname(self.file_path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".pvstate-")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.file_path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    @classmethod
    def load(cls, path: str) -> "LastSignState":
        if not os.path.exists(path):
            return cls(file_path=path)
        with open(path) as f:
            d = json.load(f)
        return cls(
            height=int(d.get("height", 0)),
            round=int(d.get("round", 0)),
            step=int(d.get("step", 0)),
            signature=bytes.fromhex(d.get("signature", "")),
            sign_bytes=bytes.fromhex(d.get("signbytes", "")),
            file_path=path,
        )


def _strip_timestamp(sign_bytes: bytes) -> bytes:
    """Remove the timestamp field from length-delimited canonical vote /
    proposal sign bytes so two signings that differ only by clock compare
    equal (file.go checkVotesOnlyDifferByTimestamp:373)."""
    # sign bytes = uvarint len || CanonicalVote/CanonicalProposal body
    from ..types.proto import read_uvarint

    try:
        _, pos = read_uvarint(sign_bytes, 0)
        body = sign_bytes[pos:]
        fields = read_fields(body)
        # Field 1 is the msg type: proposals carry their timestamp in
        # field 6, votes in field 5 (canonical.proto).
        msg_type = next((v for f, w, v in fields if f == 1), None)
        ts_field = 6 if msg_type == canonical.PROPOSAL_TYPE else 5
        out = b""
        for fnum, wire, value in fields:
            if fnum == ts_field and wire == 2:
                continue
            from ..types import proto as p

            if wire == p.VARINT:
                out += p.tag(fnum, wire) + p.varint(value)
            elif wire == p.FIXED64:
                out += p.tag(fnum, wire) + value.to_bytes(8, "little")
            elif wire == p.BYTES:
                out += p.tag(fnum, wire) + p.uvarint(len(value)) + value
            else:
                out += p.tag(fnum, wire)
        return out
    except Exception:
        return sign_bytes


@dataclass(slots=True)
class _FilePVKey:
    address: bytes
    priv_key: Ed25519PrivKey
    file_path: str = ""

    def save(self) -> None:
        if not self.file_path:
            return
        pub = self.priv_key.pub_key()
        data = json.dumps(
            {
                "address": self.address.hex().upper(),
                "pub_key": {"type": pub.type, "value": pub.bytes().hex()},
                "priv_key": {
                    "type": self.priv_key.type,
                    "value": self.priv_key.seed.hex(),
                },
            },
            indent=2,
        )
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        # Owner-only: this file holds the validator's signing key.
        fd = os.open(
            self.file_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600
        )
        with os.fdopen(fd, "w") as f:
            f.write(data)

    @classmethod
    def load(cls, path: str) -> "_FilePVKey":
        with open(path) as f:
            d = json.load(f)
        priv = Ed25519PrivKey.from_seed(bytes.fromhex(d["priv_key"]["value"]))
        return cls(
            address=bytes.fromhex(d["address"]),
            priv_key=priv,
            file_path=path,
        )


class FilePV(PrivValidator):
    """privval/file.go:47 FilePV."""

    def __init__(self, key: _FilePVKey, last_sign_state: LastSignState):
        self.key = key
        self.last_sign_state = last_sign_state

    # -- constructors ------------------------------------------------------

    @classmethod
    def generate(cls, key_file: str, state_file: str) -> "FilePV":
        pv = cls.generate_from_key(
            Ed25519PrivKey.generate(), key_file, state_file
        )
        pv.save()
        return pv

    @classmethod
    def generate_from_key(
        cls, priv, key_file: str, state_file: str
    ) -> "FilePV":
        """Wrap an existing key (testnet generator, commands/testnet.go)."""
        key = _FilePVKey(
            address=bytes(priv.pub_key().address()),
            priv_key=priv,
            file_path=key_file,
        )
        return cls(key, LastSignState(file_path=state_file))

    @classmethod
    def load(cls, key_file: str, state_file: str) -> "FilePV":
        return cls(_FilePVKey.load(key_file), LastSignState.load(state_file))

    @classmethod
    def load_or_generate(cls, key_file: str, state_file: str) -> "FilePV":
        if os.path.exists(key_file):
            return cls.load(key_file, state_file)
        return cls.generate(key_file, state_file)

    def save(self) -> None:
        self.key.save()
        self.last_sign_state.save()

    # -- PrivValidator -----------------------------------------------------

    def get_pub_key(self):
        return self.key.priv_key.pub_key()

    def sign_vote(
        self, chain_id: str, vote: Vote, sign_extension: bool = True
    ) -> None:
        """file.go:262 SignVote → signVote:304."""
        height, round_, step = vote.height, vote.round, vote_to_step(vote)
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)

        ext_sig = b""
        if (
            sign_extension
            and vote.msg_type == canonical.PRECOMMIT_TYPE
            and not vote.block_id.is_nil()
        ):
            ext_sig = self.key.priv_key.sign(
                vote.extension_sign_bytes(chain_id)
            )

        if same_hrs:
            # Crash replay: identical sign bytes → reuse the signature;
            # timestamp-only diff → re-sign with the OLD timestamp.
            if sign_bytes == lss.sign_bytes:
                vote.signature = lss.signature
            elif _strip_timestamp(sign_bytes) == _strip_timestamp(
                lss.sign_bytes
            ):
                vote.timestamp_ns = self._saved_timestamp_ns(vote, chain_id)
                vote.signature = lss.signature
            else:
                raise DoubleSignError(
                    f"conflicting vote data at {height}/{round_}/{step}"
                )
            vote.extension_signature = ext_sig
            return

        sig = self.key.priv_key.sign(sign_bytes)
        # Persist BEFORE releasing the signature (file.go saveSigned).
        lss.height, lss.round, lss.step = height, round_, step
        lss.signature, lss.sign_bytes = sig, sign_bytes
        lss.save()
        vote.signature = sig
        vote.extension_signature = ext_sig

    def _saved_timestamp_ns(self, vote: Vote, chain_id: str) -> int:
        """Recover the previously-signed timestamp by re-deriving sign
        bytes across candidate timestamps is impossible; instead the saved
        sign bytes carry it — parse field 5/6 back out."""
        from ..types.proto import read_uvarint

        raw = self.last_sign_state.sign_bytes
        _, pos = read_uvarint(raw, 0)
        fields = read_fields(raw[pos:])
        msg_type = next((v for f, w, v in fields if f == 1), None)
        ts_field = 6 if msg_type == canonical.PROPOSAL_TYPE else 5
        for fnum, wire, value in fields:
            if fnum == ts_field and wire == 2:
                secs = nanos = 0
                for f2, _, v2 in read_fields(value):
                    if f2 == 1:
                        secs = v2 if v2 < 1 << 63 else v2 - (1 << 64)
                    elif f2 == 2:
                        nanos = v2
                return secs * 1_000_000_000 + nanos
        return vote.timestamp_ns

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        """file.go SignProposal."""
        height, round_, step = proposal.height, proposal.round, STEP_PROPOSAL
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                proposal.signature = lss.signature
            elif _strip_timestamp(sign_bytes) == _strip_timestamp(
                lss.sign_bytes
            ):
                proposal.signature = lss.signature
            else:
                raise DoubleSignError(
                    f"conflicting proposal data at {height}/{round_}"
                )
            return
        sig = self.key.priv_key.sign(sign_bytes)
        lss.height, lss.round, lss.step = height, round_, step
        lss.signature, lss.sign_bytes = sig, sign_bytes
        lss.save()
        proposal.signature = sig
