"""Remote signer protocol: production validators sign over a socket.

Reference surface: privval/signer_client.go (SignerClient implementing
types.PrivValidator over an endpoint), privval/signer_listener_endpoint.go
(the NODE side — it *listens*; the remote signer dials in, tmkms-style),
privval/signer_dialer_endpoint.go + privval/signer_server.go (the SIGNER
side), privval/messages.go (PubKey/SignVote/SignProposal/Ping + errors),
privval/retry_signer_client.go.

Transport: `tcp://` endpoints upgrade to SecretConnection (X25519 +
ChaCha20-Poly1305, the same channel p2p uses — privval/socket_dialers.go
semantics); `unix://` endpoints stay raw (filesystem permissions are the
auth boundary). Frames are uvarint-length-prefixed JSON envelopes like the
ABCI socket codec — one codec family across all process boundaries.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
from ..libs import sync as libsync
import time
from dataclasses import dataclass

from ..abci import codec
from ..crypto.keys import Ed25519PrivKey, PUBKEY_TYPES
from ..libs import log as logmod
from ..libs.service import BaseService
from ..types import proto
from ..types.block import BlockID, PartSetHeader
from ..types.priv_validator import PrivValidator
from ..types.vote import Proposal, Vote


class RemoteSignerError(Exception):
    """Error returned by the remote signer (privval/errors.go)."""

    def __init__(self, code: int, description: str):
        super().__init__(f"remote signer error {code}: {description}")
        self.code = code
        self.description = description


# ------------------------------------------------------------------ wire


@dataclass(slots=True)
class PubKeyRequest:
    chain_id: str = ""


@dataclass(slots=True)
class PubKeyResponse:
    pub_key_type: str = ""
    pub_key_bytes: bytes = b""
    error_code: int = 0
    error_desc: str = ""


@dataclass(slots=True)
class SignVoteRequest:
    vote: Vote | None = None
    chain_id: str = ""
    skip_extension_signing: bool = False


@dataclass(slots=True)
class SignedVoteResponse:
    vote: Vote | None = None
    error_code: int = 0
    error_desc: str = ""


@dataclass(slots=True)
class SignProposalRequest:
    proposal: Proposal | None = None
    chain_id: str = ""


@dataclass(slots=True)
class SignedProposalResponse:
    proposal: Proposal | None = None
    error_code: int = 0
    error_desc: str = ""


@dataclass(slots=True)
class PingRequest:
    pass


@dataclass(slots=True)
class PingResponse:
    pass


_TYPES = {
    cls.__name__: cls
    for cls in (
        PubKeyRequest,
        PubKeyResponse,
        SignVoteRequest,
        SignedVoteResponse,
        SignProposalRequest,
        SignedProposalResponse,
        PingRequest,
        PingResponse,
        Vote,
        Proposal,
        BlockID,
        PartSetHeader,
    )
}


# The tagged-JSON (de)serializers and the uvarint frame reader are the
# shared process-boundary codec (abci/codec.py, types/proto.py) bound to
# this protocol's type registry.


def encode_msg(msg) -> bytes:
    return proto.delimited(
        json.dumps(codec._to_jsonable(msg), separators=(",", ":")).encode()
    )


MAX_MSG_BYTES = 16 * 1024 * 1024


def decode_msg(read_exact):
    """Read one message via ``read_exact(n) -> bytes`` (raises EOFError)."""
    payload = proto.read_delimited(read_exact, MAX_MSG_BYTES)
    return codec._from_jsonable(json.loads(payload), types=_TYPES)


# -------------------------------------------------------------- endpoint


def parse_addr(addr: str) -> tuple[str, str | tuple[str, int]]:
    """'tcp://h:p' | 'unix:///path' -> (proto, target)."""
    if addr.startswith("tcp://"):
        host, port = addr[6:].rsplit(":", 1)
        return "tcp", (host, int(port))
    if addr.startswith("unix://"):
        return "unix", addr[7:]
    raise ValueError(f"unsupported privval address {addr!r}")


class _Conn:
    """One established signer connection: framing over raw or secret."""

    def __init__(self, sock, secret=None):
        self.sock = sock
        self.secret = secret  # SecretConnection or None (unix)

    def _read_exact(self, n: int) -> bytes:
        if self.secret is not None:
            return self.secret.read_exact_msg(n)
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise EOFError("privval connection closed")
            out += chunk
        return out

    def send(self, msg) -> None:
        data = encode_msg(msg)
        if self.secret is not None:
            self.secret.write(data)
        else:
            self.sock.sendall(data)

    def recv(self):
        return decode_msg(self._read_exact)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class SignerListenerEndpoint(BaseService):
    """Node-side endpoint: LISTENS for the remote signer to dial in
    (privval/signer_listener_endpoint.go). Single active connection;
    requests are serialized; a ping keep-alive detects dead signers."""

    def __init__(
        self,
        addr: str,
        node_priv_key: Ed25519PrivKey | None = None,
        timeout: float = 5.0,
        ping_interval: float = 2.0,
        logger=None,
    ):
        super().__init__("SignerListenerEndpoint", logger)
        self.addr = addr
        self.timeout = timeout
        self.ping_interval = ping_interval
        # tcp upgrades to SecretConnection; the node authenticates with an
        # ephemeral key unless a persistent node key is supplied.
        self.node_priv_key = node_priv_key or Ed25519PrivKey.generate()
        self.logger = logger or logmod.default_logger().with_module("privval")
        self._listener = None
        self._conn: _Conn | None = None
        self._conn_ready = threading.Event()
        self._req_mtx = libsync.Mutex("privval.signer._req_mtx")
        self._accept_thread = None
        self._ping_thread = None

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        proto_, target = parse_addr(self.addr)
        if proto_ == "tcp":
            self._listener = socket.create_server(
                target, reuse_port=False
            )
        else:
            import os

            try:
                os.unlink(target)
            except FileNotFoundError:
                pass
            self._listener = socket.socket(socket.AF_UNIX)
            self._listener.bind(target)
            self._listener.listen(1)
        self._listener.settimeout(0.2)
        self._proto = proto_
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="privval-accept", daemon=True
        )
        self._accept_thread.start()
        self._ping_thread = threading.Thread(
            target=self._ping_loop, name="privval-ping", daemon=True
        )
        self._ping_thread.start()

    def on_stop(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self._drop_conn()

    def _drop_conn(self) -> None:
        conn, self._conn = self._conn, None
        self._conn_ready.clear()
        if conn is not None:
            conn.close()

    def _accept_loop(self) -> None:
        while self.is_running():
            if self._conn is not None:
                time.sleep(0.1)
                continue
            try:
                sock, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                sock.settimeout(self.timeout)
                secret = None
                if self._proto == "tcp":
                    from ..p2p.conn.secret_connection import SecretConnection

                    secret = SecretConnection(sock, self.node_priv_key)
                self._conn = _Conn(sock, secret)
                self._conn_ready.set()
                self.logger.info("remote signer connected", addr=self.addr)
            except Exception as e:
                self.logger.error("signer handshake failed", err=repr(e))
                try:
                    sock.close()
                except OSError:
                    pass

    def _ping_loop(self) -> None:
        while self.is_running():
            time.sleep(self.ping_interval)
            if self._conn is None:
                continue
            try:
                self.request(PingRequest())
            except Exception as e:
                self.logger.error("signer ping failed", err=repr(e))
                self._drop_conn()

    # -- requests ----------------------------------------------------------

    def wait_for_conn(self, timeout: float | None = None) -> bool:
        return self._conn_ready.wait(
            timeout if timeout is not None else self.timeout
        )

    def request(self, msg):
        """Send one request and read its response (serialized)."""
        with self._req_mtx:  # cometlint: disable=CLNT009 -- the request mutex pairs one signer request with its response on the shared socket
            conn = self._conn
            if conn is None:
                if not self._conn_ready.wait(self.timeout):
                    raise TimeoutError("no remote signer connected")
                conn = self._conn
                if conn is None:
                    raise TimeoutError("no remote signer connected")
            try:
                conn.send(msg)
                return conn.recv()
            except Exception:
                self._drop_conn()
                raise


class SignerDialerEndpoint:
    """Signer-side endpoint: dials the node with retries
    (privval/signer_dialer_endpoint.go)."""

    def __init__(
        self,
        addr: str,
        signer_priv_key: Ed25519PrivKey | None = None,
        timeout: float = 5.0,
        max_retries: int = 10,
        retry_wait: float = 0.5,
    ):
        self.addr = addr
        self.timeout = timeout
        self.max_retries = max_retries
        self.retry_wait = retry_wait
        self.signer_priv_key = signer_priv_key or Ed25519PrivKey.generate()

    def dial(self) -> _Conn:
        proto_, target = parse_addr(self.addr)
        last_err: Exception | None = None
        for _ in range(self.max_retries):
            try:
                if proto_ == "tcp":
                    sock = socket.create_connection(
                        target, timeout=self.timeout
                    )
                    from ..p2p.conn.secret_connection import SecretConnection

                    secret = SecretConnection(sock, self.signer_priv_key)
                    return _Conn(sock, secret)
                sock = socket.socket(socket.AF_UNIX)
                sock.settimeout(self.timeout)
                sock.connect(target)
                return _Conn(sock)
            except OSError as e:
                last_err = e
                time.sleep(self.retry_wait)
        raise ConnectionError(
            f"cannot reach validator at {self.addr}: {last_err!r}"
        )


class SignerServer(BaseService):
    """The remote signing process: FilePV behind a socket
    (privval/signer_server.go). Dials the validator node and serves
    PubKey/SignVote/SignProposal/Ping until stopped."""

    def __init__(
        self, endpoint: SignerDialerEndpoint, chain_id: str, priv_val, logger=None
    ):
        super().__init__("SignerServer", logger)
        self.endpoint = endpoint
        self.chain_id = chain_id
        self.priv_val = priv_val  # any PrivValidator (FilePV in production)
        self.logger = logger or logmod.default_logger().with_module("privval")
        self._thread = None

    def on_start(self) -> None:
        self._thread = threading.Thread(
            target=self._serve_loop, name="privval-server", daemon=True
        )
        self._thread.start()

    def on_stop(self) -> None:
        pass  # the serve loop exits on is_running() / connection close

    def _serve_loop(self) -> None:
        while self.is_running():
            try:
                conn = self.endpoint.dial()
            except ConnectionError as e:
                self.logger.error("dial failed", err=repr(e))
                time.sleep(1.0)
                continue
            self.logger.info("serving validator", addr=self.endpoint.addr)
            try:
                while self.is_running():
                    req = conn.recv()
                    conn.send(self._handle(req))
            except (EOFError, OSError, socket.timeout) as e:
                if self.is_running():
                    self.logger.error("connection lost", err=repr(e))
            finally:
                conn.close()

    def _handle(self, req):
        try:
            if isinstance(req, PingRequest):
                return PingResponse()
            if isinstance(req, PubKeyRequest):
                pub = self.priv_val.get_pub_key()
                return PubKeyResponse(
                    pub_key_type=pub.type, pub_key_bytes=pub.bytes()
                )
            if isinstance(req, SignVoteRequest):
                self.priv_val.sign_vote(
                    req.chain_id,
                    req.vote,
                    sign_extension=not req.skip_extension_signing,
                )
                return SignedVoteResponse(vote=req.vote)
            if isinstance(req, SignProposalRequest):
                self.priv_val.sign_proposal(req.chain_id, req.proposal)
                return SignedProposalResponse(proposal=req.proposal)
        except Exception as e:  # double-sign protection etc. -> error resp
            kind = type(req).__name__
            if isinstance(req, SignVoteRequest):
                return SignedVoteResponse(error_code=2, error_desc=str(e))
            if isinstance(req, SignProposalRequest):
                return SignedProposalResponse(error_code=2, error_desc=str(e))
            return PubKeyResponse(error_code=2, error_desc=f"{kind}: {e}")
        return PubKeyResponse(error_code=1, error_desc="unknown request")


class SignerClient(PrivValidator):
    """PrivValidator over a SignerListenerEndpoint
    (privval/signer_client.go). The consensus engine can't tell it from a
    FilePV; double-sign protection lives with the remote key."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self.endpoint = endpoint
        self.chain_id = chain_id
        self._pub_key = None

    def close(self) -> None:
        self.endpoint.stop()

    def ping(self) -> None:
        resp = self.endpoint.request(PingRequest())
        if not isinstance(resp, PingResponse):
            raise RemoteSignerError(1, f"unexpected ping response {resp!r}")

    def get_pub_key(self):
        if self._pub_key is None:
            resp = self.endpoint.request(PubKeyRequest(chain_id=self.chain_id))
            if not isinstance(resp, PubKeyResponse):
                raise RemoteSignerError(1, f"unexpected response {resp!r}")
            if resp.error_code:
                raise RemoteSignerError(resp.error_code, resp.error_desc)
            cls = PUBKEY_TYPES[resp.pub_key_type]
            self._pub_key = cls(resp.pub_key_bytes)
        return self._pub_key

    def sign_vote(
        self, chain_id: str, vote: Vote, sign_extension: bool = True
    ) -> None:
        resp = self.endpoint.request(
            SignVoteRequest(
                vote=vote,
                chain_id=chain_id,
                skip_extension_signing=not sign_extension,
            )
        )
        if not isinstance(resp, SignedVoteResponse):
            raise RemoteSignerError(1, f"unexpected response {resp!r}")
        if resp.error_code:
            raise RemoteSignerError(resp.error_code, resp.error_desc)
        if resp.vote is None:
            raise RemoteSignerError(1, "signed-vote response missing vote")
        # Adopt the WHOLE signed vote, not just the signature: the remote
        # FilePV's crash-replay path re-signs the same HRS by rewinding
        # the timestamp to the originally signed one (file_pv
        # check_only_differs_by_timestamp); pairing the caller's newer
        # timestamp with the old-timestamp signature would make every
        # peer reject the vote. (Reference: signer_client.go does
        # *vote = *resp.Vote.)
        for f in dataclasses.fields(Vote):
            setattr(vote, f.name, getattr(resp.vote, f.name))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        resp = self.endpoint.request(
            SignProposalRequest(proposal=proposal, chain_id=chain_id)
        )
        if not isinstance(resp, SignedProposalResponse):
            raise RemoteSignerError(1, f"unexpected response {resp!r}")
        if resp.error_code:
            raise RemoteSignerError(resp.error_code, resp.error_desc)
        if resp.proposal is None:
            raise RemoteSignerError(
                1, "signed-proposal response missing proposal"
            )
        for f in dataclasses.fields(Proposal):
            setattr(proposal, f.name, getattr(resp.proposal, f.name))


class RetrySignerClient(PrivValidator):
    """Retry wrapper (privval/retry_signer_client.go): transient endpoint
    failures (signer restarting, ping-dropped conn) retry with backoff;
    remote signing REFUSALS (double-sign protection) do not."""

    def __init__(self, client: SignerClient, retries: int = 5, wait: float = 0.4):
        self.client = client
        self.retries = retries
        self.wait = wait

    def close(self) -> None:
        self.client.close()

    def _retry(self, fn):
        last: Exception | None = None
        for _ in range(self.retries):
            try:
                return fn()
            except RemoteSignerError:
                raise  # the signer answered: a refusal is final
            except Exception as e:
                last = e
                time.sleep(self.wait)
        raise last

    def get_pub_key(self):
        return self._retry(self.client.get_pub_key)

    def sign_vote(self, chain_id, vote, sign_extension: bool = True) -> None:
        return self._retry(
            lambda: self.client.sign_vote(chain_id, vote, sign_extension)
        )

    def sign_proposal(self, chain_id, proposal) -> None:
        return self._retry(
            lambda: self.client.sign_proposal(chain_id, proposal)
        )
