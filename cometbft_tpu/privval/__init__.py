"""Private validator implementations (reference: privval/)."""

from .file_pv import FilePV, LastSignState  # noqa: F401
