"""proxy.AppConns — 4 named ABCI connections (reference:
proxy/multi_app_conn.go:21-193, proxy/app_conn.go:18-58).

The node talks to its application over four logical connections —
consensus, mempool, query, snapshot — so mempool CheckTx traffic never
queues behind block execution. For a local app they share one mutex (the
reference's ``NewLocalClientCreator``); for a socket app each connection
is its own socket. A client error triggers ``on_error`` (the reference
kills the node — fail-stop, multi_app_conn.go:129).
"""

from __future__ import annotations

from .libs import sync as libsync
from typing import Callable

from .abci.application import Application
from .abci.client import Client, LocalClient
from .libs.service import BaseService

ClientCreator = Callable[[], Client]


def local_client_creator(app: Application) -> ClientCreator:
    """All four connections share one mutex around one in-process app."""
    mtx = libsync.RLock("proxy.mtx")
    return lambda: LocalClient(app, mtx)


def socket_client_creator(addr: str) -> ClientCreator:
    from .abci.socket_client import SocketClient

    return lambda: SocketClient(addr)


def grpc_client_creator(addr: str) -> ClientCreator:
    """ABCI over gRPC (proxy/client.go's grpc transport option)."""
    from .abci.grpc import GrpcClient

    return lambda: GrpcClient(addr)


class AppConns(BaseService):
    def __init__(
        self,
        creator: ClientCreator,
        on_error: Callable[[Exception], None] | None = None,
    ):
        super().__init__("proxy-app-conns")
        self._creator = creator
        self._on_error = on_error
        self.consensus: Client | None = None
        self.mempool: Client | None = None
        self.query: Client | None = None
        self.snapshot: Client | None = None

    def on_start(self) -> None:
        started = []
        try:
            for name in ("query", "snapshot", "mempool", "consensus"):
                client = self._creator()
                client.set_error_callback(self.kill_on_client_error)
                client.start()
                started.append(client)
                setattr(self, name, client)
        except BaseException:
            for c in started:
                try:
                    c.stop()
                except Exception:
                    pass
            raise

    def on_stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c is not None and c.is_running():
                try:
                    c.stop()
                except Exception:
                    pass

    def kill_on_client_error(self, err: Exception) -> None:
        if self._on_error:
            self._on_error(err)
