"""Evidence of Byzantine behavior: pool, verification, gossip
(reference: evidence/)."""

from .pool import EvidencePool  # noqa: F401
from .verify import verify_evidence, verify_duplicate_vote  # noqa: F401
from .reactor import EvidenceReactor  # noqa: F401
