"""Evidence pool (reference: evidence/pool.go:30-574).

Pending evidence lives in the DB (and on a clist for gossip) until a
block commits it; committed markers prevent resubmission. Consensus
reports conflicting votes here (``report_conflicting_votes``, pool.go:180
— called from tryAddVote); the proposer reaps with ``pending_evidence``.
"""

from __future__ import annotations

from ..libs import sync as libsync

from ..libs import db as dbm
from ..libs.clist import CList
from ..types import serialization as ser
from ..types.evidence import DuplicateVoteEvidence, EvidenceError
from .verify import verify_evidence

_PENDING = b"evP:"
_COMMITTED = b"evC:"


def _key(prefix: bytes, ev) -> bytes:
    return prefix + b"%020d:" % ev.height() + ev.hash()


class EvidencePool:
    def __init__(self, db: dbm.DB, state_store, block_store):
        self.db = db
        self.state_store = state_store
        self.block_store = block_store
        self._mtx = libsync.Mutex("evidence.pool._mtx")
        self.evidence_list = CList()  # gossip tail
        self._in_list: dict[bytes, object] = {}  # hash -> CElement
        # load persisted pending evidence into the gossip list
        for key, raw in self.db.iterator(_PENDING, dbm.prefix_end(_PENDING)):
            ev = ser.loads(raw)
            self._in_list[ev.hash()] = self.evidence_list.push_back(ev)

    # -- queries -----------------------------------------------------------

    def pending_evidence(self, max_bytes: int) -> list:
        """pool.go PendingEvidence — for block proposal."""
        out, total = [], 0
        for el in self.evidence_list:
            ev = el.value
            size = len(ser.dumps(ev))
            if max_bytes >= 0 and total + size > max_bytes:
                break
            out.append(ev)
            total += size
        return out

    def is_pending(self, ev) -> bool:
        return self.db.has(_key(_PENDING, ev))

    def is_committed(self, ev) -> bool:
        return self.db.has(_key(_COMMITTED, ev))

    # -- ingress -----------------------------------------------------------

    def add_evidence(self, ev) -> None:
        """pool.go:135 AddEvidence: dedup → verify → persist → gossip."""
        with self._mtx:  # cometlint: disable=CLNT009 -- byzantine evidence is rare; verify+persist must be atomic for dedup
            if self.is_pending(ev) or self.is_committed(ev):
                return
            self.verify(ev)
            self._add_pending_locked(ev)

    def _add_pending_locked(self, ev) -> None:
        self.db.set_sync(_key(_PENDING, ev), ser.dumps(ev))
        self._in_list[ev.hash()] = self.evidence_list.push_back(ev)

    def report_conflicting_votes(self, vote_a, vote_b) -> None:
        """pool.go:180 — from consensus on ConflictingVoteError. Builds the
        DuplicateVoteEvidence against the validator set at that height."""
        with self._mtx:  # cometlint: disable=CLNT009 -- conflicting-vote reports are rare; build+persist atomic under the pool mutex
            state = self.state_store.load()
            if state is None:
                return
            val_set = self.state_store.load_validators(vote_a.height)
            if val_set is None:
                return
            block_meta = (
                self.block_store.load_block_meta(vote_a.height)
                if self.block_store
                else None
            )
            time_ns = (
                block_meta.header.time_ns
                if block_meta is not None
                else state.last_block_time_ns
            )
            try:
                ev = DuplicateVoteEvidence.from_conflicting_votes(
                    vote_a, vote_b, time_ns, val_set
                )
            except EvidenceError:
                return
            if self.is_pending(ev) or self.is_committed(ev):
                return
            self._add_pending_locked(ev)

    # -- block validation hook (BlockExecutor) -----------------------------

    def verify(self, ev) -> None:
        state = self.state_store.load()
        if state is None:
            raise EvidenceError("no state to verify evidence against")
        val_set = self.state_store.load_validators(ev.height())
        if val_set is None:
            raise EvidenceError(
                f"no validator set stored for height {ev.height()}"
            )
        verify_evidence(ev, state, val_set)

    def check_evidence(self, evidence_list) -> None:
        """pool.go:193 CheckEvidence — full verification of a proposed
        block's evidence; duplicates within the block are rejected."""
        seen = set()
        for ev in evidence_list or ():
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)
            if self.is_committed(ev):
                raise EvidenceError("evidence was already committed")
            if not self.is_pending(ev):
                self.verify(ev)

    # -- post-commit -------------------------------------------------------

    def update(self, state, evidence_list) -> None:
        """pool.go Update — mark committed, drop from pending, prune."""
        with self._mtx:  # cometlint: disable=CLNT009 -- commit-time evidence pruning is once per height
            for ev in evidence_list or ():
                self.db.set(_key(_COMMITTED, ev), b"\x01")
                self._remove_pending(ev)
            self._prune_expired(state)

    def _remove_pending(self, ev) -> None:
        self.db.delete(_key(_PENDING, ev))
        el = self._in_list.pop(ev.hash(), None)
        if el is not None:
            self.evidence_list.remove(el)

    def _prune_expired(self, state) -> None:
        params = state.consensus_params.evidence
        for el in list(self.evidence_list):
            ev = el.value
            if (
                state.last_block_height - ev.height()
                > params.max_age_num_blocks
                and state.last_block_time_ns - ev.time_ns()
                > params.max_age_duration_ns
            ):
                self._remove_pending(ev)
