"""Evidence gossip reactor (reference: evidence/reactor.go, channel 0x38).

Clist-tailing broadcast like the mempool reactor; received evidence goes
through the pool's full verification before being gossiped onward.
"""

from __future__ import annotations

import threading

from ..p2p.base_reactor import ChannelDescriptor, Reactor
from ..types import serialization as ser
from ..types.evidence import EvidenceError
from .pool import EvidencePool

EVIDENCE_CHANNEL = 0x38


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool):
        super().__init__("evidence-reactor")
        self.pool = pool

    def get_channels(self):
        return [
            ChannelDescriptor(
                id=EVIDENCE_CHANNEL, priority=6, send_queue_capacity=100
            )
        ]

    def add_peer(self, peer) -> None:
        if getattr(peer, "sim_driven", False):
            # simnet peers: the scheduler calls gossip_step on virtual
            # ticks instead of a clist-tailing thread per peer
            return
        threading.Thread(
            target=self._broadcast_routine,
            args=(peer,),
            name=f"evidence-bcast-{peer.id[:8]}",
            daemon=True,
        ).start()

    # virtual-ns interval after which still-pending evidence is offered
    # again (simnet links may silently eat a send — unlike TCP, where a
    # True send is delivered or the conn dies and a reconnect resets the
    # gossip cursor — so a one-shot offer could lose the only copy)
    REOFFER_NS = 1_000_000_000

    def gossip_step(self, peer, now_ns: int = 0) -> int:
        """Simnet tick: send every pending evidence this peer hasn't
        been offered recently (the clist cursor of the thread path,
        without blocking waits, plus periodic re-offers while the item
        stays pending).  Returns the number of items sent."""
        sent = peer.get("evidence_sent")
        if sent is None:
            sent = {}  # evidence hash -> virtual ns of last offer
            peer.set("evidence_sent", sent)
        n = 0
        for el in self.pool.evidence_list:
            if el.removed:
                continue
            ev = el.value
            h = ev.hash()
            last = sent.get(h)
            if last is not None and now_ns - last < self.REOFFER_NS:
                continue
            if peer.send(EVIDENCE_CHANNEL, ser.dumps(ev)):
                sent[h] = now_ns
                n += 1
        return n

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        try:
            ev = ser.loads(msg_bytes)
            self.pool.add_evidence(ev)
        except (EvidenceError, ValueError, KeyError):
            if self.switch is not None:
                self.switch.stop_and_remove_peer(peer, "bad evidence")

    def _broadcast_routine(self, peer) -> None:
        el = None
        while peer.is_running() and self.is_running():
            if el is None:
                el = self.pool.evidence_list.front_wait(timeout=0.2)
                if el is None:
                    continue
            if not el.removed:
                if not peer.send(EVIDENCE_CHANNEL, ser.dumps(el.value)):
                    continue
            nxt = el.next_wait(timeout=0.2)
            if nxt is not None:
                el = nxt
            elif el.removed:
                el = None
