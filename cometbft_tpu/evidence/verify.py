"""Evidence verification (reference: evidence/verify.go:19-294)."""

from __future__ import annotations

from ..types.evidence import (
    DuplicateVoteEvidence,
    EvidenceError,
    LightClientAttackEvidence,
)
from ..types.validation import verify_commit_light_trusting, Fraction


def verify_evidence(ev, state, val_set_at_height, common_val_set=None) -> None:
    """evidence/verify.go:19 — age checks then type-specific verification.

    ``val_set_at_height``: validator set at ev.height (from state store).
    """
    height = state.last_block_height
    ev_params = state.consensus_params.evidence
    age_blocks = height - ev.height()
    age_ns = state.last_block_time_ns - ev.time_ns()
    if (
        age_blocks > ev_params.max_age_num_blocks
        and age_ns > ev_params.max_age_duration_ns
    ):
        raise EvidenceError(
            f"evidence from height {ev.height()} is too old "
            f"({age_blocks} blocks / {age_ns / 1e9:.0f}s)"
        )
    from ..libs import devledger

    # outermost ledger tenant: every routed verify under evidence
    # checking (vote signatures, the attack header's trusting commit
    # check) attributes to the evidence caller class
    with devledger.caller_class("evidence"):
        if isinstance(ev, DuplicateVoteEvidence):
            verify_duplicate_vote(ev, state.chain_id, val_set_at_height)
        elif isinstance(ev, LightClientAttackEvidence):
            verify_light_client_attack(
                ev, state.chain_id, common_val_set or val_set_at_height
            )
        else:
            raise EvidenceError(
                f"unknown evidence type {type(ev).__name__}"
            )


def verify_duplicate_vote(
    ev: DuplicateVoteEvidence, chain_id: str, val_set
) -> None:
    """evidence/verify.go:167 VerifyDuplicateVote — 2 signature checks."""
    _, val = val_set.get_by_address(ev.vote_a.validator_address)
    if val is None:
        raise EvidenceError(
            f"address {ev.vote_a.validator_address.hex()} was not a "
            "validator at the evidence height"
        )
    # NOTE: no vote-TYPE restriction — the reference punishes PREVOTE
    # equivocation too (VerifyDuplicateVote:174-181 only requires equal
    # H/R/Type and differing block IDs). A precommit-only rule here once
    # made a proposer pack prevote-equivocation evidence its own block
    # validation then rejected — fatal at finalize (the evidence pool
    # and this verifier must accept the same set).
    ev.validate_basic()
    # recorded powers must match the set we verified against
    if ev.validator_power != val.voting_power:
        raise EvidenceError(
            f"validator power mismatch: {ev.validator_power} vs "
            f"{val.voting_power}"
        )
    if ev.total_voting_power != val_set.total_voting_power():
        raise EvidenceError("total voting power mismatch")
    for vote in (ev.vote_a, ev.vote_b):
        if not val.pub_key.verify_signature(
            vote.sign_bytes(chain_id), vote.signature
        ):
            raise EvidenceError("invalid signature on duplicate vote")


def verify_light_client_attack(
    ev: LightClientAttackEvidence, chain_id: str, common_val_set
) -> None:
    """evidence/verify.go:110 — the conflicting header must carry a
    commit trusted at 1/3 of the common validator set (the batched
    light-trusting path)."""
    ev.validate_basic()
    sh = ev.conflicting_block.signed_header
    verify_commit_light_trusting(
        chain_id, common_val_set, sh.commit, Fraction(1, 3)
    )
