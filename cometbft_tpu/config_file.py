"""TOML config file round-trip + per-section validation.

Reference: config/toml.go (template writer), config/config.go:73-1135
(per-section ValidateBasic). ``save_toml`` renders every section of the
dataclass tree with field comments preserved as TOML comments;
``load_toml`` reads one back over a default Config so missing keys keep
their defaults (the reference's viper behavior). ``validate_basic``
rejects the configurations that brick a node before it boots.
"""

from __future__ import annotations

import dataclasses
import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: fall back to the minimal
    tomllib = None  # reader below, which covers exactly what we render

from .config import Config, default_config


def _scan_value(s: str, i: int):
    """Parse one TOML value of the subset ``render_toml`` emits
    (strings with backslash escapes, ints, floats, bools, flat lists).
    Returns (value, index after the value)."""
    while i < len(s) and s[i] in " \t":
        i += 1
    if i >= len(s):
        raise ValueError("missing value")
    c = s[i]
    if c == '"':
        out = []
        i += 1
        esc = {"n": "\n", "r": "\r", "t": "\t", "\\": "\\", '"': '"'}
        while i < len(s) and s[i] != '"':
            if s[i] == "\\":
                i += 1
                if i >= len(s) or s[i] not in esc:
                    raise ValueError("bad string escape")
                out.append(esc[s[i]])
            else:
                out.append(s[i])
            i += 1
        if i >= len(s):
            raise ValueError("unterminated string")
        return "".join(out), i + 1
    if c == "[":
        vals = []
        i += 1
        while True:
            while i < len(s) and s[i] in " \t,":
                i += 1
            if i >= len(s):
                raise ValueError("unterminated array")
            if s[i] == "]":
                return vals, i + 1
            v, i = _scan_value(s, i)
            vals.append(v)
    j = i
    while j < len(s) and s[j] not in " \t#,]":
        j += 1
    tok = s[i:j]
    if tok == "true":
        return True, j
    if tok == "false":
        return False, j
    try:
        return int(tok), j
    except ValueError:
        return float(tok), j


def _parse_toml_minimal(text: str) -> dict:
    """Line-oriented reader for the flat ``[section]`` / ``key = value``
    subset this module writes. Loud on anything outside it — a config
    this code didn't render should be read with real tomllib."""
    root: dict = {}
    table = root
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            if not line.endswith("]"):
                raise ValueError(f"line {lineno}: malformed section header")
            name = line[1:-1].strip()
            if not name or "." in name:
                raise ValueError(
                    f"line {lineno}: unsupported section {name!r}"
                )
            table = root.setdefault(name, {})
            continue
        key, sep, rest = line.partition("=")
        if not sep:
            raise ValueError(f"line {lineno}: expected key = value")
        value, end = _scan_value(rest, 0)
        tail = rest[end:].strip()
        if tail and not tail.startswith("#"):
            raise ValueError(f"line {lineno}: trailing junk {tail!r}")
        table[key.strip()] = value
    return root

_SECTION_ORDER = (
    ("base", ""),  # base fields live at the top level, like the reference
    ("rpc", "rpc"),
    ("p2p", "p2p"),
    ("mempool", "mempool"),
    ("statesync", "statesync"),
    ("blocksync", "blocksync"),
    ("consensus", "consensus"),
    ("storage", "storage"),
    ("tx_index", "tx_index"),
    ("instrumentation", "instrumentation"),
)


def _render_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        out = v.replace("\\", "\\\\").replace('"', '\\"')
        out = out.replace("\n", "\\n").replace("\r", "\\r").replace(
            "\t", "\\t"
        )
        if any(ord(c) < 0x20 for c in out):
            raise ValueError(
                f"control characters not representable in config: {v!r}"
            )
        return '"' + out + '"'
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_render_value(x) for x in v) + "]"
    raise TypeError(f"unrenderable config value {v!r}")


def render_toml(cfg: Config) -> str:
    out = [
        "# CometBFT-TPU node configuration",
        "# Durations are integer nanoseconds (_ns suffix).",
        "",
    ]
    for attr, section in _SECTION_ORDER:
        sub = getattr(cfg, attr)
        if section:
            out.append(f"[{section}]")
        for f in dataclasses.fields(sub):
            out.append(f"{f.name} = {_render_value(getattr(sub, f.name))}")
        out.append("")
    return "\n".join(out)


def save_toml(cfg: Config, path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(render_toml(cfg))
    os.replace(tmp, path)


def load_toml(path: str, base: Config | None = None) -> Config:
    """Read a config file over defaults; unknown keys error loudly
    (a typo'd timeout silently keeping its default is how consensus
    misconfigurations ship)."""
    cfg = base if base is not None else default_config()
    with open(path, "rb") as fh:
        raw = fh.read()
    if tomllib is not None:
        data = tomllib.loads(raw.decode())
    else:
        data = _parse_toml_minimal(raw.decode())
    known_sections = {s for _, s in _SECTION_ORDER if s}
    for key, value in data.items():
        if isinstance(value, dict) and key not in known_sections:
            raise ValueError(f"unknown config section [{key}]")
    for attr, section in _SECTION_ORDER:
        sub = getattr(cfg, attr)
        payload = data if not section else data.get(section, {})
        field_names = {f.name for f in dataclasses.fields(sub)}
        for key, value in payload.items():
            if isinstance(value, dict):
                if not section:
                    continue  # sibling [section] table at top level
                raise ValueError(
                    f"unexpected nested table [{section}.{key}]"
                )
            if key not in field_names:
                raise ValueError(
                    f"unknown config key "
                    f"{(section + '.') if section else ''}{key}"
                )
            setattr(sub, key, value)
    return cfg


def validate_basic(cfg: Config) -> None:
    """Per-section ValidateBasic (config.go:232,370,523,...)."""
    errs: list[str] = []

    def need(cond: bool, msg: str) -> None:
        if not cond:
            errs.append(msg)

    b = cfg.base
    need(b.log_level in ("debug", "info", "error", "none"),
         f"base.log_level invalid: {b.log_level!r}")
    need(b.db_backend in ("file", "mem", "native"),
         f"base.db_backend invalid: {b.db_backend!r}")
    need(bool(b.proxy_app), "base.proxy_app must be set")

    p = cfg.p2p
    need(p.max_num_inbound_peers >= 0, "p2p.max_num_inbound_peers < 0")
    need(p.max_num_outbound_peers >= 0, "p2p.max_num_outbound_peers < 0")
    need(p.send_rate > 0, "p2p.send_rate must be positive")
    need(p.recv_rate > 0, "p2p.recv_rate must be positive")
    need(p.flush_throttle_timeout_ns >= 0, "p2p.flush_throttle_timeout < 0")

    m = cfg.mempool
    need(m.size > 0, "mempool.size must be positive")
    need(m.max_txs_bytes > 0, "mempool.max_txs_bytes must be positive")
    need(m.max_tx_bytes > 0, "mempool.max_tx_bytes must be positive")

    c = cfg.consensus
    for name in (
        "timeout_propose_ns", "timeout_propose_delta_ns",
        "timeout_prevote_ns", "timeout_prevote_delta_ns",
        "timeout_precommit_ns", "timeout_precommit_delta_ns",
        "timeout_commit_ns",
    ):
        need(getattr(c, name) >= 0, f"consensus.{name} < 0")
    need(c.timeout_propose_ns > 0, "consensus.timeout_propose must be > 0")

    s = cfg.statesync
    if s.enable:
        need(len(s.rpc_servers) >= 1,
             "statesync.rpc_servers required when statesync is enabled")
        need(s.trust_height > 0,
             "statesync.trust_height required when statesync is enabled")
        need(len(s.trust_hash) == 64,
             "statesync.trust_hash must be 32 hex bytes")
        need(s.trust_period_ns > 0, "statesync.trust_period must be > 0")

    need(cfg.tx_index.indexer in ("kv", "sqlite", "null"),
         f"tx_index.indexer invalid: {cfg.tx_index.indexer!r}")

    if errs:
        raise ValueError("invalid config: " + "; ".join(errs))
