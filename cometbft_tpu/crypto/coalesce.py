"""Cross-caller verify coalescer: micro-batched, double-buffered device
launches for the steady-state vote path.

Without this module the TPU is only reachable from whole-commit
verification: an individually-gossiped vote carries ONE signature, one
signature can never cross the host/device crossover
(crypto/batch.host_batch_threshold), so a realistic 100-200-validator
set verifies every steady-state vote serially on the host. Committee-
based-consensus measurements show per-vote EdDSA verification
dominating vote processing and batch verification recovering most of it
(arXiv:2302.00418); pipelined hardware verification engines get their
throughput from keeping the verifier FED with coalesced work rather
than per-request dispatch (arXiv:2112.02229). This module is that
feeder for the verify kernel:

* concurrent callers — vote admission (types/vote_set.py), the
  proposal-signature check (consensus/state.py), evidence/light single
  verifies (types/vote.py routes them all), and sub-crossover batch
  verifiers (crypto/batch.py) — submit signature lanes and block on a
  per-submit ticket;
* the executor thread coalesces lanes into fixed-shape-bucket device
  micro-batches (the same bucket discipline as every other launch —
  the no-recompile guard stays green), flushed by a size threshold
  (COMETBFT_TPU_COALESCE_MAX_LANES) or a small deadline window
  (COMETBFT_TPU_COALESCE_WINDOW_US);
* windows pipeline through the ``verify_bytes_async`` /
  ``verify_rsk_async`` split plus a dedicated readback drain thread:
  the host-side pack + arena lookup of window N+1 overlaps the device
  execute of window N, and window N's d2h readback materializes on the
  drain thread while N+1 executes — under sustained load the device
  never idles between launches and the per-window cost approaches
  max(execute, readback) instead of their sum. The drain is strictly
  FIFO (tickets resolve in submission order) and the executor blocks
  at the COMETBFT_TPU_COALESCE_INFLIGHT depth bound (default 2, the
  classic double buffer);
* steady-state lanes are index-only: the consensus FSM prestages the
  validator set (crypto/batch.prestage_validators), so a window whose
  signers are arena-resident ships 96 B of R|S|kneg plus a 2-byte slot
  per lane through ``verify_rsk_async``;
* host fallback is clean: device absent -> windows run the native host
  RLC batch (still one MSM for the whole window — coalescing wins on
  host too); sub-``min_device_lanes`` windows run host; shutdown
  drains every pending ticket before ``stop()`` returns; an absent or
  stopped coalescer leaves callers on their unrouted paths.

Behavioral identity: a lane's verdict is computed by the same kernels /
host verifiers as every other batch path, so admission decisions are
bit-identical to ``pub_key.verify_signature``; an exception raised
while staging one submit's lanes fails only that submit's ticket.

Locking: ``crypto.coalesce._mtx`` guards the pending queue — the flush
path pops a window under it and releases it before pack, dispatch, the
materializing readback, and ticket resolution; ``crypto.coalesce.
_rb_mtx`` guards only the executor->drain handoff (the drain pops
under it and releases it before the readback). Neither blocks on the
device while held and neither acquires an engine mutex (both asserted
edge-free by tests/test_lint_graph.py).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque

from ..libs import devledger as libdevledger
from ..libs import health as libhealth
from ..libs import metrics as libmetrics
from ..libs import sync as libsync
from ..libs import trace as libtrace
from ..libs.service import BaseService, ServiceError
from .keys import ED25519_KEY_TYPE

# Deadline window before a sub-size window flushes anyway. 500 us is
# ~an order of magnitude under the per-window device cost, so the
# deadline adds negligible latency while letting concurrent callers
# pile into one launch.
_DEFAULT_WINDOW_US = 500
# Lanes that trigger an immediate size flush (and the per-window cap).
# 1024 covers a full prevote round of a 1000-validator set in one
# launch; typical 100-200-validator windows land in the 128/256
# buckets.
_DEFAULT_MAX_LANES = 1024
# Windows below the device cutover verify on host — still ONE RLC MSM
# per window, so coalescing wins there too (the container bench
# measures 4-12x over serial); the cutover defaults to the LIVE
# host/device crossover (crypto/batch.host_batch_threshold: env pin >
# adaptive calibration > chip-table seed) because a sub-crossover
# window on the device is, by that same measurement, slower than the
# host MSM it displaces. The knob/ctor arg pins a fixed count (tests,
# bench device-path probes).

# Ticket wait bound for the routed helpers. Routed callers hold engine
# mutexes while they wait (vote admission under vote_set, the proposal
# check under consensus.state), so this bound is ALSO the worst-case
# consensus stall a wedged device can inflict — it must stay near the
# round-timeout scale, not the relay tunnel's transient ceiling. On
# expiry the helper falls back to an unrouted host verify (verdict
# still correct, the work paid twice) and trips the cooldown breaker
# below; a tunnel transient that outlives this bound therefore costs
# one short cooldown of host routing, never a frozen node.
_RESULT_TIMEOUT_S = 5.0
# Device windows dispatched but not yet materialized, across the
# executor and the readback drain thread. 2 = the classic double
# buffer (window N materializing on the drain thread while the
# executor packs + dispatches N+1); raising it deepens the pipeline at
# the cost of more staged wire memory in flight.
_DEFAULT_MAX_INFLIGHT = 2
# How long a tripped coalescer stays unrouted before routing re-arms.
# While tripped, every caller falls back to host instantly and the
# groups already queued behind the (possibly wedged) executor are
# handed to a host rescue thread; on expiry the FIRST routed verify
# claims the half-open probe (try_verify pushes the deadline forward
# for everyone else) — probe success re-arms routing for all, another
# timeout re-trips. A dead device degrades throughput by at most one
# bounded stall per cooldown and a recovered device is picked back up
# without a node restart.
_TRIP_COOLDOWN_S = 30.0


class CoalescerStoppedError(ServiceError):
    """submit() after the drain began — callers fall back to host."""


# -- per-request deadline propagation ---------------------------------------
#
# Request-scoped callers (the light-client proof service serves thousands
# of concurrent RPC clients, each with its own deadline) wrap their work
# in ``request_deadline``; every coalescer ticket wait on that thread is
# then bounded by the REQUEST's remaining budget, not just the global
# wedge bound. A deadline-capped timeout is the caller running out of
# time, not evidence of a wedged executor — it must never trip the
# breaker (that would unroute a healthy device for every other caller).

_DEADLINE_TLS = threading.local()


@contextlib.contextmanager
def request_deadline(deadline_monotonic: float):
    """Bound every coalescer wait on this thread by a monotonic deadline.

    Nested scopes tighten, never loosen: an inner deadline later than
    the enclosing one is clamped to the outer budget.
    """
    prev = getattr(_DEADLINE_TLS, "deadline", None)
    _DEADLINE_TLS.deadline = (
        deadline_monotonic if prev is None else min(prev, deadline_monotonic)
    )
    try:
        yield
    finally:
        _DEADLINE_TLS.deadline = prev


def deadline_remaining() -> float | None:
    """Seconds left in this thread's request deadline (None = unbounded).

    May be negative once the deadline has passed — callers treat <= 0
    as expired."""
    d = getattr(_DEADLINE_TLS, "deadline", None)
    if d is None:
        return None
    return d - time.monotonic()


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_opt_int(name: str) -> int | None:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return None


class _Ticket:
    """One submit()'s pending verdict.

    Resolved exactly once by the executor (or the shutdown drain) with
    either the per-lane validity bits or the exception that killed this
    submit's lanes — never the whole window's.
    """

    __slots__ = ("n", "caller", "t_submit", "_done", "_bits", "_exc")

    def __init__(self, n: int, caller: int = 0):
        self.n = n
        # caller class (libs/devledger enum) captured at submit from
        # the submitting thread's declaration — the device-time
        # ledger's attribution key
        self.caller = caller
        self.t_submit = time.perf_counter()
        self._done = threading.Event()
        self._bits: list[bool] | None = None
        self._exc: BaseException | None = None

    def resolve(self, bits) -> None:
        self._bits = list(bits)
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None) -> list[bool]:
        """Block for this submit's verdict bits.

        Callers may hold engine mutexes here (vote admission waits
        under ``vote_set``, the proposal check under
        ``consensus.state``) — the wait is sanctioned: it is bounded by
        the coalescer's flush-window deadline plus one device launch,
        it replaces equal-or-longer inline host verification under the
        same locks, and the executor thread that resolves it never
        acquires an engine mutex (tests/test_lint_graph.py pins that),
        so no lock cycle can form through it.
        """
        ok = self._done.wait(timeout)  # cometlint: disable=CLNT009 -- bounded coalescer wait: resolved within the flush-window deadline + one launch by the executor thread, which acquires no engine mutex (asserted leaf in test_lint_graph); replaces equal-or-longer inline host verification under the same caller locks
        if not ok:
            raise TimeoutError(
                "coalesced verify not resolved within "
                f"{timeout}s ({self.n} lanes)"
            )
        if self._exc is not None:
            raise self._exc
        return list(self._bits or [])


class _Inflight:
    """A dispatched-but-unmaterialized window (double-buffer slot)."""

    __slots__ = (
        "finish", "host_ok", "groups", "lanes", "reason", "prep_s",
        "wire", "t_launch",
    )

    def __init__(
        self, finish, host_ok, groups, lanes, reason, prep_s, wire,
        t_launch=0.0,
    ):
        self.finish = finish  # zero-arg materializer from ops/verify
        self.host_ok = host_ok
        self.groups = groups  # [(ticket, lo, n)]
        self.lanes = lanes
        self.reason = reason
        # pack-start-to-dispatch-end seconds, banked at launch: the
        # adaptive-crossover feed is prep + readback, NOT wall time to
        # _finish — the double buffer interleaves window N+1's collect
        # wait and pack before N materializes, and charging that idle
        # gap to the device would systematically overstate its cost
        self.prep_s = prep_s
        self.wire = wire  # (pubkeys, msgs, sigs) for fault recovery
        # window pop time: the queue-wait anchor the ledger charges
        # tickets against (submit -> launch is queueing; launch ->
        # resolve is execute)
        self.t_launch = t_launch


class VerifyCoalescer(BaseService):
    """Background verify executor coalescing single-signature callers.

    ``submit`` enqueues raw ed25519 (pubkey32, msg, sig64) lanes and
    returns a ticket; the executor thread flushes windows by size or
    deadline, double-buffering device launches. See the module
    docstring for the full design.
    """

    # how long on_stop waits for the executor before the safety net
    # takes over the remaining tickets (tests shrink this)
    _JOIN_TIMEOUT_S = 10.0

    def __init__(
        self,
        window_us: int | None = None,
        max_lanes: int | None = None,
        min_device_lanes: int | None = None,
        device: bool | None = None,
        max_inflight: int | None = None,
        logger=None,
    ):
        super().__init__("VerifyCoalescer", logger)
        self.window_s = (
            window_us
            if window_us is not None
            else _env_int("COMETBFT_TPU_COALESCE_WINDOW_US", _DEFAULT_WINDOW_US)
        ) / 1e6
        self.max_lanes = max(
            1,
            max_lanes
            if max_lanes is not None
            else _env_int("COMETBFT_TPU_COALESCE_MAX_LANES", _DEFAULT_MAX_LANES),
        )
        # None = defer to the live crossover at flush time
        self.min_device_lanes: int | None = (
            min_device_lanes
            if min_device_lanes is not None
            else _env_opt_int("COMETBFT_TPU_COALESCE_MIN_DEVICE_LANES")
        )
        # None = defer to the process-wide accelerator probe
        # (libs/accel); True/False pin (tests, bench, the dead-tunnel
        # host branch).
        self._device = device
        self._mtx = libsync.Mutex("crypto.coalesce._mtx")
        self._cv = libsync.Condition(self._mtx, name="crypto.coalesce._mtx")
        # pending groups: (ticket, pubkeys, msgs, sigs). A deque: the
        # flush pops hundreds of 1-lane groups per window while holding
        # _mtx, and list.pop(0) would shuffle the whole backlog under
        # the same lock every submit needs.
        self._pending: deque[tuple] = deque()
        self._pending_lanes = 0
        # lockfree: drain gate — locked writes, advisory fast-path reads; a stale read routes one submit to the host fallback
        self._draining = False
        # Lock-free running flag read by submit()/active(): consulting
        # BaseService.is_running there would acquire libs.service._mtx
        # under crypto.coalesce._mtx (or under caller engine mutexes)
        # and grow the lock graph for a boolean. Benign races resolve
        # to the host fallback.
        # lockfree: locked writes, advisory fast-path reads (see above)
        self._accepting = False
        # monotonic deadline until which the breaker keeps this
        # coalescer unrouted (0.0 = armed); see _TRIP_COOLDOWN_S
        # lockfree: breaker deadline — locked writes, racy reads re-check under the lock before re-arming; a stale read only delays routing one window
        self._tripped_until = 0.0
        self._thread: threading.Thread | None = None
        # -- readback drain: dispatched windows hand off to a dedicated
        # drain thread that materializes them IN SUBMISSION ORDER, so
        # the executor starts packing + dispatching window N+1 while
        # window N's d2h readback is still in flight. The depth bound
        # (max_inflight) counts queued + mid-finish windows; the
        # executor blocks at the bound so device memory in flight stays
        # bounded. _rb_mtx guards ONLY this handoff bookkeeping — the
        # drain pops under it and releases it before the materializing
        # readback and ticket resolution (same leaf contract as _mtx).
        self.max_inflight = max(
            1,
            max_inflight
            if max_inflight is not None
            else _env_int(
                "COMETBFT_TPU_COALESCE_INFLIGHT", _DEFAULT_MAX_INFLIGHT
            ),
        )
        self._rb_mtx = libsync.Mutex("crypto.coalesce._rb_mtx")
        self._rb_cv = libsync.Condition(
            self._rb_mtx, name="crypto.coalesce._rb_mtx"
        )
        self._readback: deque[_Inflight] = deque()
        self._rb_busy = 0  # windows the drain popped but hasn't finished
        self._rb_closed = False
        self._rb_alive = False
        self._rb_thread: threading.Thread | None = None
        # dispatched-but-unmaterialized windows, mirrored here (the
        # executor appends, the drain thread drops) so the rescue
        # paths can reach their tickets — a popped window is in
        # neither _pending nor any caller's hands. At most
        # max_inflight live at once (the drain depth bound).
        # lockfree: flight ring — executor appends, drain thread removes, rescues snapshot via tuple(); GIL-atomic list ops, single writer per end
        self._inflights: list[_Inflight] = []
        # the window currently inside _launch (popped from _pending,
        # not yet host-resolved or published to _inflights): same
        # single-writer mirror, so an executor wedged mid-dispatch
        # cannot take these tickets beyond the rescues' reach
        self._staging: list[tuple] | None = None
        # windows flushed / tickets accepted, for tests and /debug
        # dumps: windows < tickets means at least one window carried
        # lanes from more than one submitter — the sharing the module
        # exists for
        self.windows = 0
        self.device_windows = 0
        self.tickets = 0

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        with self._mtx:
            self._draining = False
        with self._rb_mtx:
            self._rb_closed = False
            self._rb_alive = True
        rt = threading.Thread(
            target=self._drain_run, name="verify-readback", daemon=True
        )
        rt.start()
        # lockfree: start/stop lifecycle handle, written only by the thread driving the service transition
        self._rb_thread = rt
        t = threading.Thread(
            target=self._run, name="verify-coalescer", daemon=True
        )
        # accept only once the executor exists: if the spawn throws,
        # submits must keep raising (host fallback) rather than queue
        # lanes nobody will ever flush
        t.start()
        # lockfree: start/stop lifecycle handle, written only by the thread driving the service transition
        self._thread = t
        with self._mtx:
            self._accepting = True

    def on_stop(self) -> None:
        """Drain: every pending ticket is resolved before stop returns."""
        with self._mtx:
            self._draining = True
            self._accepting = False
            self._cv.notify_all()
        with self._rb_mtx:
            # wake an executor blocked at the in-flight depth bound
            self._rb_cv.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self._JOIN_TIMEOUT_S)
        rt = self._rb_thread
        if rt is not None and rt is not threading.current_thread():
            self._close_readback()
            rt.join(timeout=self._JOIN_TIMEOUT_S)
        # Safety net: if the executor died (or the join timed out with
        # it wedged), resolve leftovers on host so no caller hangs —
        # including a window the executor popped and dispatched but
        # never materialized (wedged in a device stall). Racing the
        # still-alive executor is benign: done() gates both sides and a
        # double resolution carries identical verdicts.
        with self._mtx:
            leftovers, self._pending = self._pending, deque()
            self._pending_lanes = 0
        for group in leftovers:
            self._resolve_group_host(group)
        # a window the wedged executor popped but never dispatched
        # (stuck inside _launch) is visible only through the staging
        # slot; don't clear it — the executor owns the slot, and
        # done() gates make a late double resolution benign
        for group in self._staging or ():
            self._resolve_group_host(group)
        for fl in tuple(self._inflights):
            self._rescue_inflight(fl)
            self._drop_inflight(fl)

    # -- submission --------------------------------------------------------

    def submit(self, pubkeys, msgs, sigs) -> _Ticket:
        """Queue raw ed25519 lanes; returns the ticket with their bits.

        ``pubkeys[i]`` is the 32-byte key encoding (``PubKey.data``),
        not a key object — the wire format the packers consume.
        Raises :class:`CoalescerStoppedError` once the drain has begun
        (callers fall back to their unrouted verify).
        """
        return self.submit_many([(pubkeys, msgs, sigs)])[0]

    def submit_many(self, groups) -> list[_Ticket]:
        """Batch-submit several lane groups as ONE queue transaction.

        ``groups`` is a sequence of ``(pubkeys, msgs, sigs)`` triples;
        returns one ticket per group, in order. All groups land in the
        pending queue under a single mutex acquisition with a single
        executor wake-up, so a multi-window caller (an oversized batch
        chunked by :meth:`try_verify`, or the light service issuing a
        whole commit's lanes) cannot interleave with other submitters
        mid-batch — its chunks pack into consecutive windows. Raises
        :class:`CoalescerStoppedError` once the drain has begun.
        """
        tickets: list[_Ticket] = []
        staged: list[tuple] = []
        cid = libdevledger.current_caller()
        for pks, ms, ss in groups:
            t = _Ticket(len(pks), cid)
            tickets.append(t)
            if t.n == 0:
                t.resolve([])
            else:
                staged.append((t, pks, ms, ss))
        if not staged:
            return tickets
        with self._mtx:
            # the breaker gates ROUTING (active()/_claim_probe), not
            # direct submits: a tripped-but-alive executor still
            # flushes, and a wedged one's queue is drained by the next
            # trip's host rescue, so accepted lanes never leak
            if self._draining or not self._accepting:
                raise CoalescerStoppedError(self._name)
            for g in staged:
                self._pending.append(g)
                self._pending_lanes += g[0].n
            self.tickets += len(staged)
            self._cv.notify_all()
        return tickets

    def try_verify(self, pubkeys, msgs, sigs) -> list[bool] | None:
        """submit + wait with a clean not-routed signal.

        Returns the per-lane bits, or None when the coalescer cannot
        serve the request (stopped, ticket failed, wait expired) — the
        caller then runs its unrouted path, so routing through here
        never changes a verdict. Groups larger than one window are
        chunked into ``max_lanes``-sized tickets submitted as one batch
        (:meth:`submit_many`) and reassembled in order. Waits honor the
        thread's :func:`request_deadline` budget when one is set; a
        deadline-capped expiry returns None WITHOUT tripping the
        breaker — the caller ran out of time, the executor is fine.
        """
        rem = deadline_remaining()
        if rem is not None and rem <= 0:
            return None
        if not self._claim_probe():
            # breaker cooldown in force (or another caller holds the
            # half-open probe): fall back without queueing anything
            return None
        n = len(pubkeys)
        if n <= self.max_lanes:
            groups = [(pubkeys, msgs, sigs)]
        else:
            groups = [
                (pubkeys[i : i + self.max_lanes],
                 msgs[i : i + self.max_lanes],
                 sigs[i : i + self.max_lanes])
                for i in range(0, n, self.max_lanes)
            ]
        try:
            tickets = self.submit_many(groups)
        except ServiceError:
            return None
        bits: list[bool] = []
        for ticket in tickets:
            wait_s = _RESULT_TIMEOUT_S
            capped = False
            rem = deadline_remaining()
            if rem is not None and rem < wait_s:
                wait_s, capped = max(rem, 0.0), True
            try:
                bits.extend(ticket.result(wait_s))
            except TimeoutError:
                # A ticket outliving the FULL result bound means the
                # executor is wedged (dead tunnel, stuck dispatch) or a
                # transient outlasted the bound. Trip the cooldown
                # breaker so subsequent callers fall back to host
                # instantly instead of each paying the full bound under
                # engine mutexes — one wedged device must degrade
                # throughput, not freeze consensus. Already-queued
                # callers wait at most one more bound; stop()'s safety
                # net still drains every ticket; a recovered device
                # re-routes after the cooldown. A deadline-capped wait
                # expiring is NOT executor evidence: no trip.
                if not capped:
                    self._trip()
                return None
            except Exception:
                return None
        self._rearm()
        return bits

    def routable(self) -> bool:
        """Accepting submits and not inside a breaker cooldown (an
        expired cooldown counts as routable). PURE query — active()
        and its is-a-coalescer-routed callers must never consume the
        single-flight probe; only try_verify claims it."""
        return self._accepting and (
            self._tripped_until == 0.0
            or time.monotonic() >= self._tripped_until
        )

    def _claim_probe(self) -> bool:
        """True when a routed verify may proceed: breaker armed, or
        this caller atomically won the post-cooldown half-open probe.
        Called ONLY from try_verify — the one place that can cash the
        probe in. Winning pushes the deadline one more cooldown
        forward, so concurrent callers keep falling back until the
        probe's verdict: a successful try_verify re-arms for everyone
        (:meth:`_rearm`), another timeout re-trips."""
        if self._tripped_until == 0.0:
            return True
        with self._mtx:
            if self._tripped_until == 0.0:
                return True
            if time.monotonic() < self._tripped_until:
                return False
            self._tripped_until = time.monotonic() + _TRIP_COOLDOWN_S
            return True

    def _rearm(self) -> None:
        if self._tripped_until == 0.0:
            return
        with self._mtx:
            self._tripped_until = 0.0
        libhealth.note_breaker_rearm()

    def _trip(self) -> None:
        """Unroute a wedged coalescer for one breaker cooldown.

        Groups already queued are handed to a host rescue thread: a
        wedged executor may never collect them, and they must not sit
        unresolved for a whole cooldown (or leak until shutdown).
        Overlap with a merely-slow executor is benign — resolution is
        done()-gated and verdicts are identical."""
        leftovers: deque | None = None
        with self._mtx:
            if self._draining or not self._accepting:
                return
            self._tripped_until = time.monotonic() + _TRIP_COOLDOWN_S
            if self._pending:
                leftovers, self._pending = self._pending, deque()
                self._pending_lanes = 0
            self._cv.notify_all()
        if leftovers:
            groups = tuple(leftovers)
            threading.Thread(
                target=lambda: [
                    self._resolve_group_host(g) for g in groups
                ],
                name="verify-coalescer-rescue",
                daemon=True,
            ).start()
        # health hook: the wedged-coalescer watchdog converts this
        # notice into a trip + black-box bundle (no lock held here)
        libhealth.note_breaker_trip()
        if self.logger is not None:
            self.logger.error(
                "verify coalescer unresponsive; unrouted for cooldown",
                timeout_s=_RESULT_TIMEOUT_S,
                cooldown_s=_TRIP_COOLDOWN_S,
            )

    # -- the executor ------------------------------------------------------

    def _run(self) -> None:
        try:
            while True:
                try:
                    groups, lanes, reason = self._collect(block=True)
                    if groups:
                        self._staging = groups
                        handle = self._launch(groups, lanes, reason)
                        if handle is not None:
                            # published BEFORE the drain handoff: if
                            # the finish faults or wedges, this
                            # window's tickets must be reachable by
                            # the rescues
                            self._inflights.append(handle)
                            self._hand_to_drain(handle)
                        self._staging = None
                    if reason == "quit":
                        return
                except Exception:
                    # The loop must survive anything: pending tickets
                    # are resolved by _launch/_finish's own fallbacks;
                    # anything still queued drains on the next
                    # iteration (or the on_stop safety net). A staged
                    # or in-flight window's tickets live NOWHERE else —
                    # rescue the staging slot and every tracked window
                    # (every drain-queue slot) before dropping the
                    # handles, or their submitters stall the full
                    # result timeout.
                    try:
                        import traceback

                        traceback.print_exc()
                    except Exception:
                        pass  # closed stderr must not kill the loop
                    staged, self._staging = self._staging, None
                    for group in staged or ():
                        self._resolve_group_host(group)
                    for fl in tuple(self._inflights):
                        self._rescue_inflight(fl)
                        self._drop_inflight(fl)
        finally:
            # The executor is gone for good — normal drain exit or a
            # death nothing above could catch. Let the readback drain
            # finish the windows already handed to it (submission-order
            # resolution with real device verdicts), then make sure no
            # ticket is left for callers to time out on: stop
            # accepting, then drain every slot a ticket can live in
            # (pending queue, staging window, drain-queue windows).
            # Everything here is done()-gated/idempotent, so overlap
            # with on_stop's safety net is benign.
            self._close_readback()
            rt = self._rb_thread
            if rt is not None and rt is not threading.current_thread():
                rt.join(timeout=self._JOIN_TIMEOUT_S)
            with self._mtx:
                self._accepting = False
                leftovers, self._pending = self._pending, deque()
                self._pending_lanes = 0
            staged, self._staging = self._staging, None
            for group in staged or ():
                self._resolve_group_host(group)
            for group in leftovers:
                self._resolve_group_host(group)
            for fl in tuple(self._inflights):
                self._rescue_inflight(fl)
                self._drop_inflight(fl)

    # -- the readback drain ------------------------------------------------

    def _hand_to_drain(self, fl: _Inflight) -> None:
        """Queue a dispatched window for the readback drain, blocking at
        the in-flight depth bound so execute of window N+1 overlaps the
        d2h of window N without letting the pipeline run unboundedly
        ahead. Falls back to finishing inline if the drain thread is
        gone (it must never strand a dispatched window)."""
        handed = False
        with self._rb_mtx:
            if self._rb_alive and not self._rb_closed:
                self._readback.append(fl)
                handed = True
                self._rb_cv.notify_all()
                while (
                    self._rb_alive
                    and not self._rb_closed
                    and not self._draining
                    and len(self._readback) + self._rb_busy
                    >= self.max_inflight
                ):
                    self._rb_cv.wait(0.2)
        if not handed:
            self._finish(fl)
            self._drop_inflight(fl)

    def _close_readback(self) -> None:
        with self._rb_mtx:
            self._rb_closed = True
            self._rb_cv.notify_all()

    def _drain_run(self) -> None:
        """Materialize dispatched windows in submission order.

        FIFO over the handoff queue: window N's tickets resolve before
        window N+1's even when N+1's device result lands first — routed
        callers observe the same ordering the synchronous executor
        gave them. A finish fault falls back to the host rescue for
        that window only; the loop survives anything.
        """
        try:
            while True:
                with self._rb_mtx:
                    while not self._readback and not self._rb_closed:
                        self._rb_cv.wait(0.2)
                    if not self._readback:
                        return  # closed and empty
                    fl = self._readback.popleft()
                    self._rb_busy += 1
                try:
                    self._finish(fl)
                except Exception:
                    try:
                        import traceback

                        traceback.print_exc()
                    except Exception:
                        pass
                    self._rescue_inflight(fl)
                finally:
                    self._drop_inflight(fl)
                    with self._rb_mtx:
                        self._rb_busy -= 1
                        self._rb_cv.notify_all()
        finally:
            # drain death (normal close or a fault nothing above
            # caught): no handed-off window may be left unresolved,
            # and a depth-blocked executor must wake and notice
            # _rb_alive is down (it then finishes windows inline)
            with self._rb_mtx:
                self._rb_alive = False
                leftovers = list(self._readback)
                self._readback.clear()
                self._rb_cv.notify_all()
            for fl in leftovers:
                self._rescue_inflight(fl)
                self._drop_inflight(fl)

    def _drop_inflight(self, fl: _Inflight) -> None:
        try:
            self._inflights.remove(fl)
        except ValueError:  # already rescued+dropped by on_stop
            pass

    def _collect(self, block: bool):
        """Pop one flush window from the pending queue.

        Returns ``(groups, lanes, reason)``; groups is None for an
        empty poll. reason: "size" | "deadline" | "drain" when a window
        was popped, "idle" (non-blocking poll found nothing — the
        caller materializes its in-flight window), "quit" (draining and
        empty). The deadline anchors at the OLDEST pending ticket's
        submit time, so a request never waits more than one window.
        """
        with self._mtx:
            if block:
                while not self._pending and not self._draining:
                    self._cv.wait(0.2)
            if not self._pending:
                return None, 0, ("quit" if self._draining else "idle")
            first_t = self._pending[0][0].t_submit
            while self._pending_lanes < self.max_lanes and not self._draining:
                rem = self.window_s - (time.perf_counter() - first_t)
                if rem <= 0:
                    break
                self._cv.wait(rem)
            if self._draining:
                reason = "drain"
            elif self._pending_lanes >= self.max_lanes:
                reason = "size"
            else:
                reason = "deadline"
            groups: list[tuple] = []
            lanes = 0
            while self._pending and (
                not groups or lanes + self._pending[0][0].n <= self.max_lanes
            ):
                g = self._pending.popleft()
                groups.append(g)
                lanes += g[0].n
            self._pending_lanes -= lanes
            return groups, lanes, reason

    def _device_ok(self) -> bool:
        if self._device is not None:
            return self._device
        # live peek only: the flush path runs every window and must
        # never pay (or hang in) jax backend init — node boot's
        # accelerator_backend() probe brings the backend up
        from ..libs.accel import accelerator_backend_live

        return accelerator_backend_live()

    def _stage(self, groups):
        """Flatten groups into wire lists; a lane that cannot coerce to
        bytes fails ONLY its own submit's ticket."""
        pubkeys: list[bytes] = []
        msgs: list[bytes] = []
        sigs: list[bytes] = []
        staged: list[tuple] = []  # (ticket, lo, n)
        for ticket, pks, ms, ss in groups:
            try:
                lanes = [
                    (bytes(pk), bytes(m), bytes(s))
                    for pk, m, s in zip(pks, ms, ss)
                ]
                if len(lanes) != ticket.n:
                    raise ValueError(
                        f"lane count mismatch: {len(lanes)} != {ticket.n}"
                    )
            except Exception as e:
                ticket.fail(e)
                continue
            lo = len(pubkeys)
            for pk, m, s in lanes:
                pubkeys.append(pk)
                msgs.append(m)
                sigs.append(s)
            staged.append((ticket, lo, ticket.n))
        return pubkeys, msgs, sigs, staged

    def _launch(self, groups, lanes, reason) -> _Inflight | None:
        """Stage + dispatch one window. Device windows return an
        in-flight handle (materialized by the NEXT loop turn — the
        double buffer); host windows resolve synchronously and return
        None."""
        t_pop = time.perf_counter()
        libdevledger.exec_begin(libdevledger.PLANE_VERIFY)
        try:
            return self._launch_inner(groups, lanes, reason, t_pop)
        finally:
            # the executor-busy marker brackets staging, pack, dispatch
            # AND the inline host resolve — the occupancy view's
            # overlap estimator reads it from the readback drain
            libdevledger.exec_end(libdevledger.PLANE_VERIFY)

    def _launch_inner(self, groups, lanes, reason, t_pop) -> _Inflight | None:
        pubkeys, msgs, sigs, staged = self._stage(groups)
        if not staged:
            # every group failed staging: nothing flushed, nothing to
            # count — a window of all-malformed lanes must not inflate
            # the flush/lane metrics
            return None
        n = len(pubkeys)
        m = libmetrics.node_metrics()
        m.coalesce_window_lanes.observe(n)
        m.coalesce_flushes.labels(reason).inc()
        self.windows += 1
        use_device = self._device_ok()
        if use_device:
            # crossover only matters once the device gate passed: a
            # device=False pin must keep the flush path off jax entirely
            cut = self.min_device_lanes
            if cut is None:
                from . import batch as crypto_batch

                cut = crypto_batch.host_batch_threshold()
            use_device = n >= cut
        if use_device:
            t0 = time.perf_counter()
            try:
                from ..ops import verify as ov

                buf, host_ok = ov.pack_bytes(pubkeys, msgs, sigs)
                hit = (
                    ov._PUBKEY_CACHE.lookup(pubkeys)
                    if ov._cache_enabled()
                    else None
                )
                arena = "hit" if hit is not None else "bypass"
                t1 = time.perf_counter()
                libmetrics.observe_verify_phase(
                    "pack", "ed25519-coalesce", t1 - t0, n, arena=arena
                )
                if hit is not None:
                    idxs, arena_buf, arena_ok = hit
                    finish = ov.verify_rsk_async(
                        buf[32:], idxs, arena_buf, arena_ok, n
                    )
                else:
                    finish = ov.verify_bytes_async(buf, n)
                libmetrics.observe_verify_phase(
                    "dispatch",
                    "ed25519-coalesce",
                    time.perf_counter() - t1,
                    n,
                    arena=arena,
                )
                self.device_windows += 1
                libdevledger.note_window(
                    libdevledger.PLANE_VERIFY, n, True
                )
                return _Inflight(
                    finish, host_ok, staged, n, reason,
                    time.perf_counter() - t0, (pubkeys, msgs, sigs),
                    t_launch=t_pop,
                )
            except Exception:
                # device staging/dispatch fault: clean host fallback
                # for the whole window
                import traceback

                traceback.print_exc()
        libdevledger.note_window(libdevledger.PLANE_VERIFY, n, False)
        self._resolve_host(pubkeys, msgs, sigs, staged, reason, t_pop)
        return None

    def _finish(self, fl: _Inflight) -> None:
        """Materialize a dispatched window and resolve its tickets."""
        t0 = time.perf_counter()
        t0_ns = time.monotonic_ns()
        busy0 = libdevledger.exec_busy_ns(libdevledger.PLANE_VERIFY)
        try:
            device_ok = fl.finish()
        except Exception:
            # device-side fault at materialization: clean host fallback
            # for the window (tickets resolve with host verdicts, not
            # errors — routing must never change an answer)
            import traceback

            traceback.print_exc()
            pubkeys, msgs, sigs = fl.wire
            self._resolve_host(
                pubkeys, msgs, sigs, fl.groups, fl.reason, fl.t_launch
            )
            return
        now = time.perf_counter()
        libdevledger.note_readback(
            libdevledger.PLANE_VERIFY, t0_ns, busy0
        )
        libmetrics.observe_verify_phase(
            "readback", "ed25519-coalesce", now - t0, fl.lanes
        )
        from . import batch as crypto_batch

        crypto_batch.note_device_window(fl.lanes, fl.prep_s + (now - t0))
        valid = device_ok & fl.host_ok
        self._resolve_bits(
            fl.groups, valid, fl.reason, "device",
            t_launch=fl.t_launch, exec_s=fl.prep_s + (now - t0),
        )

    def _resolve_host(
        self, pubkeys, msgs, sigs, staged, reason, t_launch=None
    ) -> None:
        """Host-window verdicts: one native RLC batch for the whole
        window (coalescing still wins on host), sequential per-lane
        verify if the batch engine throws."""
        t0 = time.perf_counter()
        try:
            from . import host_batch

            bitmap = host_batch.verify_many(pubkeys, msgs, sigs)
        except Exception:
            from . import fast25519

            bitmap = []
            for pk, m, s in zip(pubkeys, msgs, sigs):
                try:
                    bitmap.append(bool(fast25519.verify_one(pk, m, s)))
                except Exception:
                    bitmap.append(False)
        dt = time.perf_counter() - t0
        n = len(pubkeys)
        libmetrics.observe_verify_phase(
            "fallback", "ed25519-coalesce", dt, n
        )
        from . import batch as crypto_batch

        crypto_batch.note_host_window(n, dt)
        self._resolve_bits(
            staged, bitmap, reason, "host", t_launch=t_launch, exec_s=dt
        )

    def _resolve_bits(
        self, staged, bits, reason, backend, t_launch=None, exec_s=0.0
    ) -> None:
        m = libmetrics.node_metrics()
        now = time.perf_counter()
        total = 0
        for _, _, n in staged:
            total += n
        exec_ns = int(exec_s * 1e9)
        device = backend == "device"
        plane = libdevledger.PLANE_VERIFY
        # the WHOLE accounting block rides the ledger kill switch:
        # COMETBFT_TPU_LEDGER=0 promises a single flag check, so the
        # per-ticket histogram observes (two mutex hops each) and the
        # EV_BUDGET ring rows go dark with the columns
        ledger_on = libdevledger.enabled()
        if ledger_on and exec_ns > 0:
            libdevledger.note_window_time(plane, exec_ns)
        # queue-wait anchor: the window pop — submit->pop is queueing,
        # pop->resolve is execute (charged pro-rata by lane count so
        # per-caller shares reconcile to the window total within
        # integer floor error, < one ns per ticket)
        anchor = t_launch if t_launch is not None else now
        bw = bx = 0  # consensus-caller wait/exec sums (the budget row)
        for ticket, lo, n in staged:
            ticket.resolve([bool(b) for b in bits[lo : lo + n]])
            m.coalesce_wait_seconds.observe(now - ticket.t_submit)
            if not ledger_on:
                continue
            wait_ns = int((anchor - ticket.t_submit) * 1e9)
            if wait_ns < 0:
                wait_ns = 0
            share = exec_ns * n // total if total else 0
            cid = ticket.caller
            libdevledger.note_resolve(
                plane, cid, n, wait_ns,
                share if device else 0, 0 if device else share,
            )
            m.device_queue_wait.labels(
                "verify", libdevledger.caller_name(cid)
            ).observe(wait_ns / 1e9)
            if cid in libdevledger.BUDGET_VERIFY_CALLERS:
                bw += wait_ns
                bx += share
        if bw or bx:
            # the per-height budget overlay: consensus-caller verify
            # queue+execute time, window-assigned to a height by the
            # budget decomposition (libs/health.budget)
            libhealth.record(
                libhealth.EV_BUDGET, 0, plane, bw, bx
            )
        if libtrace.enabled():
            libtrace.event(
                "coalesce.flush",
                reason=reason,
                backend=backend,
                lanes=sum(n for _, _, n in staged),
                tickets=len(staged),
            )

    def _rescue_inflight(self, fl: _Inflight) -> None:
        """Resolve an in-flight window's still-undone tickets on host.

        Called when the window's materialization can no longer be
        trusted to happen (executor fault after dispatch, or shutdown
        with the executor wedged). Verdicts come from the retained wire
        copy, so rescued callers get the same answers a clean
        materialization would have produced; a ticket the executor
        resolved concurrently is skipped (done() gates), and one whose
        host re-verify also fails gets the exception instead of a hang.
        """
        pubkeys, msgs, sigs = fl.wire
        for ticket, lo, n in fl.groups:
            if ticket.done():
                continue
            try:
                from . import host_batch

                ticket.resolve(host_batch.verify_many(
                    pubkeys[lo : lo + n],
                    msgs[lo : lo + n],
                    sigs[lo : lo + n],
                ))
            except Exception as e:
                ticket.fail(e)

    def _resolve_group_host(self, group) -> None:
        """Per-group host resolution for the trip-time rescue, the
        shutdown safety net, and post-fault recovery; done()-gated, so
        overlap with a still-alive executor is benign."""
        ticket, pks, ms, ss = group
        if ticket.done():
            return
        try:
            from . import host_batch

            ticket.resolve(host_batch.verify_many(
                [bytes(p) for p in pks],
                [bytes(x) for x in ms],
                [bytes(s) for s in ss],
            ))
        except Exception as e:
            ticket.fail(e)


# -- process-wide routing switch ------------------------------------------
#
# A stack, like libs/metrics' node-metrics stack: in-process multi-node
# test nets push one coalescer per node; the most recent running one
# receives routed verifies, pops are by identity so out-of-order node
# shutdown cannot evict a live node's coalescer.

_ACTIVE: list[VerifyCoalescer] = []


def push_active(co: VerifyCoalescer) -> None:
    """Install ``co`` as the process-wide routed coalescer (node boot)."""
    _ACTIVE.append(co)


def pop_active(co: VerifyCoalescer) -> None:
    for i in range(len(_ACTIVE) - 1, -1, -1):
        if _ACTIVE[i] is co:
            del _ACTIVE[i]
            return


def active() -> VerifyCoalescer | None:
    """The routed coalescer, or None when verification is unrouted."""
    # snapshot: a concurrent pop_active (another node shutting down)
    # must not shrink the list under this walk
    for co in reversed(tuple(_ACTIVE)):
        if co.routable():
            return co
    return None


def breaker_open() -> bool:
    """True while ANY pushed coalescer sits inside a breaker cooldown —
    the health engine's `health_breaker_open` SLI. Pure query (same
    contract as routable(): never consumes the half-open probe)."""
    now = time.monotonic()
    for co in tuple(_ACTIVE):
        t = co._tripped_until
        if t and now < t:
            return True
    return False


def configured_mode() -> str:
    """COMETBFT_TPU_COALESCE: "auto" (default; the node starts a
    coalescer only on accelerator backends), "1"/"on" force, "0" off."""
    v = os.environ.get("COMETBFT_TPU_COALESCE", "auto").lower()
    if v in ("1", "on", "true", "yes"):
        return "on"
    if v in ("0", "off", "false", "no"):
        return "off"
    return "auto"


def node_wants_coalescer() -> bool:
    """Whether a booting node should start a VerifyCoalescer."""
    mode = configured_mode()
    if mode == "on":
        return True
    if mode == "off":
        return False
    from ..libs.accel import accelerator_backend

    return accelerator_backend()


def eligible(pub_key) -> bool:
    """Keys the coalescer can carry (ed25519 — the device wire format)."""
    return (
        getattr(pub_key, "type", None) == ED25519_KEY_TYPE
        and len(getattr(pub_key, "data", b"") or b"") == 32
    )


def verify_signature(pub_key, msg: bytes, signature: bytes) -> bool:
    """Single-signature verify, coalesced when a coalescer is routed.

    THE drop-in for ``pub_key.verify_signature`` on the steady-state
    paths (vote admission, proposal checks, evidence/light): identical
    verdicts, and any routing failure falls back to the unrouted host
    verify — never to a different answer.
    """
    co = active()
    if co is not None and eligible(pub_key):
        bits = co.try_verify([pub_key.data], [msg], [signature])
        if bits is not None and len(bits) == 1:
            return bool(bits[0])
    return pub_key.verify_signature(msg, signature)


def verify_bytes(pubkeys, msgs, sigs) -> list[bool] | None:
    """Batch helper for crypto/batch.py's sub-crossover cutover: raw
    32-byte ed25519 keys -> per-lane bits, or None when unrouted."""
    co = active()
    if co is None:
        return None
    return co.try_verify(pubkeys, msgs, sigs)
