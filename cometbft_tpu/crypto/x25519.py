"""Pure-Python X25519 (RFC 7748) — fallback key exchange for the p2p
secret-connection handshake when the ``cryptography`` wheel is absent.

One ladder evaluation is ~1 ms of bigint work; the handshake runs it
twice per connection, so the pure path costs nothing observable next to
socket latency. Production images carry the wheel and never route here
(p2p/conn/secret_connection.py prefers OpenSSL).
"""

from __future__ import annotations

_P = 2**255 - 19
_A24 = 121665


def _decode_u(u: bytes) -> int:
    if len(u) != 32:
        raise ValueError("x25519 u-coordinate must be 32 bytes")
    x = bytearray(u)
    x[31] &= 127  # RFC 7748 §5: mask the unused high bit
    return int.from_bytes(x, "little") % _P


def _decode_scalar(k: bytes) -> int:
    if len(k) != 32:
        raise ValueError("x25519 scalar must be 32 bytes")
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(b, "little")


def x25519(scalar: bytes, u: bytes) -> bytes:
    """Montgomery-ladder scalar multiplication (RFC 7748 §5)."""
    k = _decode_scalar(scalar)
    x1 = _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 % _P
        z3 = z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, _P - 2, _P) % _P).to_bytes(32, "little")


def x25519_base(scalar: bytes) -> bytes:
    """Public key for a 32-byte private scalar (u = 9 base point)."""
    return x25519(scalar, (9).to_bytes(32, "little"))
