"""Legacy AEAD helpers + ASCII armor (reference: crypto/xchacha20poly1305,
crypto/xsalsa20symmetric, crypto/armor — used for encrypted key files and
armored key export, NOT on any consensus path).

XChaCha20-Poly1305: HChaCha20 subkey derivation (pure-Python ChaCha
core — the 24-byte-nonce variant isn't in the `cryptography` wheel) over
the wheel's IETF ChaCha20-Poly1305.

XSalsa20: pure-Python Salsa20 core with the classic HSalsa20 key setup
(NaCl secretbox's stream layer); `xsalsa20symmetric` matches the
reference's `EncryptSymmetric`/`DecryptSymmetric` shape — secretbox-like
framing with the MAC provided by Poly1305 in NaCl, here by sha256 MAC
over ciphertext like the reference's legacy scheme is NOT reproduced;
instead we provide the modern authenticated construction the reference
migrated toward (xchacha) and keep xsalsa20 as the raw stream cipher the
legacy decoder needs.
"""

from __future__ import annotations

import struct

# ------------------------------------------------------------- chacha core


def _rotl(v: int, n: int) -> int:
    v &= 0xFFFFFFFF
    return ((v << n) | (v >> (32 - n))) & 0xFFFFFFFF


def _chacha_quarter(s, a, b, c, d):
    s[a] = (s[a] + s[b]) & 0xFFFFFFFF
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = (s[c] + s[d]) & 0xFFFFFFFF
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = (s[a] + s[b]) & 0xFFFFFFFF
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = (s[c] + s[d]) & 0xFFFFFFFF
    s[b] = _rotl(s[b] ^ s[c], 7)


_CHACHA_CONST = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _chacha_rounds(state: list[int]) -> list[int]:
    s = list(state)
    for _ in range(10):  # 20 rounds = 10 double rounds
        _chacha_quarter(s, 0, 4, 8, 12)
        _chacha_quarter(s, 1, 5, 9, 13)
        _chacha_quarter(s, 2, 6, 10, 14)
        _chacha_quarter(s, 3, 7, 11, 15)
        _chacha_quarter(s, 0, 5, 10, 15)
        _chacha_quarter(s, 1, 6, 11, 12)
        _chacha_quarter(s, 2, 7, 8, 13)
        _chacha_quarter(s, 3, 4, 9, 14)
    return s


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20 subkey derivation (draft-irtf-cfrg-xchacha §2.2)."""
    if len(key) != 32 or len(nonce16) != 16:
        raise ValueError("hchacha20 needs 32-byte key, 16-byte nonce")
    state = list(_CHACHA_CONST)
    state += list(struct.unpack("<8L", key))
    state += list(struct.unpack("<4L", nonce16))
    s = _chacha_rounds(state)
    out = s[0:4] + s[12:16]
    return struct.pack("<8L", *out)


# ------------------------------------------- chacha20-poly1305 (RFC 8439)
# Pure-Python IETF AEAD over the chacha core above: the fallback the
# secret connection and the xchacha helpers use when the `cryptography`
# wheel is absent. The wheel's OpenSSL path is preferred whenever it
# imports (new_chacha20poly1305) — the pure path is ~1 ms per 1 KiB
# frame, fine for tests and slim containers, not for production relay.


def _chacha20_block(key: bytes, counter: int, nonce12: bytes) -> bytes:
    state = list(_CHACHA_CONST)
    state += list(struct.unpack("<8L", key))
    state.append(counter & 0xFFFFFFFF)
    state += list(struct.unpack("<3L", nonce12))
    s = _chacha_rounds(state)
    return struct.pack(
        "<16L", *((a + b) & 0xFFFFFFFF for a, b in zip(s, state))
    )


def chacha20_stream_xor(
    key: bytes, counter: int, nonce12: bytes, data: bytes
) -> bytes:
    if len(key) != 32 or len(nonce12) != 12:
        raise ValueError("chacha20 needs 32-byte key, 12-byte nonce")
    out = bytearray()
    for i in range(0, len(data), 64):
        block = _chacha20_block(key, counter + i // 64, nonce12)
        chunk = data[i : i + 64]
        out += bytes(a ^ b for a, b in zip(chunk, block))
    return bytes(out)


def poly1305_mac(key32: bytes, msg: bytes) -> bytes:
    r = (
        int.from_bytes(key32[:16], "little")
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    )
    s = int.from_bytes(key32[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block, "little") + (1 << (8 * len(block)))
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b"\x00" * (-len(b) % 16)


def _mac_data(aad: bytes, ct: bytes) -> bytes:
    return (
        aad
        + _pad16(aad)
        + ct
        + _pad16(ct)
        + struct.pack("<QQ", len(aad), len(ct))
    )


class ChaCha20Poly1305Fallback:
    """Drop-in for the wheel's ChaCha20Poly1305 (encrypt/decrypt API)."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("chacha20poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aad = aad or b""
        otk = _chacha20_block(self._key, 0, nonce)[:32]
        ct = chacha20_stream_xor(self._key, 1, nonce, data)
        return ct + poly1305_mac(otk, _mac_data(aad, ct))

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        import hmac as _hmac

        aad = aad or b""
        if len(data) < 16:
            raise ValueError("ciphertext shorter than the poly1305 tag")
        ct, tag = data[:-16], data[-16:]
        otk = _chacha20_block(self._key, 0, nonce)[:32]
        if not _hmac.compare_digest(
            tag, poly1305_mac(otk, _mac_data(aad, ct))
        ):
            raise ValueError("poly1305 tag mismatch")
        return chacha20_stream_xor(self._key, 1, nonce, ct)


def new_chacha20poly1305(key: bytes):
    """IETF ChaCha20-Poly1305: OpenSSL via the wheel when importable,
    the pure-Python construction above otherwise."""
    try:
        from cryptography.hazmat.primitives.ciphers.aead import (
            ChaCha20Poly1305,
        )

        return ChaCha20Poly1305(key)
    except ImportError:
        return ChaCha20Poly1305Fallback(key)


def xchacha20poly1305_encrypt(
    key: bytes, nonce24: bytes, plaintext: bytes, aad: bytes = b""
) -> bytes:
    """XChaCha20-Poly1305 seal (crypto/xchacha20poly1305 semantics)."""
    if len(nonce24) != 24:
        raise ValueError("xchacha nonce must be 24 bytes")
    subkey = hchacha20(key, nonce24[:16])
    iv = b"\x00" * 4 + nonce24[16:]
    return new_chacha20poly1305(subkey).encrypt(iv, plaintext, aad)


def xchacha20poly1305_decrypt(
    key: bytes, nonce24: bytes, ciphertext: bytes, aad: bytes = b""
) -> bytes:
    if len(nonce24) != 24:
        raise ValueError("xchacha nonce must be 24 bytes")
    subkey = hchacha20(key, nonce24[:16])
    iv = b"\x00" * 4 + nonce24[16:]
    return new_chacha20poly1305(subkey).decrypt(iv, ciphertext, aad)


# ------------------------------------------------------------- salsa core


def _salsa_quarter(s, a, b, c, d):
    s[b] ^= _rotl((s[a] + s[d]) & 0xFFFFFFFF, 7)
    s[c] ^= _rotl((s[b] + s[a]) & 0xFFFFFFFF, 9)
    s[d] ^= _rotl((s[c] + s[b]) & 0xFFFFFFFF, 13)
    s[a] ^= _rotl((s[d] + s[c]) & 0xFFFFFFFF, 18)


_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _salsa20_block(key32: bytes, nonce8: bytes, counter: int) -> bytes:
    k = struct.unpack("<8L", key32)
    n = struct.unpack("<2L", nonce8)
    state = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        counter & 0xFFFFFFFF, (counter >> 32) & 0xFFFFFFFF,
        _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    s = list(state)
    for _ in range(10):
        # column rounds
        _salsa_quarter(s, 0, 4, 8, 12)
        _salsa_quarter(s, 5, 9, 13, 1)
        _salsa_quarter(s, 10, 14, 2, 6)
        _salsa_quarter(s, 15, 3, 7, 11)
        # row rounds
        _salsa_quarter(s, 0, 1, 2, 3)
        _salsa_quarter(s, 5, 6, 7, 4)
        _salsa_quarter(s, 10, 11, 8, 9)
        _salsa_quarter(s, 15, 12, 13, 14)
    out = [(s[i] + state[i]) & 0xFFFFFFFF for i in range(16)]
    return struct.pack("<16L", *out)


def hsalsa20(key: bytes, nonce16: bytes) -> bytes:
    """HSalsa20 (NaCl's XSalsa20 key setup)."""
    k = struct.unpack("<8L", key)
    n = struct.unpack("<4L", nonce16)
    s = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        n[2], n[3], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    z = list(s)
    for _ in range(10):
        _salsa_quarter(z, 0, 4, 8, 12)
        _salsa_quarter(z, 5, 9, 13, 1)
        _salsa_quarter(z, 10, 14, 2, 6)
        _salsa_quarter(z, 15, 3, 7, 11)
        _salsa_quarter(z, 0, 1, 2, 3)
        _salsa_quarter(z, 5, 6, 7, 4)
        _salsa_quarter(z, 10, 11, 8, 9)
        _salsa_quarter(z, 15, 12, 13, 14)
    out = [z[0], z[5], z[10], z[15], z[6], z[7], z[8], z[9]]
    return struct.pack("<8L", *out)


def xsalsa20_stream_xor(key: bytes, nonce24: bytes, data: bytes) -> bytes:
    """XSalsa20 stream XOR (crypto/xsalsa20symmetric's cipher layer)."""
    if len(key) != 32 or len(nonce24) != 24:
        raise ValueError("xsalsa20 needs 32-byte key, 24-byte nonce")
    subkey = hsalsa20(key, nonce24[:16])
    out = bytearray()
    counter = 0
    for i in range(0, len(data), 64):
        block = _salsa20_block(subkey, nonce24[16:], counter)
        chunk = data[i : i + 64]
        out += bytes(a ^ b for a, b in zip(chunk, block))
        counter += 1
    return bytes(out)


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """Authenticated symmetric encryption for key files
    (crypto/xsalsa20symmetric EncryptSymmetric's role, modern AEAD):
    random 24-byte nonce || XChaCha20-Poly1305 box."""
    import os

    if len(secret) != 32:
        raise ValueError("secret must be 32 bytes (use a KDF)")
    nonce = os.urandom(24)
    return nonce + xchacha20poly1305_encrypt(secret, nonce, plaintext)


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    if len(secret) != 32:
        raise ValueError("secret must be 32 bytes (use a KDF)")
    if len(ciphertext) < 24 + 16:
        raise ValueError("ciphertext too short")
    return xchacha20poly1305_decrypt(
        secret, ciphertext[:24], ciphertext[24:]
    )


# ---------------------------------------------------------------- armor


_ARMOR_HEAD = "-----BEGIN {}-----"
_ARMOR_TAIL = "-----END {}-----"


def _crc24(data: bytes) -> int:
    """OpenPGP CRC-24 (RFC 4880 §6.1)."""
    crc = 0xB704CE
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= 0x1864CFB
    return crc & 0xFFFFFF


def armor_encode(
    data: bytes, block_type: str, headers: dict[str, str] | None = None
) -> str:
    """ASCII armor (crypto/armor.EncodeArmor; OpenPGP-style framing)."""
    import base64
    import textwrap

    lines = [_ARMOR_HEAD.format(block_type)]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    lines.append("")
    body = base64.b64encode(data).decode()
    lines.extend(textwrap.wrap(body, 64))
    crc = base64.b64encode(struct.pack(">I", _crc24(data))[1:]).decode()
    lines.append("=" + crc)
    lines.append(_ARMOR_TAIL.format(block_type))
    return "\n".join(lines) + "\n"


def armor_decode(text: str) -> tuple[str, dict[str, str], bytes]:
    """-> (block_type, headers, data); raises ValueError on bad framing/CRC."""
    import base64

    lines = [ln.strip() for ln in text.strip().splitlines()]
    if not lines or not lines[0].startswith("-----BEGIN "):
        raise ValueError("missing armor header")
    block_type = lines[0][len("-----BEGIN ") : -5]
    if lines[-1] != _ARMOR_TAIL.format(block_type):
        raise ValueError("missing/mismatched armor tail")
    headers: dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i]:
        if ":" not in lines[i]:
            break
        k, _, v = lines[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) and not lines[i]:
        i += 1
    body_lines = []
    crc_line = None
    for ln in lines[i:-1]:
        if ln.startswith("="):
            crc_line = ln[1:]
        elif ln:
            body_lines.append(ln)
    data = base64.b64decode("".join(body_lines))
    if crc_line is not None:
        want = base64.b64decode(crc_line)
        got = struct.pack(">I", _crc24(data))[1:]
        if want != got:
            raise ValueError("armor CRC mismatch")
    return block_type, headers, data
