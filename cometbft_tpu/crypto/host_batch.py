"""Host ed25519 batch verification over the native MSM engine.

The reference's host hot path is curve25519-voi BATCH verification
(crypto/ed25519/ed25519.go:196-228): draw random 128-bit coefficients
z_i and check the single random-linear-combination equation

    [8]( [sum z_i S_i]B - sum [z_i k_i]A_i - sum [z_i]R_i ) == O

with one multiscalar multiplication. This module is that algorithm for
this framework: CPython does the byte-level work (SHA-512 challenges,
canonicality checks, bigint coefficient reduction mod L — microseconds
per batch) and native/edbatch.cpp does the Pippenger MSM and ZIP-215
decompression via ctypes.

Roles:
  * the MEASURED baseline for bench.py's vs_baseline (replacing the
    former "OpenSSL single-verify x 2.0" guess), and
  * the production host path for sub-device-threshold batches
    (crypto/batch.Ed25519BatchVerifier): a 150-validator commit verifies
    in ~1 MSM instead of 150 sequential OpenSSL calls.

Soundness: an invalid signature passes the RLC check with probability
~2^-128 over the coefficient draw (z_i from ``secrets``). On batch
failure, lanes are attributed by binary splitting (reusing the drawn
coefficients — they were never revealed), bottoming out in single
cofactored verifies through the same MSM core, so every per-lane verdict
has exact ZIP-215 semantics (crypto/ed25519/ed25519.go:26-29).
"""

from __future__ import annotations

import ctypes
import os
from ..libs import sync as libsync
import secrets

import numpy as np

from ..libs.native_build import NativeBuildError, build_and_load
from . import ed25519_ref as ref

L = ref.L
_B_ENC = bytes([0x58]) + bytes([0x66]) * 31  # compressed base point

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "edbatch.cpp"))
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "_edbatch.so"))

_build_lock = libsync.Mutex("crypto.host_batch._build_lock")
_lib = None
_lib_failed = False


def _load():
    """Compile + load the native engine once; None if the toolchain is
    unavailable (callers fall back to sequential OpenSSL verification)."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            lib = build_and_load(_SRC, _SO)
            try:
                _bind(lib)
            except AttributeError:
                # a pre-existing .so from OLDER source (deploy that
                # preserved mtimes) lacks newer symbols: force a clean
                # rebuild from the current source once
                try:
                    os.remove(_SO)
                except OSError:
                    pass
                lib = build_and_load(_SRC, _SO)
                _bind(lib)
            _install_sha512_constants(lib)
            _lib = lib
        except (NativeBuildError, AttributeError):
            _lib_failed = True
    return _lib


def _bind(lib) -> None:
    """ctypes signatures for every engine symbol; raises AttributeError
    when the loaded .so predates one (callers force a rebuild)."""
    lib.edb_msm_is_identity_x8.restype = ctypes.c_long
    lib.edb_msm_is_identity_x8.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t
    ]
    lib.edb_decompress_ok.restype = None
    lib.edb_decompress_ok.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
    ]
    lib.edb_scalar_base_mult_xy.restype = None
    lib.edb_scalar_base_mult_xy.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p
    ]
    lib.edb_keccak_f1600.restype = None
    lib.edb_keccak_f1600.argtypes = [ctypes.c_void_p]
    lib.edb_sha512_set_constants.restype = None
    lib.edb_sha512_set_constants.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p
    ]
    lib.edb_pack_challenges.restype = ctypes.c_long
    lib.edb_pack_challenges.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.edb_verify_batch.restype = ctypes.c_long
    lib.edb_verify_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.edb_sr_challenge_batch.restype = ctypes.c_long
    lib.edb_sr_challenge_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.edb_ristretto_to_edwards.restype = None
    lib.edb_ristretto_to_edwards.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.c_char_p,
    ]


def _install_sha512_constants(lib) -> None:
    """Compute the FIPS 180-4 SHA-512 constants from their definition
    (first 64 fractional bits of the cube/square roots of the first
    primes, exact integer arithmetic — no hardcoded magic tables) and
    install them in the native engine. hashlib parity is pinned by
    tests/test_host_batch tests."""
    primes = []
    cand = 2
    while len(primes) < 80:
        if all(cand % p for p in primes):
            primes.append(cand)
        cand += 1

    def iroot(x: int, k: int) -> int:
        """Exact integer k-th root via Newton on Python ints."""
        if x == 0:
            return 0
        r = 1 << ((x.bit_length() + k - 1) // k)
        while True:
            nr = ((k - 1) * r + x // r ** (k - 1)) // k
            if nr >= r:
                break
            r = nr
        return r

    def frac_bits(p: int, k: int) -> int:
        # floor(frac(p^(1/k)) * 2^64)
        r = iroot(p << (64 * k), k)
        return r - ((iroot(p, k)) << 64)

    k80 = (ctypes.c_uint64 * 80)(*[frac_bits(p, 3) for p in primes])
    h8 = (ctypes.c_uint64 * 8)(*[frac_bits(p, 2) for p in primes[:8]])
    lib.edb_sha512_set_constants(k80, h8)


def available() -> bool:
    return _load() is not None


def pack_challenges(recs: bytes, msgs_blob: bytes, offs, n: int):
    """Native per-lane challenge packing for ops/verify.pack_bytes.

    ``recs``: n x 96 bytes (A|R|S); ``msgs_blob`` + ``offs`` (n+1 u64):
    concatenated sign bytes. Returns (kneg_rows 32n bytes, s_ok (n,)
    bool) or None when the native engine is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    out_kneg = ctypes.create_string_buffer(32 * n)
    out_ok = ctypes.create_string_buffer(n)
    offs_arr = (ctypes.c_uint64 * (n + 1))(*offs)
    rc = lib.edb_pack_challenges(
        recs, msgs_blob, offs_arr, n, out_kneg, out_ok
    )
    if rc != 0:
        return None
    return out_kneg.raw, np.frombuffer(out_ok.raw, np.uint8).astype(bool)


def sr_challenge_batch(
    ctx_state: bytes, recs: bytes, msgs_blob: bytes, offs, n: int
):
    """Batched sr25519 (schnorrkel) verification challenges.

    ``ctx_state``: 203-byte serialized STROBE state of the merlin
    transcript prefix Transcript("SigningContext") + append("", ctx)
    (crypto/sr25519._context_prefix — pure function of the signing
    context, cached). ``recs``: n x 64 bytes (pk | R); ``msgs_blob`` +
    ``offs`` (n+1 u64): concatenated sign bytes. Returns n x 32 bytes of
    little-endian challenges k_i mod L, or None when the native engine
    is unavailable. Reference surface: crypto/sr25519/batch.go:14-46.
    """
    lib = _load()
    if lib is None:
        return None
    out_k = ctypes.create_string_buffer(32 * n)
    offs_arr = (ctypes.c_uint64 * (n + 1))(*offs)
    rc = lib.edb_sr_challenge_batch(
        ctx_state, recs, msgs_blob, offs_arr, n, out_k
    )
    if rc != 0:
        return None
    return out_k.raw


def ristretto_to_edwards_batch(encs: bytes, m: int):
    """Decode m ristretto255 encodings (RFC 9496) to compressed edwards.

    Returns (enc_rows: 32*m bytes, ok: (m,) bool) or None when the
    native engine is unavailable. Both sr25519 batch consumers — the
    host MSM and the TPU kernel — take compressed edwards points, so
    the decode and re-compression never touch Python bigints.
    """
    lib = _load()
    if lib is None:
        return None
    out_enc = ctypes.create_string_buffer(32 * m)
    out_ok = ctypes.create_string_buffer(m)
    lib.edb_ristretto_to_edwards(encs, m, out_enc, out_ok)
    return out_enc.raw, np.frombuffer(out_ok.raw, np.uint8).astype(bool)


def _msm_identity(points: bytes, coeffs: bytes, m: int) -> int:
    return _load().edb_msm_is_identity_x8(points, coeffs, m)


def _decompress_ok(encs: bytes, m: int) -> np.ndarray:
    out = ctypes.create_string_buffer(m)
    _load().edb_decompress_ok(encs, m, out)
    return np.frombuffer(out.raw, np.uint8).astype(bool)


def keccak_f1600_inplace(state: bytearray) -> bool:
    """Native keccak-f[1600] on a 200-byte state; False if unavailable
    (the merlin/STROBE layer falls back to its pure-Python permutation)."""
    lib = _load()
    if lib is None:
        return False
    buf = (ctypes.c_ubyte * 200).from_buffer(state)
    lib.edb_keccak_f1600(ctypes.addressof(buf))
    return True


def scalar_base_mult(scalar: int):
    """[s]B as an extended-coordinate point tuple, or None if the native
    engine is unavailable.

    The SIGNING primitive: the C side uses a constant-time window select
    (no secret-indexed loads/branches), unlike the variable-time Python
    oracle — sr25519 signing routes here (crypto/sr25519.py). ~50 us vs
    ~5 ms pure Python.
    """
    lib = _load()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(64)
    lib.edb_scalar_base_mult_xy(
        (scalar % L).to_bytes(32, "little"), out
    )
    x = int.from_bytes(out.raw[:32], "little")
    y = int.from_bytes(out.raw[32:], "little")
    return (x, y, 1, x * y % ref.P)


class _Lane:
    __slots__ = ("a", "r", "s", "k", "z")

    def __init__(self, a, r, s, k, z):
        self.a, self.r, self.s, self.k, self.z = a, r, s, k, z


def _check_lanes_res(lanes) -> int:
    """One RLC MSM over the given lanes.

    Returns the raw engine verdict: 1 all-valid, 0 equation fails,
    -(2+i) when MSM input point i fails ZIP-215 decoding (the engine
    decompresses before any bucket work, so a decode failure costs
    only the decompression prefix, not an MSM)."""
    m = 2 * len(lanes) + 1
    points = bytearray()
    coeffs = bytearray()
    b = 0
    for ln in lanes:
        b = (b + ln.z * ln.s) % L
        points += ln.a
        coeffs += ((-(ln.z * ln.k)) % L).to_bytes(32, "little")
        # -R with coefficient +z (128-bit) instead of R with L - z
        # (252-bit): point negation is a sign-bit flip on the encoding
        # (exact under ZIP-215 incl. the x == 0 fixed point), and short
        # coefficients skip half the Pippenger windows.
        points += ln.r[:31] + bytes([ln.r[31] ^ 0x80])
        coeffs += ln.z.to_bytes(32, "little")
    points += _B_ENC
    coeffs += b.to_bytes(32, "little")
    return _msm_identity(bytes(points), bytes(coeffs), m)


def _check_lanes(lanes) -> bool:
    """True iff all lanes valid; callers guarantee decodable points."""
    res = _check_lanes_res(lanes)
    # decompress failures were filtered upstream; a residual -n is a
    # bug, not an invalid signature — surface it
    if res < 0:
        raise RuntimeError(f"unexpected decompress failure at {-res - 2}")
    return res == 1


def _verdict_lanes(lanes, out, idx_map, res=None) -> None:
    """Full RLC verdict over built lanes: one MSM; on an undecodable
    point, filter it and re-check; on equation failure, binary-split
    attribution. Shared by verify_many's sad path and verify_quads so
    the ed25519 and sr25519 host paths can't diverge.

    ``res``: a verdict already obtained for exactly these lanes and
    coefficients (verify_many's fused edb_verify_batch call) — skips
    the redundant opening MSM."""
    if not lanes:
        return
    if res is None:
        res = _check_lanes_res(lanes)
    if res == 1:
        for i in idx_map:
            out[i] = True
        return
    if res < 0:
        enc = b"".join(ln.a + ln.r for ln in lanes)
        ok = _decompress_ok(enc, 2 * len(lanes))
        good, gmap = [], []
        for j, (ln, i) in enumerate(zip(lanes, idx_map)):
            if ok[2 * j] and ok[2 * j + 1]:
                good.append(ln)
                gmap.append(i)
        lanes, idx_map = good, gmap
        if not lanes:
            return
        if _check_lanes(lanes):
            for i in idx_map:
                out[i] = True
            return
    _attribute(lanes, out, idx_map)


def _attribute(lanes, out, idx_map) -> None:
    """Binary-split attribution of a failing batch (voi-style)."""
    if len(lanes) == 1:
        out[idx_map[0]] = _check_lanes(lanes)
        return
    if _check_lanes(lanes):
        for i in idx_map:
            out[i] = True
        return
    mid = len(lanes) // 2
    _attribute(lanes[:mid], out, idx_map[:mid])
    _attribute(lanes[mid:], out, idx_map[mid:])


def verify_quads(quads) -> list[bool] | None:
    """RLC batch verdict over precomputed (A_enc, R_enc, s, k) quads.

    The sr25519 HOST path: challenges come from the native merlin engine
    (sr_challenge_batch) and the points are ristretto decodes
    re-compressed as edwards encodings — the curve equation, one
    Pippenger MSM, and the binary-split attribution are exactly the
    ed25519 machinery (reference: crypto/sr25519/batch.go:48-61 feeds
    the same curve25519-voi verifier core its ed25519 batch uses).
    Entries may be None (malformed lane -> False). Returns None when the
    native engine is unavailable.
    """
    if _load() is None:
        return None
    n = len(quads)
    out = [False] * n
    lanes, idx_map = [], []
    for i, q in enumerate(quads):
        if q is None:
            continue
        a_enc, r_enc, s, k = q
        z = 0
        while z == 0:  # z == 0 voids the RLC: redraw (p = 2^-128)
            z = int.from_bytes(secrets.token_bytes(16), "little")
        lanes.append(_Lane(bytes(a_enc), bytes(r_enc), s, k, z))
        idx_map.append(i)
    _verdict_lanes(lanes, out, idx_map)
    return out


def verify_many(pubkeys, msgs, sigs) -> list[bool]:
    """Batch ZIP-215 verification; one MSM for an all-valid batch.

    Falls back to fast25519 (sequential OpenSSL + oracle recheck) when
    the native engine is unavailable.
    """
    if _load() is None:
        from . import fast25519

        return fast25519.verify_many(pubkeys, msgs, sigs)
    n = len(pubkeys)
    out = [False] * n
    # Happy path: ONE fused native call — SHA-512 challenges, mod-L
    # coefficient math, the basepoint scalar, and the MSM all in C. The
    # only per-lane Python left is the length/S<L admission filter.
    well = []  # (index, pubkey, sig, msg) of well-formed lanes
    for i in range(n):
        p, m, s = bytes(pubkeys[i]), bytes(msgs[i]), bytes(sigs[i])
        if len(p) != 32 or len(s) != 64:
            continue
        if int.from_bytes(s[32:], "little") >= L:
            continue  # S must be canonical even under ZIP-215
        well.append((i, p, s, m))
    if not well:
        return out
    zs = bytearray(secrets.token_bytes(16 * len(well)))
    zero16 = bytes(16)
    for j in range(len(well)):  # z == 0 voids the RLC: redraw (p=2^-128)
        while zs[16 * j : 16 * j + 16] == zero16:
            zs[16 * j : 16 * j + 16] = secrets.token_bytes(16)
    recs = b"".join(p + s for _i, p, s, _m in well)
    msgs_blob = b"".join(m for *_x, m in well)
    offs = [0]
    for *_x, m in well:
        offs.append(offs[-1] + len(m))
    offs_arr = (ctypes.c_uint64 * len(offs))(*offs)
    res = _load().edb_verify_batch(
        recs, msgs_blob, offs_arr, bytes(zs), len(well)
    )
    if res == 1:
        for i, *_x in well:
            out[i] = True
        return out
    # Sad path (invalid signature or undecodable point in the batch):
    # rebuild Python lanes for attribution, REUSING the drawn
    # coefficients (they were never revealed, so they stay sound — and
    # the splits then re-check exactly the committed linear
    # combination). Paying the challenge twice here is fine — this path
    # only runs under attack/corruption.
    lanes, idx_map = [], []
    for j, (i, p, s, m) in enumerate(well):
        k = ref.challenge_scalar(s[:32], p, m)
        z = int.from_bytes(zs[16 * j : 16 * j + 16], "little")
        lanes.append(
            _Lane(p, s[:32], int.from_bytes(s[32:], "little"), k, z)
        )
        idx_map.append(i)
    _verdict_lanes(lanes, out, idx_map, res=res)
    return out
