"""secp256k1 ECDSA keys (reference: crypto/secp256k1/secp256k1.go:227).

Host-side only, like the reference (btcec has no batch interface and
secp256k1 is out of the consensus hot path). Backed by OpenSSL through
the ``cryptography`` wheel with a pure-Python fallback for the math the
wheel doesn't expose (point decompression for 33-byte keys).

Wire formats match the reference: 33-byte compressed pubkeys, 32-byte
private keys, 64-byte raw (r||s) signatures with LOW-S normalization
(secp256k1.go Sign uses RFC6979 + canonical low-s), addresses =
RIPEMD160(SHA256(pubkey)) — the Bitcoin-style address the reference
keeps for this key type (secp256k1.go:30-40).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

try:  # the cryptography wheel is baked into prod images; degrade
    # explicitly on slim containers instead of breaking package import
    # (secp256k1 is off the consensus hot path — ed25519 stays fully
    # functional without the wheel).
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
        encode_dss_signature,
    )

    _HAVE_OPENSSL = True
except ImportError:  # pragma: no cover
    hashes = ec = decode_dss_signature = encode_dss_signature = None
    _HAVE_OPENSSL = False

SECP256K1_KEY_TYPE = "secp256k1"
PUBKEY_SIZE = 33
PRIVKEY_SIZE = 32
SIGNATURE_SIZE = 64

# curve order (for low-s normalization)
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


_degraded_warned = False


def _warn_degraded_once() -> None:
    global _degraded_warned
    if _degraded_warned:
        return
    _degraded_warned = True
    from ..libs import log as _log

    _log.default_logger().with_module("crypto.secp256k1").error(
        "secp256k1 verification UNAVAILABLE (no 'cryptography' wheel): "
        "all secp256k1 signatures verify False — this node will diverge "
        "from wheel-backed peers on chains with secp256k1 validators"
    )


def _address(pubkey33: bytes) -> bytes:
    return hashlib.new(
        "ripemd160", hashlib.sha256(pubkey33).digest()
    ).digest()


@dataclass(frozen=True, slots=True)
class Secp256k1PubKey:
    data: bytes  # 33-byte compressed SEC1 point

    def __post_init__(self) -> None:
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("secp256k1 pubkey must be 33 bytes")

    @property
    def type(self) -> str:
        return SECP256K1_KEY_TYPE

    def address(self) -> bytes:
        from .keys import Address

        return Address(_address(self.data))

    def bytes(self) -> bytes:
        return self.data

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if not _HAVE_OPENSSL:
            # Reject-only degradation: never accept unchecked. This IS a
            # consensus divergence on chains with secp256k1 validators —
            # say so loudly (once), don't let the operator discover it
            # as a silent stall.
            _warn_degraded_once()
            return False
        if len(sig) != SIGNATURE_SIZE:
            return False
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256K1(), self.data
            )
            r = int.from_bytes(sig[:32], "big")
            s = int.from_bytes(sig[32:], "big")
            # low-S only: the reference rejects malleable high-S forms
            # (secp256k1.go Signature serialization is canonical)
            if r == 0 or s == 0 or r >= _N or s > _N // 2:
                return False
            pub.verify(
                encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256())
            )
            return True
        except Exception:
            return False

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Secp256k1PubKey) and self.data == other.data
        )

    def __hash__(self) -> int:
        return hash((SECP256K1_KEY_TYPE, self.data))


@dataclass(frozen=True, slots=True)
class Secp256k1PrivKey:
    data: bytes  # 32-byte big-endian scalar

    def __post_init__(self) -> None:
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("secp256k1 privkey must be 32 bytes")

    @classmethod
    def generate(cls, rng=os.urandom) -> "Secp256k1PrivKey":
        while True:
            seed = rng(32)
            v = int.from_bytes(seed, "big")
            if 0 < v < _N:
                return cls(seed)

    @classmethod
    def from_seed(cls, seed: bytes) -> "Secp256k1PrivKey":
        v = int.from_bytes(hashlib.sha256(seed).digest(), "big") % (_N - 1) + 1
        return cls(v.to_bytes(32, "big"))

    @property
    def type(self) -> str:
        return SECP256K1_KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def _key(self):
        if not _HAVE_OPENSSL:
            raise RuntimeError(
                "secp256k1 signing requires the 'cryptography' wheel"
            )
        return ec.derive_private_key(
            int.from_bytes(self.data, "big"), ec.SECP256K1()
        )

    def sign(self, msg: bytes) -> bytes:
        """64-byte r||s with low-s normalization (deterministic modulo
        OpenSSL's nonce; verification accepts any valid nonce)."""
        der = self._key().sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        if s > _N // 2:
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> Secp256k1PubKey:
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        raw = self._key().public_key().public_bytes(
            Encoding.X962, PublicFormat.CompressedPoint
        )
        return Secp256k1PubKey(raw)
