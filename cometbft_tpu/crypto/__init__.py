"""L1 crypto: keys, hashing, merkle, batch verification dispatch."""

from .keys import (  # noqa: F401
    Address,
    Ed25519PrivKey,
    Ed25519PubKey,
    ED25519_KEY_TYPE,
    pubkey_from_type_and_bytes,
)
from .batch import (  # noqa: F401
    BatchVerifier,
    Ed25519BatchVerifier,
    create_batch_verifier,
    supports_batch_verifier,
)
from . import hashplane, merkle, tmhash  # noqa: F401

# sr25519/secp256k1 register here (not in keys.py) to avoid import cycles
# while staying reachable from every production entry point.
from .keys import register_extra_key_types as _register_extra  # noqa: E402

_register_extra()
