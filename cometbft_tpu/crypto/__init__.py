"""L1 crypto: keys, hashing, merkle, batch verification dispatch."""

from .keys import (  # noqa: F401
    Address,
    Ed25519PrivKey,
    Ed25519PubKey,
    ED25519_KEY_TYPE,
    pubkey_from_type_and_bytes,
)
from .batch import (  # noqa: F401
    BatchVerifier,
    Ed25519BatchVerifier,
    create_batch_verifier,
    supports_batch_verifier,
)
from . import merkle, tmhash  # noqa: F401
