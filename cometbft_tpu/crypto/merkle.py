"""RFC-6962 merkle tree, proofs, and proof-operator chaining.

Reference surface: crypto/merkle/tree.go (HashFromByteSlices), proof.go
(Proof, ComputeProofs), proof_op.go (ProofOperator chaining). Domain
separation: leaf = SHA256(0x00 || item), inner = SHA256(0x01 || l || r);
empty tree hashes to SHA256("").
"""

from __future__ import annotations

from dataclasses import dataclass

from . import tmhash

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _leaf_hash(item: bytes) -> bytes:
    return tmhash.sum(LEAF_PREFIX + item)


def _inner_hash(left: bytes, right: bytes) -> bytes:
    return tmhash.sum(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (RFC 6962 split)."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Root hash of the RFC-6962 tree over ``items``."""
    n = len(items)
    if n == 0:
        return tmhash.sum(b"")
    if n == 1:
        return _leaf_hash(items[0])
    k = _split_point(n)
    return _inner_hash(
        hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:])
    )


@dataclass(slots=True)
class Proof:
    """Inclusion proof for item ``index`` of ``total`` (crypto/merkle/proof.go)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes]

    def compute_root_hash(self) -> bytes | None:
        return _root_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root_hash: bytes, leaf: bytes) -> None:
        if self.total < 0 or self.index < 0:
            raise ValueError("proof total/index must be non-negative")
        if _leaf_hash(leaf) != self.leaf_hash:
            raise ValueError("leaf hash mismatch")
        if self.compute_root_hash() != root_hash:
            raise ValueError("invalid merkle proof")


def _root_from_aunts(
    index: int, total: int, leaf: bytes, aunts: list[bytes]
) -> bytes | None:
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _root_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return _inner_hash(left, aunts[-1])
    right = _root_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return _inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """(root, per-item proofs) — crypto/merkle/proof.go ProofsFromByteSlices."""
    trails, root = _trails_from_byte_slices(items)
    root_hash = root.hash
    proofs = [
        Proof(
            total=len(items),
            index=i,
            leaf_hash=trail.hash,
            aunts=trail.flatten_aunts(),
        )
        for i, trail in enumerate(trails)
    ]
    return root_hash, proofs


class _ProofNode:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, hash_: bytes):
        self.hash = hash_
        self.parent = None
        self.left = None  # sibling on the left
        self.right = None  # sibling on the right

    def flatten_aunts(self) -> list[bytes]:
        aunts: list[bytes] = []
        node = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_byte_slices(items: list[bytes]):
    n = len(items)
    if n == 0:
        return [], _ProofNode(tmhash.sum(b""))
    if n == 1:
        node = _ProofNode(_leaf_hash(items[0]))
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _ProofNode(_inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root


# --- Proof operators (crypto/merkle/proof_op.go) -----------------------------


class ProofOperator:
    """One verification step: maps child value(s) -> parent value."""

    def run(self, values: list[bytes]) -> list[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError


@dataclass(slots=True)
class ValueOp(ProofOperator):
    """Leaf-value op: proves SHA256(value)'s inclusion under a root."""

    key: bytes
    proof: Proof

    def run(self, values: list[bytes]) -> list[bytes]:
        if len(values) != 1:
            raise ValueError("ValueOp expects one value")
        vhash = tmhash.sum(values[0])
        if _leaf_hash(vhash) != self.proof.leaf_hash:
            raise ValueError("leaf mismatch")
        root = self.proof.compute_root_hash()
        if root is None:
            raise ValueError("bad proof shape")
        return [root]

    def get_key(self) -> bytes:
        return self.key


class ProofOperators(list):
    """Chain of operators verified leaf -> root (proof_op.go Verify)."""

    def verify_value(self, root: bytes, keypath: list[bytes], value: bytes) -> None:
        self.verify(root, keypath, [value])

    def verify(self, root: bytes, keypath: list[bytes], args: list[bytes]) -> None:
        keys = list(keypath)
        for op in self:
            key = op.get_key()
            if key:
                if not keys or keys[-1] != key:
                    raise ValueError(f"key mismatch on {key!r}")
                keys.pop()
            args = op.run(args)
        if args[0] != root:
            raise ValueError("computed root does not match")
        if keys:
            raise ValueError("keypath not fully consumed")
