"""RFC-6962 merkle tree, proofs, and proof-operator chaining.

Reference surface: crypto/merkle/tree.go (HashFromByteSlices), proof.go
(Proof, ComputeProofs), proof_op.go (ProofOperator chaining). Domain
separation: leaf = SHA256(0x00 || item), inner = SHA256(0x01 || l || r);
empty tree hashes to SHA256("").

The tree is built as an iterative LEVEL-ORDER walk, not the reference's
largest-power-of-two-split recursion: pairing adjacent nodes and
promoting an odd tail unchanged produces the IDENTICAL tree (the
certificate-transparency construction — the promoted node is exactly
the right spine the split recursion builds), it cannot hit Python's
recursion limit on 100k+-leaf trees (large blocks, simnet storms), and
each level is one flat batch of independent hashes — which is what
lets the device hash plane (crypto/hashplane.py) run leaf and inner
rounds level-by-level through the batched SHA-256 kernel. Level-shape
identity with the recursion is pinned by tests/test_hashplane.py.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import tmhash

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _leaf_hash(item: bytes) -> bytes:
    # routed: a 64 KiB PartSet leaf coalesces into a device window when
    # the hash plane is up; small leaves (and device-less containers)
    # take the plain host hash with zero round trips. Ledger default:
    # untagged merkle hashing attributes to the merkle tenant (an
    # outer mempool/blocksync declaration wins).
    from . import hashplane
    from ..libs import devledger

    with devledger.caller_class("merkle"):
        return hashplane.hash_bytes(LEAF_PREFIX + item)


def _inner_hash(left: bytes, right: bytes) -> bytes:
    return tmhash.sum(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (RFC 6962 split)."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def _compute_levels(items: list[bytes]) -> list[list[bytes]]:
    """All tree levels bottom-up: level 0 = leaf hashes, last = [root].

    Each level pairs adjacent nodes; an odd tail node is promoted to
    the next level unchanged. THE one level walk — every level is one
    flat batch through ``hashplane.hash_many``, which routes it to the
    device plane when a routed window can win and to host ``hashlib``
    otherwise, so the tree logic (and the domain-separation prefixes)
    cannot fork between the two paths.
    """
    from . import hashplane
    from ..libs import devledger

    with devledger.caller_class("merkle"):
        level = hashplane.hash_many(
            [LEAF_PREFIX + bytes(x) for x in items]
        )
        levels = [level]
        while len(level) > 1:
            nxt = hashplane.hash_many(
                [
                    INNER_PREFIX + level[i] + level[i + 1]
                    for i in range(0, len(level) - 1, 2)
                ]
            )
            if len(level) % 2:
                nxt.append(level[-1])
            level = nxt
            levels.append(level)
        return levels


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Root hash of the RFC-6962 tree over ``items``."""
    if not items:
        return tmhash.sum(b"")
    return _compute_levels(items)[-1][0]


@dataclass(slots=True)
class Proof:
    """Inclusion proof for item ``index`` of ``total`` (crypto/merkle/proof.go)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes]

    def compute_root_hash(self) -> bytes | None:
        return _root_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root_hash: bytes, leaf: bytes) -> None:
        if self.total < 0 or self.index < 0:
            raise ValueError("proof total/index must be non-negative")
        if _leaf_hash(leaf) != self.leaf_hash:
            raise ValueError("leaf hash mismatch")
        if self.compute_root_hash() != root_hash:
            raise ValueError("invalid merkle proof")


def _root_from_aunts(
    index: int, total: int, leaf: bytes, aunts: list[bytes]
) -> bytes | None:
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _root_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return _inner_hash(left, aunts[-1])
    right = _root_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return _inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """(root, per-item proofs) — crypto/merkle/proof.go ProofsFromByteSlices.

    Built from the level arrays instead of a recursive trail forest:
    leaf ``i``'s aunt at each level is its pair sibling (``idx ^ 1``)
    when one exists — a promoted odd-tail node contributes no aunt at
    the level it skipped — and ``idx //= 2`` maps to the parent either
    way. Aunt order is leaf-to-root, exactly what ``_root_from_aunts``
    consumes from the end.
    """
    if not items:
        return tmhash.sum(b""), []
    levels = _compute_levels(items)
    total = len(items)
    proofs = []
    for i in range(total):
        aunts: list[bytes] = []
        idx = i
        for level in levels[:-1]:
            sib = idx ^ 1
            if sib < len(level):
                aunts.append(level[sib])
            idx //= 2
        proofs.append(
            Proof(
                total=total,
                index=i,
                leaf_hash=levels[0][i],
                aunts=aunts,
            )
        )
    return levels[-1][0], proofs


# --- Proof operators (crypto/merkle/proof_op.go) -----------------------------


class ProofOperator:
    """One verification step: maps child value(s) -> parent value."""

    def run(self, values: list[bytes]) -> list[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError


@dataclass(slots=True)
class ValueOp(ProofOperator):
    """Leaf-value op: proves SHA256(value)'s inclusion under a root."""

    key: bytes
    proof: Proof

    def run(self, values: list[bytes]) -> list[bytes]:
        if len(values) != 1:
            raise ValueError("ValueOp expects one value")
        vhash = tmhash.sum(values[0])
        if _leaf_hash(vhash) != self.proof.leaf_hash:
            raise ValueError("leaf mismatch")
        root = self.proof.compute_root_hash()
        if root is None:
            raise ValueError("bad proof shape")
        return [root]

    def get_key(self) -> bytes:
        return self.key


class ProofOperators(list):
    """Chain of operators verified leaf -> root (proof_op.go Verify)."""

    def verify_value(self, root: bytes, keypath: list[bytes], value: bytes) -> None:
        self.verify(root, keypath, [value])

    def verify(self, root: bytes, keypath: list[bytes], args: list[bytes]) -> None:
        keys = list(keypath)
        for op in self:
            key = op.get_key()
            if key:
                if not keys or keys[-1] != key:
                    raise ValueError(f"key mismatch on {key!r}")
                keys.pop()
            args = op.run(args)
        if args[0] != root:
            raise ValueError("computed root does not match")
        if keys:
            raise ValueError("keypath not fully consumed")
