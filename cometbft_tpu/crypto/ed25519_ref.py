"""Pure-Python ed25519 reference implementation (host side).

This is the correctness oracle for the batched JAX/TPU verifier in
``cometbft_tpu.ops`` and the signing path for host key types. Verification
uses **ZIP-215** point-acceptance semantics, matching the reference engine's
consensus-critical rules (reference: crypto/ed25519/ed25519.go:26-29):

  * non-canonical point encodings (y >= p) are accepted,
  * the encoding with x = 0 and sign bit 1 ("negative zero") is accepted,
  * S must be canonical (S < L),
  * the verification equation is cofactored: [8]([S]B - [k]A - R) == O.

Signing follows RFC 8032 exactly (deterministic nonce).

All arithmetic is Python big-int; speed is adequate for signing, test
oracles, and the single-signature fallback path. The hot batch path lives on
the TPU (ops/verify.py).
"""

from __future__ import annotations

import hashlib

# --- Field / curve constants -------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P            # curve constant d
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)                    # sqrt(-1) mod p

# Base point B: y = 4/5, x recovered with even parity.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """Recover x from y and the sign bit. Returns None if not on curve.

    ZIP-215: 'negative zero' (x == 0, sign == 1) is *accepted* and yields 0.
    (RFC 8032 would reject it; the reference engine consensus rules are
    ZIP-215 — crypto/ed25519/ed25519.go:26-29.)
    """
    y %= P
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root of u/v: x = u * v^3 * (u * v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    vxx = v * x * x % P
    if vxx == u:
        pass
    elif vxx == (P - u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x & 1 != sign:
        x = (P - x) % P
    return x


_BX = _recover_x(_BY, 0)
assert _BX is not None

# --- Point arithmetic (extended twisted Edwards coordinates) -----------------
# Point = (X, Y, Z, T) with x = X/Z, y = Y/Z, T = X*Y/Z.
# The addition law is complete on the whole curve group because a = -1 is a
# square mod p and d is a non-square (Bernstein–Lange completeness theorem),
# which matters under ZIP-215: small-order/mixed-order points are admitted.

IDENTITY = (0, 1, 1, 0)
BASE = (_BX, _BY, 1, _BX * _BY % P)


def point_add(p1, p2):
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = T1 * D2 % P * T2 % P
    Dd = 2 * Z1 * Z2 % P
    E = B - A
    F = Dd - C
    G = Dd + C
    H = B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p1):
    X1, Y1, Z1, _ = p1
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + B
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = A - B
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_neg(p1):
    X1, Y1, Z1, T1 = p1
    return ((P - X1) % P, Y1, Z1, (P - T1) % P)


def scalar_mult(k: int, point) -> tuple:
    acc = IDENTITY
    while k > 0:
        if k & 1:
            acc = point_add(acc, point)
        point = point_double(point)
        k >>= 1
    return acc


def point_equal(p1, p2) -> bool:
    X1, Y1, Z1, _ = p1
    X2, Y2, Z2, _ = p2
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def is_identity(p1) -> bool:
    X1, Y1, Z1, _ = p1
    return X1 % P == 0 and (Y1 - Z1) % P == 0


def compress(point) -> bytes:
    X, Y, Z, _ = point
    zinv = pow(Z, P - 2, P)
    x = X * zinv % P
    y = Y * zinv % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def decompress(s: bytes):
    """ZIP-215 decompression. Returns extended point or None."""
    if len(s) != 32:
        return None
    enc = int.from_bytes(s, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)  # NOT reduced-checked: y >= p accepted (ZIP-215)
    x = _recover_x(y, sign)
    if x is None:
        return None
    y %= P
    return (x, y, 1, x * y % P)


# --- Signing / verification (RFC 8032 + ZIP-215) -----------------------------


def _sha512(*parts: bytes) -> bytes:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return h.digest()


def _clamp(a: bytes) -> int:
    s = bytearray(a)
    s[0] &= 248
    s[31] &= 127
    s[31] |= 64
    return int.from_bytes(bytes(s), "little")


def pubkey_from_seed(seed: bytes) -> bytes:
    return _expand_seed(seed)[2]


_EXPANDED_CACHE: dict[bytes, tuple[int, bytes, bytes]] = {}


def _expand_seed(seed: bytes) -> tuple[int, bytes, bytes]:
    """seed -> (clamped scalar a, prefix, compressed pubkey A), cached.

    Mirrors the reference engine's expanded-pubkey cache
    (crypto/ed25519/ed25519.go:31,56): the [a]B scalar mult is per-key
    constant and must not be repaid on every vote signature.
    """
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    cached = _EXPANDED_CACHE.get(seed)
    if cached is None:
        h = _sha512(seed)
        a = _clamp(h[:32])
        cached = (a, h[32:], compress(scalar_mult(a, BASE)))
        if len(_EXPANDED_CACHE) >= 4096:  # bound like the reference LRU
            _EXPANDED_CACHE.pop(next(iter(_EXPANDED_CACHE)))
        _EXPANDED_CACHE[seed] = cached
    return cached


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 deterministic signature; returns 64 bytes R||S.

    NOTE: this pure-Python path is variable-time (secret-dependent branches
    and big-int timing). It is the correctness oracle and test signer; the
    production privval signing path delegates to a constant-time backend.
    """
    a, prefix, A = _expand_seed(seed)
    r = int.from_bytes(_sha512(prefix, msg), "little") % L
    R = compress(scalar_mult(r, BASE))
    k = int.from_bytes(_sha512(R, A, msg), "little") % L
    s = (r + k * a) % L
    return R + int.to_bytes(s, 32, "little")


def challenge_scalar(sig_r: bytes, pubkey: bytes, msg: bytes) -> int:
    """k = SHA512(R || A || M) mod L — shared by host and device paths."""
    return int.from_bytes(_sha512(sig_r, pubkey, msg), "little") % L


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 single-signature verification (cofactored equation)."""
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    s_int = int.from_bytes(sig[32:], "little")
    if s_int >= L:  # S must be canonical under ZIP-215
        return False
    A = decompress(pubkey)
    if A is None:
        return False
    R = decompress(sig[:32])
    if R is None:
        return False
    k = challenge_scalar(sig[:32], pubkey, msg)
    # [8]([S]B - [k]A - R) == O
    sB = scalar_mult(s_int, BASE)
    kA = scalar_mult(k, A)
    acc = point_add(point_add(sB, point_neg(kA)), point_neg(R))
    for _ in range(3):
        acc = point_double(acc)
    return is_identity(acc)
