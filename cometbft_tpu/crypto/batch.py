"""Batch-verification dispatch: key type -> batch verifier backend.

Reference surface: crypto/crypto.go:45-54 (BatchVerifier interface) and
crypto/batch/batch.go:11-32 (CreateBatchVerifier / SupportsBatchVerifier).

The ed25519 backend accumulates (pubkey, msg, sig) triples on host and
verifies them in ONE TPU kernel launch (ops/verify.py) — the engine-wide
hot path: commit verification (types/validation.go:153-257), light-client
replay, blocksync catch-up, and the vote-ingest micro-batching window all
come through this interface.
"""

from __future__ import annotations

import os

import numpy as np

from ..libs import metrics as libmetrics
from ..libs import sync as libsync
from . import keys
from .keys import Ed25519PubKey


class BatchVerifier:
    """Add/Verify contract of crypto.BatchVerifier (crypto/crypto.go:45-54).

    ``verify`` returns (all_valid, per_signature_validity); per-lane results
    let callers attribute failures without the second single-verify pass the
    reference falls back to (types/validation.go:243-250).
    """

    def add(self, pub_key, msg: bytes, signature: bytes) -> None:
        raise NotImplementedError

    def verify(self) -> tuple[bool, list[bool]]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


# Below this size the host finishes before the device round trip's fixed
# latency floor (~70 ms through the relay) — measured crossover ~768
# lanes on a v5e against the old sequential-OpenSSL host path. The host
# path is now the native RLC batch verifier (crypto/host_batch.py,
# ~1.5-3x sequential OpenSSL), which pushes the true crossover HIGHER;
# the device side also got faster (expanded-pubkey arena, pre-staging,
# donated buffers). The reference has the inverse constant
# (batchVerifyThreshold, types/validation.go:13-17: below it batching
# isn't worth setup).
#
# Derivation chain, most authoritative first:
#   1. COMETBFT_TPU_HOST_THRESHOLD env (operator override / driver);
#   2. the last chip-measured crossover recorded by bench.py's
#      9_device_floor breakdown (BENCH_CHIP_TABLE.json, only trusted
#      when measured on an accelerator backend);
#   3. the static 768 fallback.
_DEFAULT_HOST_BATCH_THRESHOLD = 768


def _derive_host_threshold() -> int:
    import os

    from ..libs import chip_table

    env = os.environ.get("COMETBFT_TPU_HOST_THRESHOLD")
    if env:
        try:
            return max(2, int(env))
        except ValueError:
            pass
    # load_chip_table anchors the path to the repo root (bench.py
    # writes it there) and trusts only accelerator-measured captures.
    row = chip_table.find_row(
        chip_table.load_chip_table(), "9_device_floor"
    )
    if row is not None:
        xo = row.get("measured_crossover_lanes")
        if isinstance(xo, int) and xo >= 2:
            return xo
        rows = row.get("rows") or []
        max_n = max((r.get("n", 0) for r in rows), default=0)
        if xo is None and max_n >= 2048:
            # The chip WAS measured, the sweep covered real production
            # sizes, and the device never beat the host: route
            # everything host rather than trusting the static guess
            # (round-4 verdict task 4 — 768 can be wrong both ways). A
            # tiny or truncated sweep (max n < 2048) must NOT poison
            # the knob.
            return 1 << 30
    return _DEFAULT_HOST_BATCH_THRESHOLD


HOST_BATCH_THRESHOLD = _derive_host_threshold()


class AdaptiveCrossover:
    """Runtime-calibrated host/device batch-size crossover.

    The static cutover (HOST_BATCH_THRESHOLD's env > chip-table > 768
    chain) is a boot-time guess; this class refines it from the SAME
    measurements the phase metrics record. Both sides get the same
    model, matching what 9_device_floor measures:
    ``time(n) = floor + slope * n`` — the device floor is the launch
    cost that dominates small batches, and the host floor is the fixed
    per-call cost of ``host_batch.verify_many`` (the dominant host feed
    is tiny sub-cutover coalescer windows, and folding that per-call
    cost into a per-lane rate would drag the crossover below the host
    MSM's true win region). Every end-to-end observation
    (crypto/batch._observe, plus the coalescer's windows — the steady
    state's only source of small-n samples on both sides) feeds decayed
    least-squares accumulators; the crossover solves
    ``h_floor + h_rate * n = d_floor + d_slope * n`` and is clamped to
    [64, 16384].

    Until both sides have ``MIN_SAMPLES`` the seed answers, so boot
    behavior is exactly the old static routing; an operator env pin
    (COMETBFT_TPU_HOST_THRESHOLD) disables adaptation entirely.
    """

    DECAY = 0.98  # per-observation decay of the running moments
    MIN_SAMPLES = 5
    LO, HI = 64, 16384

    def __init__(self) -> None:
        self._mtx = libsync.Mutex("crypto.batch._crossover")
        # decayed least-squares moments of (n, seconds) pairs per side
        self._host = [0.0, 0.0, 0.0, 0.0, 0.0]  # sw, sx, sy, sxx, sxy
        self._dev = [0.0, 0.0, 0.0, 0.0, 0.0]
        self._host_n = 0
        self._dev_n = 0

    def _accumulate(self, acc: list[float], n: int, seconds: float) -> None:
        d = self.DECAY
        acc[0] = d * acc[0] + 1.0
        acc[1] = d * acc[1] + n
        acc[2] = d * acc[2] + seconds
        acc[3] = d * acc[3] + float(n) * n
        acc[4] = d * acc[4] + n * seconds

    def observe_host(self, n: int, seconds: float) -> None:
        if n <= 0 or seconds <= 0:
            return
        with self._mtx:
            self._host_n += 1
            self._accumulate(self._host, n, seconds)

    def observe_device(self, n: int, seconds: float) -> None:
        if n <= 0 or seconds <= 0:
            return
        with self._mtx:
            self._dev_n += 1
            self._accumulate(self._dev, n, seconds)

    @staticmethod
    def _fit(acc: list[float]) -> tuple[float, float]:
        """(floor, slope) of time(n) = floor + slope*n from the decayed
        moments. Samples at ~one size give a pure floor (slope 0) —
        conservative, since a flat model overstates that side's cost at
        small n and understates it at large n only where the other
        side's slope decides anyway."""
        sw, sx, sy, sxx, sxy = acc
        mx = sx / sw
        my = sy / sw
        var = sxx / sw - mx * mx
        cov = sxy / sw - mx * my
        if var > 1e-9:
            slope = max(0.0, cov / var)
            floor = max(0.0, my - slope * mx)
        else:
            slope, floor = 0.0, my
        return floor, slope

    def reset(self) -> None:
        """Drop every accumulated sample (a refit from scratch).

        The decayed moments forget slowly (~50-sample half-life); when
        the device cost profile steps — lane arenas flip on, the
        readback drain lands, a kernel swap — stale samples would keep
        answering for the OLD floor for hundreds of windows. Callers
        that change the profile (bench captures, an operator toggling
        staging knobs) reset so the live fit re-converges on the new
        floor immediately."""
        with self._mtx:
            self._host = [0.0, 0.0, 0.0, 0.0, 0.0]
            self._dev = [0.0, 0.0, 0.0, 0.0, 0.0]
            self._host_n = 0
            self._dev_n = 0

    def fit_summary(self) -> dict:
        """The live floor fit, for bench/debug surfaces: per-side
        (floor_s, slope_s_per_lane, samples) plus the solved crossover.
        Floors are None while that side is uncalibrated."""
        with self._mtx:
            host_n, dev_n = self._host_n, self._dev_n
            h = (
                self._fit(self._host)
                if host_n >= self.MIN_SAMPLES and self._host[0] > 0
                else None
            )
            d = (
                self._fit(self._dev)
                if dev_n >= self.MIN_SAMPLES and self._dev[0] > 0
                else None
            )
        return {
            "host_floor_s": h[0] if h else None,
            "host_rate_s_per_lane": h[1] if h else None,
            "host_samples": host_n,
            "device_floor_s": d[0] if d else None,
            "device_slope_s_per_lane": d[1] if d else None,
            "device_samples": dev_n,
            "crossover_lanes": self.threshold(),
        }

    def threshold(self) -> int | None:
        """The calibrated crossover, or None while uncalibrated."""
        with self._mtx:
            if (
                self._host_n < self.MIN_SAMPLES
                or self._dev_n < self.MIN_SAMPLES
                or self._host[0] <= 0
                or self._dev[0] <= 0
            ):
                return None
            h_floor, h_rate = self._fit(self._host)
            d_floor, d_slope = self._fit(self._dev)
        if h_rate <= d_slope:
            # the host's per-lane cost never exceeds the device's: past
            # any floors the host wins at EVERY size, keep everything up
            # to the clamp ceiling on host
            return self.HI
        # h_floor + h_rate*n = d_floor + d_slope*n; a device floor
        # already below the host floor clamps at LO (device wins from
        # the smallest routed sizes)
        n_star = (d_floor - h_floor) / (h_rate - d_slope)
        return int(min(self.HI, max(self.LO, n_star)))


CROSSOVER = AdaptiveCrossover()

_ENV_PINNED = bool(os.environ.get("COMETBFT_TPU_HOST_THRESHOLD"))


def _adaptive_enabled() -> bool:
    """Adaptation applies when not env-pinned and either forced
    (COMETBFT_TPU_ADAPTIVE_THRESHOLD=1) or running on an accelerator
    backend — CPU test runs must stay deterministically on the seed."""
    if _ENV_PINNED:
        return False
    mode = os.environ.get("COMETBFT_TPU_ADAPTIVE_THRESHOLD", "auto")
    if mode == "0":
        return False
    if mode == "1":
        return True
    # live peek only: host_batch_threshold() sits inside every batch
    # verify, which must never pay (or hang in) jax backend init
    from ..libs.accel import accelerator_backend_live

    return accelerator_backend_live()


def host_batch_threshold() -> int:
    """The LIVE host/device cutover: operator env pin > adaptive
    runtime calibration > the boot seed (module attr
    HOST_BATCH_THRESHOLD — monkeypatchable, chip-table-derived)."""
    base = HOST_BATCH_THRESHOLD
    if not _adaptive_enabled():
        return base
    t = CROSSOVER.threshold()
    return base if t is None else t


def note_device_window(n: int, seconds: float) -> None:
    """Adaptive-crossover feed from the coalescer's device windows."""
    if _adaptive_enabled():
        CROSSOVER.observe_device(n, seconds)


def note_host_window(n: int, seconds: float) -> None:
    if _adaptive_enabled():
        CROSSOVER.observe_host(n, seconds)


class Ed25519BatchVerifier(BatchVerifier):
    """TPU-backed ed25519 batch verification with a host small-batch path."""

    def __init__(self) -> None:
        self._pubkeys: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, pub_key, msg: bytes, signature: bytes) -> None:
        if not isinstance(pub_key, Ed25519PubKey):
            raise TypeError("Ed25519BatchVerifier requires ed25519 keys")
        self._pubkeys.append(pub_key.data)
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(signature))

    def __len__(self) -> int:
        return len(self._pubkeys)

    def verify(self) -> tuple[bool, list[bool]]:
        import time as _time

        t0 = _time.perf_counter()
        if len(self._pubkeys) < host_batch_threshold():
            # Sub-crossover batches first try the cross-caller
            # coalescer: concurrent small callers (per-vote admission,
            # commit checks, preverify windows) share ONE device
            # micro-batch instead of each paying the host path alone.
            # Not routed / unavailable -> the native RLC host batch
            # (one multiscalar mult, the voi algorithm), which itself
            # falls back to sequential OpenSSL when the engine can't
            # build.
            from . import coalesce, host_batch

            bits = coalesce.verify_bytes(
                self._pubkeys, self._msgs, self._sigs
            )
            if bits is not None:
                _observe("ed25519-coalesce", t0, len(bits))
                return all(bits), list(bits)
            # restart the clock: a failed coalesce attempt's wait
            # (worst case a stalled-device ticket timeout) must not be
            # charged to the host backend's metrics or the crossover's
            # host-rate fit — that would collapse the threshold toward
            # the device exactly when the device path is unhealthy
            t0 = _time.perf_counter()
            bitmap = host_batch.verify_many(
                self._pubkeys, self._msgs, self._sigs
            )
            libmetrics.observe_verify_phase(
                "fallback",
                "ed25519-host",
                _time.perf_counter() - t0,
                len(bitmap),
            )
            _observe("ed25519-host", t0, len(bitmap))
            return all(bitmap), bitmap
        from ..ops import verify as ov

        # pack/dispatch/readback phase attribution happens inside
        # ops.verify.verify_batch (the phases live there)
        ok_all, bitmap = ov.verify_batch(self._pubkeys, self._msgs, self._sigs)
        _observe("ed25519-tpu", t0, len(self._pubkeys))
        return ok_all, list(np.asarray(bitmap, bool))


class Sr25519BatchVerifier(BatchVerifier):
    """sr25519 batch verification on the SAME TPU kernel as ed25519.

    The merlin challenge k is computed on host per lane
    (crypto/sr25519.verification_parts); the cofactored curve equation
    [8](sB - kA - R) == O then decides ristretto equality exactly
    (ristretto quotients out the torsion the cofactor clears). Reference
    surface: crypto/sr25519/batch.go:14-46.
    """

    # Without the native engine the host fallback is sequential pure
    # Python (~30 ms/sig, 6 scalar mults): the device wins from a
    # handful of lanes. WITH it, the host runs the same one-MSM RLC
    # path as ed25519 (native merlin challenges + verify_quads), so the
    # ed25519 crossover applies.
    HOST_THRESHOLD = 4

    def __init__(self) -> None:
        self._pubkeys: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, pub_key, msg: bytes, signature: bytes) -> None:
        from .sr25519 import Sr25519PubKey

        if not isinstance(pub_key, Sr25519PubKey):
            raise TypeError("Sr25519BatchVerifier requires sr25519 keys")
        self._pubkeys.append(pub_key.data)
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(signature))

    def __len__(self) -> int:
        return len(self._pubkeys)

    def verify(self) -> tuple[bool, list[bool]]:
        import os as _os
        import time as _time

        from . import host_batch
        from . import sr25519 as sr

        t0 = _time.perf_counter()
        n = len(self._pubkeys)
        # Routing: with the native engine, the host path is the same
        # one-MSM RLC pipeline as ed25519 (merlin challenges batched in
        # C, then verify_quads), so the ed25519 host/device crossover
        # applies. Without it the host is sequential pure Python
        # (~30 ms/sig) and the device wins from a handful of lanes.
        # COMETBFT_TPU_SR_HOST=1 is the explicit dead-tunnel escape.
        native = host_batch.available()
        host_cut = host_batch_threshold() if native else self.HOST_THRESHOLD
        if n < host_cut or _os.environ.get("COMETBFT_TPU_SR_HOST") == "1":
            bitmap = None
            if native:
                bitmap = host_batch.verify_quads(
                    sr.verification_encs_batch(
                        self._pubkeys, self._msgs, self._sigs
                    )
                )
            if bitmap is None:
                bitmap = [
                    sr.verify(p, m, s)
                    for p, m, s in zip(
                        self._pubkeys, self._msgs, self._sigs
                    )
                ]
            libmetrics.observe_verify_phase(
                "fallback", "sr25519-host", _time.perf_counter() - t0, n
            )
            _observe("sr25519-host", t0, n)
            return all(bitmap), bitmap
        from ..ops import verify as ov

        parts = sr.verification_encs_batch(
            self._pubkeys, self._msgs, self._sigs
        )
        buf, host_ok = ov.pack_parts(parts)
        # The expanded-point cache is keyed by the edwards A encoding, so
        # sr25519 validators (converted ristretto points) share the same
        # arena as ed25519 pubkeys.
        a_keys = [p[0] if p is not None else b"" for p in parts]
        t1 = _time.perf_counter()
        libmetrics.observe_verify_phase("pack", "sr25519-tpu", t1 - t0, n)
        done = ov.verify_prepacked(buf, a_keys, n)
        t2 = _time.perf_counter()
        libmetrics.observe_verify_phase("dispatch", "sr25519-tpu", t2 - t1, n)
        device_ok = done()
        libmetrics.observe_verify_phase(
            "readback", "sr25519-tpu", _time.perf_counter() - t2, n
        )
        valid = device_ok & host_ok
        _observe("sr25519-tpu", t0, n)
        return bool(valid.all()), list(np.asarray(valid, bool))


class MixedBatchVerifier(BatchVerifier):
    """One verifier for a heterogeneous (ed25519 + sr25519) lane set.

    Both schemes decompose to the same quadruple (A_edwards, R_edwards,
    s, k) and differ only in challenge derivation (SHA-512 vs merlin
    STROBE — both computed off-device), so a mixed batch is ONE
    cofactored device launch, or ONE host RLC MSM. The reference cannot
    batch mixed sets at all: CreateBatchVerifier keys off a single type
    and verifyCommitBatch falls back to per-signature verification
    (types/validation.go:170-176); here a mixed commit stays batched.
    """

    def __init__(self) -> None:
        self._types: list[str] = []
        self._pubkeys: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, pub_key, msg: bytes, signature: bytes) -> None:
        t = getattr(pub_key, "type", None)
        if t not in _BATCH_BACKENDS:
            raise TypeError(f"unsupported key type for batching: {t!r}")
        self._types.append(t)
        self._pubkeys.append(pub_key.data)
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(signature))

    def __len__(self) -> int:
        return len(self._pubkeys)

    def _ed_lane_idxs(self) -> list[int]:
        """ed25519 lanes passing the length admission; S-canonicity and
        A/R decodability are decided downstream (native packer / MSM
        engine / device kernel), exactly like the pure ed25519 paths."""
        return [
            i
            for i, t in enumerate(self._types)
            if t == keys.ED25519_KEY_TYPE
            and len(self._pubkeys[i]) == 32
            and len(self._sigs[i]) == 64
        ]

    def _ed_knegs(self, ed_idx: list[int]):
        """(kneg_rows bytes, s_ok) from the native fused SHA-512 packer,
        or None when the toolchain is absent."""
        from . import host_batch

        recs = b"".join(self._pubkeys[i] + self._sigs[i] for i in ed_idx)
        offs = [0]
        for i in ed_idx:
            offs.append(offs[-1] + len(self._msgs[i]))
        return host_batch.pack_challenges(
            recs, b"".join(self._msgs[i] for i in ed_idx), offs,
            len(ed_idx),
        )

    def _sr_quads(self, out: list) -> list[int]:
        """Scatter sr25519 lane quads into ``out``; returns the sr lane
        indices. The ONE home of sr admission + scatter, shared by the
        host (_quads) and device (_pack_rows) paths."""
        from . import sr25519 as sr

        sr_idx = [i for i, t in enumerate(self._types) if t == "sr25519"]
        if sr_idx:
            sq = sr.verification_encs_batch(
                [self._pubkeys[i] for i in sr_idx],
                [self._msgs[i] for i in sr_idx],
                [self._sigs[i] for i in sr_idx],
            )
            for j, i in enumerate(sr_idx):
                out[i] = sq[j]
        return sr_idx

    def _quads(self) -> list:
        """Per-lane (A_enc, R_enc, s, k), challenges batched per scheme
        through the native engine (merlin STROBE for sr25519, fused
        SHA-512 for ed25519); None marks a structurally invalid lane."""
        from . import ed25519_ref as ref

        n = len(self._pubkeys)
        quads: list = [None] * n
        self._sr_quads(quads)
        ed_idx = self._ed_lane_idxs()
        if not ed_idx:
            return quads
        L = ref.L
        packed = self._ed_knegs(ed_idx)
        if packed is not None:
            kneg_rows, s_ok = packed
            for j, i in enumerate(ed_idx):
                if not s_ok[j]:
                    continue
                sig = self._sigs[i]
                kneg = int.from_bytes(
                    kneg_rows[32 * j : 32 * j + 32], "little"
                )
                quads[i] = (
                    self._pubkeys[i],
                    sig[:32],
                    int.from_bytes(sig[32:], "little"),
                    (L - kneg) % L,
                )
            return quads
        for i in ed_idx:  # toolchain-less: per-lane Python challenge
            pk, sig = self._pubkeys[i], self._sigs[i]
            s = int.from_bytes(sig[32:], "little")
            if s >= L:
                continue  # S must be canonical even under ZIP-215
            k = ref.challenge_scalar(sig[:32], pk, self._msgs[i])
            quads[i] = (pk, sig[:32], s, k)
        return quads

    _ZERO_ROW = bytes(128)

    def _pack_rows(self) -> tuple[np.ndarray, np.ndarray, list]:
        """(buf (128, n), host_ok, a_keys): the device wire rows
        A|R|S|kneg, challenges batched per scheme through the native
        engine (fused SHA-512 packer for ed25519, STROBE for sr25519) —
        no per-lane Python bigints on the happy path. Row layout lives
        in ops/verify.pack_part_row / pack_challenges."""
        from ..ops import verify as ov
        from . import host_batch

        if not host_batch.available():
            # toolchain-less: build everything through the shared quad
            # packer (one Python challenge loop lives in _quads) —
            # checked FIRST so the ed record/message blobs aren't joined
            # just to learn pack_challenges must return None
            quads = self._quads()
            buf, host_ok = ov.pack_parts(quads)
            a_keys = [q[0] if q is not None else b"" for q in quads]
            return buf, host_ok, a_keys
        n = len(self._pubkeys)
        rows: list = [None] * n
        a_keys: list = [b""] * n
        sq: list = [None] * n
        for i in self._sr_quads(sq):
            q = sq[i]
            if q is None:
                continue
            rows[i] = ov.pack_part_row(*q)
            a_keys[i] = bytes(q[0])
        ed_idx = self._ed_lane_idxs()
        packed = self._ed_knegs(ed_idx) if ed_idx else None
        if ed_idx and packed is None:  # engine vanished mid-flight
            quads = self._quads()
            buf, host_ok = ov.pack_parts(quads)
            return buf, host_ok, [
                q[0] if q is not None else b"" for q in quads
            ]
        if ed_idx:
            kneg_rows, s_ok = packed
            for j, i in enumerate(ed_idx):
                if not s_ok[j]:
                    continue
                # raw-bytes row pk|R|S|kneg: byte-identical to
                # pack_part_row's layout (sig is R||S on the wire, kneg
                # from the native packer) — pinned by
                # test_mixed_row_assembly_matches_pack_part_row
                rows[i] = (
                    self._pubkeys[i]
                    + self._sigs[i]
                    + kneg_rows[32 * j : 32 * j + 32]
                )
                a_keys[i] = self._pubkeys[i]
        host_ok = np.array([r is not None for r in rows], bool)
        blob = b"".join(
            r if r is not None else self._ZERO_ROW for r in rows
        )
        buf = np.ascontiguousarray(
            np.frombuffer(blob, np.uint8).reshape(n, 128).T
        )
        return buf, host_ok, a_keys

    def verify(self) -> tuple[bool, list[bool]]:
        import os as _os
        import time as _time

        from . import host_batch

        t0 = _time.perf_counter()
        n = len(self._pubkeys)
        native = host_batch.available()
        if native:
            host_cut = host_batch_threshold()
        else:
            # Toolchain-less host cost is dominated by pure-Python
            # sr25519 verifies (~30 ms/sig); ed25519 lanes verify via
            # OpenSSL in ~50 us. The tiny sr cutoff applies only when
            # sr lanes actually dominate — an ed-heavy mixed batch
            # keeps the ed crossover.
            n_sr = sum(1 for t in self._types if t == "sr25519")
            host_cut = (
                Sr25519BatchVerifier.HOST_THRESHOLD
                if n_sr >= Sr25519BatchVerifier.HOST_THRESHOLD
                else host_batch_threshold()
            )
        if n < host_cut or _os.environ.get("COMETBFT_TPU_SR_HOST") == "1":
            bitmap = host_batch.verify_quads(self._quads()) if native \
                else None
            if bitmap is None:
                from .sr25519 import verify as sr_verify

                bitmap = [
                    (
                        keys.Ed25519PubKey(pk).verify_signature(m, s)
                        if t == keys.ED25519_KEY_TYPE
                        else sr_verify(pk, m, s)
                    )
                    for t, pk, m, s in zip(
                        self._types, self._pubkeys, self._msgs, self._sigs
                    )
                ]
            libmetrics.observe_verify_phase(
                "fallback", "mixed-host", _time.perf_counter() - t0, n
            )
            _observe("mixed-host", t0, n)
            return all(bitmap), list(bitmap)
        from ..ops import verify as ov

        buf, host_ok, a_keys = self._pack_rows()
        t1 = _time.perf_counter()
        libmetrics.observe_verify_phase("pack", "mixed-tpu", t1 - t0, n)
        done = ov.verify_prepacked(buf, a_keys, n)
        t2 = _time.perf_counter()
        libmetrics.observe_verify_phase("dispatch", "mixed-tpu", t2 - t1, n)
        device_ok = done()
        libmetrics.observe_verify_phase(
            "readback", "mixed-tpu", _time.perf_counter() - t2, n
        )
        valid = device_ok & host_ok
        _observe("mixed-tpu", t0, n)
        return bool(valid.all()), list(np.asarray(valid, bool))


_BATCH_BACKENDS: dict[str, type] = {
    keys.ED25519_KEY_TYPE: Ed25519BatchVerifier,
    "sr25519": Sr25519BatchVerifier,
}


def supports_commit_batch(validator_set) -> bool:
    """True when every key type in the set has a batch backend (a mixed
    set rides MixedBatchVerifier)."""
    vals = getattr(validator_set, "validators", [])
    return bool(vals) and all(
        getattr(v.pub_key, "type", None) in _BATCH_BACKENDS for v in vals
    )


def create_commit_batch_verifier(validator_set) -> BatchVerifier:
    """Batch verifier for a (possibly heterogeneous) validator set.

    Homogeneous sets get their scheme's dedicated backend (ed25519 keeps
    the fused native happy path); mixed sets get MixedBatchVerifier —
    one launch where the reference falls back to per-signature verifies.
    """
    types = {
        getattr(v.pub_key, "type", None)
        for v in getattr(validator_set, "validators", [])
    }
    if len(types) == 1:
        backend = _BATCH_BACKENDS.get(next(iter(types)))
        if backend is not None:
            return backend()
    if types and all(t in _BATCH_BACKENDS for t in types):
        return MixedBatchVerifier()
    raise ValueError(
        f"batch verification unsupported for key types {sorted(types)!r}"
    )


def _observe(backend: str, t0: float, n: int) -> None:
    """Record end-to-end batch-verify latency/volume. Routed through
    node_metrics() like every other instrumentation site: the running
    node's registry when one is up, a throwaway sink otherwise. The
    same measurement feeds the adaptive host/device crossover — the
    phase metrics and the routing decision see one set of timings."""
    import time as _time

    dt = _time.perf_counter() - t0
    m = libmetrics.node_metrics()
    m.verify_batch_seconds.labels(backend).observe(dt)
    m.verify_batch_sigs.labels(backend).inc(n)
    # Only ed25519 lanes feed the crossover: its linear host/device
    # model is fit for ONE kernel's cost profile, and an sr25519 or
    # mixed sample (pure-Python host sr25519 runs ~1000x the ed25519
    # per-lane cost when the native engine is absent) would poison the
    # shared fit and misroute every verifier.
    if backend == "ed25519-host":
        note_host_window(n, dt)
    elif backend == "ed25519-tpu":
        note_device_window(n, dt)


def prestage_validators(validator_set) -> int:
    """Warm the device pubkey arena for a validator set's ed25519 keys.

    The FSM calls this at enter-new-round so steady-state commit/vote
    verification ships only R|S|k (ops/verify.prestage_pubkeys; the
    device analog of the reference's expanded-pubkey LRU being hot,
    crypto/ed25519/ed25519.go:31,56). sr25519 keys are skipped: their
    arena key is the CONVERTED edwards encoding, and the conversion
    itself is the expensive host step — converting eagerly per round
    would cost more than the build it saves.
    """
    keys_bytes = [
        v.pub_key.data
        for v in getattr(validator_set, "validators", [])
        if getattr(v.pub_key, "type", None) == keys.ED25519_KEY_TYPE
    ]
    if not keys_bytes:
        return 0
    from ..ops import verify as ov

    return ov.prestage_pubkeys(keys_bytes)


def supports_batch_verifier(pub_key) -> bool:
    return getattr(pub_key, "type", None) in _BATCH_BACKENDS


def create_batch_verifier(pub_key) -> BatchVerifier:
    """Instantiate the batch backend for ``pub_key``'s type.

    Raises ValueError for unsupported types — callers fall back to
    single-signature verification (types/validation.go:170-176 semantics).
    """
    backend = _BATCH_BACKENDS.get(getattr(pub_key, "type", None))
    if backend is None:
        raise ValueError(
            f"batch verification unsupported for key type "
            f"{getattr(pub_key, 'type', None)!r}"
        )
    return backend()
