"""Batch-verification dispatch: key type -> batch verifier backend.

Reference surface: crypto/crypto.go:45-54 (BatchVerifier interface) and
crypto/batch/batch.go:11-32 (CreateBatchVerifier / SupportsBatchVerifier).

The ed25519 backend accumulates (pubkey, msg, sig) triples on host and
verifies them in ONE TPU kernel launch (ops/verify.py) — the engine-wide
hot path: commit verification (types/validation.go:153-257), light-client
replay, blocksync catch-up, and the vote-ingest micro-batching window all
come through this interface.
"""

from __future__ import annotations

import numpy as np

from . import keys
from .keys import Ed25519PubKey


class BatchVerifier:
    """Add/Verify contract of crypto.BatchVerifier (crypto/crypto.go:45-54).

    ``verify`` returns (all_valid, per_signature_validity); per-lane results
    let callers attribute failures without the second single-verify pass the
    reference falls back to (types/validation.go:243-250).
    """

    def add(self, pub_key, msg: bytes, signature: bytes) -> None:
        raise NotImplementedError

    def verify(self) -> tuple[bool, list[bool]]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


# Below this size the host finishes before the device round trip's fixed
# latency floor (~70 ms through the relay) — measured crossover ~768
# lanes on a v5e against the old sequential-OpenSSL host path. The host
# path is now the native RLC batch verifier (crypto/host_batch.py,
# ~1.5-3x sequential OpenSSL), which pushes the true crossover HIGHER;
# the device side also got faster (expanded-pubkey arena, pre-staging,
# donated buffers). The reference has the inverse constant
# (batchVerifyThreshold, types/validation.go:13-17: below it batching
# isn't worth setup).
#
# Derivation chain, most authoritative first:
#   1. COMETBFT_TPU_HOST_THRESHOLD env (operator override / driver);
#   2. the last chip-measured crossover recorded by bench.py's
#      9_device_floor breakdown (BENCH_CHIP_TABLE.json, only trusted
#      when measured on an accelerator backend);
#   3. the static 768 fallback.
_DEFAULT_HOST_BATCH_THRESHOLD = 768


def _derive_host_threshold() -> int:
    import json
    import os

    env = os.environ.get("COMETBFT_TPU_HOST_THRESHOLD")
    if env:
        try:
            return max(2, int(env))
        except ValueError:
            pass
    # repo-root anchored (bench.py writes it there): a CWD-relative open
    # would silently miss the table for any process not started in the
    # repo root — and trust an unrelated same-named file that is.
    table_path = os.environ.get("COMETBFT_TPU_CHIP_TABLE") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        "BENCH_CHIP_TABLE.json",
    )
    try:
        with open(table_path) as f:
            table = json.load(f)
        if table.get("measured_on_accelerator"):
            for row in table.get("table", []):
                if row.get("config") == "9_device_floor":
                    xo = row.get("measured_crossover_lanes")
                    if isinstance(xo, int) and xo >= 2:
                        return xo
                    rows = row.get("rows") or []
                    max_n = max(
                        (r.get("n", 0) for r in rows), default=0
                    )
                    if xo is None and max_n >= 2048:
                        # The chip WAS measured, the sweep covered real
                        # production sizes, and the device never beat
                        # the host: route everything host rather than
                        # trusting the static guess (round-4 verdict
                        # task 4 — 768 can be wrong both ways). A tiny
                        # or truncated sweep (max n < 2048) must NOT
                        # poison the knob.
                        return 1 << 30
    except (OSError, ValueError):
        pass
    return _DEFAULT_HOST_BATCH_THRESHOLD


HOST_BATCH_THRESHOLD = _derive_host_threshold()


class Ed25519BatchVerifier(BatchVerifier):
    """TPU-backed ed25519 batch verification with a host small-batch path."""

    def __init__(self) -> None:
        self._pubkeys: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, pub_key, msg: bytes, signature: bytes) -> None:
        if not isinstance(pub_key, Ed25519PubKey):
            raise TypeError("Ed25519BatchVerifier requires ed25519 keys")
        self._pubkeys.append(pub_key.data)
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(signature))

    def __len__(self) -> int:
        return len(self._pubkeys)

    def verify(self) -> tuple[bool, list[bool]]:
        import time as _time

        t0 = _time.perf_counter()
        if len(self._pubkeys) < HOST_BATCH_THRESHOLD:
            # Native RLC batch (one multiscalar mult, the voi algorithm);
            # falls back to sequential OpenSSL inside when the native
            # engine can't build.
            from . import host_batch

            bitmap = host_batch.verify_many(
                self._pubkeys, self._msgs, self._sigs
            )
            _observe("ed25519-host", t0, len(bitmap))
            return all(bitmap), bitmap
        from ..ops import verify as ov

        ok_all, bitmap = ov.verify_batch(self._pubkeys, self._msgs, self._sigs)
        _observe("ed25519-tpu", t0, len(self._pubkeys))
        return ok_all, list(np.asarray(bitmap, bool))


class Sr25519BatchVerifier(BatchVerifier):
    """sr25519 batch verification on the SAME TPU kernel as ed25519.

    The merlin challenge k is computed on host per lane
    (crypto/sr25519.verification_parts); the cofactored curve equation
    [8](sB - kA - R) == O then decides ristretto equality exactly
    (ristretto quotients out the torsion the cofactor clears). Reference
    surface: crypto/sr25519/batch.go:14-46.
    """

    # Without the native engine the host fallback is sequential pure
    # Python (~30 ms/sig, 6 scalar mults): the device wins from a
    # handful of lanes. WITH it, the host runs the same one-MSM RLC
    # path as ed25519 (native merlin challenges + verify_quads), so the
    # ed25519 crossover applies.
    HOST_THRESHOLD = 4

    def __init__(self) -> None:
        self._pubkeys: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, pub_key, msg: bytes, signature: bytes) -> None:
        from .sr25519 import Sr25519PubKey

        if not isinstance(pub_key, Sr25519PubKey):
            raise TypeError("Sr25519BatchVerifier requires sr25519 keys")
        self._pubkeys.append(pub_key.data)
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(signature))

    def __len__(self) -> int:
        return len(self._pubkeys)

    def verify(self) -> tuple[bool, list[bool]]:
        import os as _os
        import time as _time

        from . import host_batch
        from . import sr25519 as sr

        t0 = _time.perf_counter()
        n = len(self._pubkeys)
        # Routing: with the native engine, the host path is the same
        # one-MSM RLC pipeline as ed25519 (merlin challenges batched in
        # C, then verify_quads), so the ed25519 host/device crossover
        # applies. Without it the host is sequential pure Python
        # (~30 ms/sig) and the device wins from a handful of lanes.
        # COMETBFT_TPU_SR_HOST=1 is the explicit dead-tunnel escape.
        native = host_batch.available()
        host_cut = HOST_BATCH_THRESHOLD if native else self.HOST_THRESHOLD
        if n < host_cut or _os.environ.get("COMETBFT_TPU_SR_HOST") == "1":
            bitmap = None
            if native:
                bitmap = host_batch.verify_quads(
                    sr.verification_encs_batch(
                        self._pubkeys, self._msgs, self._sigs
                    )
                )
            if bitmap is None:
                bitmap = [
                    sr.verify(p, m, s)
                    for p, m, s in zip(
                        self._pubkeys, self._msgs, self._sigs
                    )
                ]
            _observe("sr25519-host", t0, n)
            return all(bitmap), bitmap
        from ..ops import verify as ov

        parts = sr.verification_encs_batch(
            self._pubkeys, self._msgs, self._sigs
        )
        buf, host_ok = ov.pack_parts(parts)
        # The expanded-point cache is keyed by the edwards A encoding, so
        # sr25519 validators (converted ristretto points) share the same
        # arena as ed25519 pubkeys.
        a_keys = [p[0] if p is not None else b"" for p in parts]
        device_ok = ov.verify_prepacked(buf, a_keys, n)()
        valid = device_ok & host_ok
        _observe("sr25519-tpu", t0, n)
        return bool(valid.all()), list(np.asarray(valid, bool))


_BATCH_BACKENDS: dict[str, type] = {
    keys.ED25519_KEY_TYPE: Ed25519BatchVerifier,
    "sr25519": Sr25519BatchVerifier,
}


def _observe(backend: str, t0: float, n: int) -> None:
    """Record batch-verify latency/volume when a node's metrics are live."""
    import time as _time

    from ..libs import metrics as libmetrics

    m = libmetrics.DEFAULT_NODE_METRICS
    if m is not None:
        m.verify_batch_seconds.labels(backend).observe(
            _time.perf_counter() - t0
        )
        m.verify_batch_sigs.labels(backend).inc(n)


def prestage_validators(validator_set) -> int:
    """Warm the device pubkey arena for a validator set's ed25519 keys.

    The FSM calls this at enter-new-round so steady-state commit/vote
    verification ships only R|S|k (ops/verify.prestage_pubkeys; the
    device analog of the reference's expanded-pubkey LRU being hot,
    crypto/ed25519/ed25519.go:31,56). sr25519 keys are skipped: their
    arena key is the CONVERTED edwards encoding, and the conversion
    itself is the expensive host step — converting eagerly per round
    would cost more than the build it saves.
    """
    keys_bytes = [
        v.pub_key.data
        for v in getattr(validator_set, "validators", [])
        if getattr(v.pub_key, "type", None) == keys.ED25519_KEY_TYPE
    ]
    if not keys_bytes:
        return 0
    from ..ops import verify as ov

    return ov.prestage_pubkeys(keys_bytes)


def supports_batch_verifier(pub_key) -> bool:
    return getattr(pub_key, "type", None) in _BATCH_BACKENDS


def create_batch_verifier(pub_key) -> BatchVerifier:
    """Instantiate the batch backend for ``pub_key``'s type.

    Raises ValueError for unsupported types — callers fall back to
    single-signature verification (types/validation.go:170-176 semantics).
    """
    backend = _BATCH_BACKENDS.get(getattr(pub_key, "type", None))
    if backend is None:
        raise ValueError(
            f"batch verification unsupported for key type "
            f"{getattr(pub_key, 'type', None)!r}"
        )
    return backend()
