"""Cross-caller hash coalescer: the device-resident SHA-256 plane.

The verify coalescer (crypto/coalesce.py) proved the shape: concurrent
single-item callers submit lanes to per-submit tickets, an executor
thread coalesces them into fixed-shape-bucket device micro-batches, and
windows double-buffer so the host pack of window N+1 overlaps the
device execute of window N. This module is the SAME machinery for
SHA-256 — the node's OTHER ubiquitous crypto primitive
(arXiv:2407.03511: hashing dominates blockchain data paths):

* concurrent callers — mempool CheckTx tx-key hashing
  (mempool/clist_mempool.py TxKey), PartSet leaf hashing on both the
  build and the gossip-verify side (types/part_set.py via
  crypto/merkle.py), and block/data/header merkle levels
  (types/block.py) — submit message lanes and block on a ticket;
* the executor flushes windows by size (COMETBFT_TPU_HASH_MAX_LANES)
  or deadline (COMETBFT_TPU_HASH_WINDOW_US), splits each window's
  lanes by SHA block bucket (a 55-byte tx key must not pad to a
  64 KiB part's block count), and launches each bucket through
  ops/sha256's bucketed kernel;
* each block bucket carries its OWN adaptive host/device crossover
  (crypto/batch.AdaptiveCrossover instances fed per-bucket): the lane
  count where the device wins a window of 1-block messages is very
  different from where it wins 1024-block part hashing, and the live
  fit learns both separately;
* host fallback is clean AND cheap: unlike ed25519 (where a host
  window still wins as one RLC MSM), SHA-256 has no host batch trick —
  so the routed helpers fall back to plain ``hashlib`` WITHOUT
  queueing whenever no device could take the window (device-less
  container, sub-floor messages, or a batch below every bucket's
  device cut), and the flush deadline is work-proportional. The one
  deliberately OPTIMISTIC path is single-message routing
  (``hash_bytes`` at >= 1 KiB): a storm of concurrent 1-lane callers
  can only form a winning window if each queues before knowing the
  others exist, so an uncontended large single pays a bounded thread
  handoff (tens of us against an enclosing RPC/gossip operation that
  costs milliseconds) — the same trade the verify coalescer makes for
  lone votes;
* digests are bit-identical to ``hashlib.sha256`` everywhere (the
  kernel is fuzz-pinned across every padding boundary), so routing can
  never change a hash — only where it is computed.

Locking: ``crypto.hashplane._mtx`` guards the pending queue — the
flush path pops a window under it and releases it before pack,
dispatch, the materializing readback, and ticket resolution;
``crypto.hashplane._rb_mtx`` guards only the executor->drain handoff
(dispatched windows materialize on a dedicated readback drain thread,
FIFO, so execute of window N+1 overlaps the d2h of window N). Neither
blocks on the device while held and neither acquires an engine mutex
(asserted by tests/test_lint_graph.py, same contract as the verify
coalescer's locks).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import deque

from ..libs import devledger as libdevledger
from ..libs import health as libhealth
from ..libs import metrics as libmetrics
from ..libs import sync as libsync
from ..libs import trace as libtrace
from ..libs.service import BaseService, ServiceError
from .coalesce import (
    _DEFAULT_MAX_INFLIGHT,
    _env_int,
    _env_opt_int,
    deadline_remaining,
)

# Deadline window before a sub-size window flushes anyway; same scale
# and rationale as the verify coalescer's window.
_DEFAULT_WINDOW_US = 500
# Lanes that trigger an immediate size flush (and the per-window cap).
# Hash lanes are cheaper to stage than signature lanes, but a window
# splits into per-block-bucket launches, so the cap bounds the SUM.
_DEFAULT_MAX_LANES = 2048
# Ticket wait bound for the routed helpers; like the verify bound it is
# ALSO the worst-case stall a wedged device can inflict on a caller
# that holds an engine mutex (PartSet verify under consensus.state).
_RESULT_TIMEOUT_S = 5.0
# Breaker cooldown once a ticket outlives the full bound (see
# crypto/coalesce._TRIP_COOLDOWN_S — identical semantics).
_TRIP_COOLDOWN_S = 30.0

# Routed-helper floors: below these the host hashlib call is so cheap
# that even a perfectly coalesced device window cannot recover the
# ticket round trip, so the helpers skip the queue entirely.
#   hash_bytes: single messages (mempool tx keys, PartSet leaf verify)
#   route only at >= this many bytes;
_SUM_ROUTE_MIN_BYTES = 1024
#   hash_many / merkle levels: batches route only when the window
#   carries at least this much total padded-block work.
_ROUTE_MIN_BLOCKS = 64

# Seed for the per-bucket device cutover while its adaptive fit is
# uncalibrated: device wins once a window carries ~this many total
# SHA blocks, so the lane cutover for bucket B is ~SEED/B (clamped).
_SEED_DEVICE_BLOCKS = 2048

# The deadline a window waits for more lanes is PROPORTIONAL to the
# host cost of the work already pending (capped by the window knob): a
# lone 2 KiB tx key (~1 us of hashlib) must not sit out a 500 us window
# to discover nobody else was hashing — that would be a 100x+ latency
# regression on uncontended paths (serial blocksync part verifies, a
# single RPC CheckTx) — while a 64-part PartSet build (~15 ms host) can
# afford the full window for siblings to pile in. Under a real storm
# concurrent submits are already queued when the executor collects, so
# a short budget still coalesces everything actually concurrent; the
# budget only bounds how long the plane gambles on FUTURE arrivals.
_HOST_S_PER_BLOCK = 25e-9  # single-core hashlib cost per 64-byte block
_WAIT_COST_FACTOR = 2.0  # wait at most ~2x the pending work's host cost


class HashplaneStoppedError(ServiceError):
    """submit() after the drain began — callers fall back to hashlib."""


class _Ticket:
    """One submit()'s pending digests; resolved exactly once."""

    __slots__ = (
        "n", "blocks", "caller", "t_submit", "_done", "_digests", "_exc"
    )

    def __init__(self, n: int, blocks: int = 0, caller: int = 0):
        self.n = n
        # caller class (libs/devledger enum) captured at submit — the
        # device-time ledger's attribution key
        self.caller = caller
        # total padded SHA blocks across this submit's lanes — the
        # executor's work-proportional deadline budget reads it
        self.blocks = blocks
        self.t_submit = time.perf_counter()
        self._done = threading.Event()
        self._digests: list[bytes] | None = None
        self._exc: BaseException | None = None

    def resolve(self, digests) -> None:
        self._digests = list(digests)
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None) -> list[bytes]:
        """Block for this submit's digests. Callers may hold engine
        mutexes here — the wait is bounded by the flush-window deadline
        plus one launch, and the executor acquires no engine mutex
        (tests/test_lint_graph.py pins crypto.hashplane._mtx edge-free),
        so no lock cycle can form through it."""
        ok = self._done.wait(timeout)  # cometlint: disable=CLNT009 -- bounded coalescer wait: resolved within the flush-window deadline + one launch by the executor thread, which acquires no engine mutex (asserted leaf in test_lint_graph); replaces an equal-or-longer inline host hash under the same caller locks only when routing said the device wins
        if not ok:
            raise TimeoutError(
                f"coalesced hash not resolved within {timeout}s "
                f"({self.n} lanes)"
            )
        if self._exc is not None:
            raise self._exc
        return list(self._digests or [])


class _Inflight:
    """A window with dispatched-but-unmaterialized device buckets."""

    __slots__ = (
        "finishes", "out", "groups", "lanes", "reason", "device",
        "t_launch", "host_s",
    )

    def __init__(self, finishes, out, groups, lanes, reason,
                 t_launch=0.0, host_s=0.0):
        # [(materializer, window_indices, block_bucket, prep_s, lanes)]
        self.finishes = finishes
        self.out = out  # window-ordered digest slots (host buckets filled)
        self.groups = groups  # [(ticket, msgs)] — the hashlib rescue wire
        self.lanes = lanes
        self.reason = reason
        self.device = bool(finishes)
        # window pop time (queue-wait anchor) and the host-bucket
        # fallback seconds already spent at launch — _finish adds the
        # device buckets' prep+readback for the window execute total
        self.t_launch = t_launch
        self.host_s = host_s


class _BucketCrossover:
    """Per-block-bucket adaptive host/device lane cutover.

    One crypto/batch.AdaptiveCrossover per SHA block bucket, fed from
    the plane's own window timings: ``threshold(bucket)`` answers "at
    how many lanes does a window of THIS message size win on device".
    Until a bucket is calibrated the seed curve answers
    (~:data:`_SEED_DEVICE_BLOCKS` total blocks); adaptation follows the
    same gate as the verify crossover (env force / accelerator-only).
    """

    def __init__(self) -> None:
        self._mtx = libsync.Mutex("crypto.hashplane._crossover")
        self._fits: dict[int, object] = {}

    def _fit(self, bucket: int):
        from . import batch as crypto_batch

        with self._mtx:
            xo = self._fits.get(bucket)
            if xo is None:
                xo = crypto_batch.AdaptiveCrossover()
                self._fits[bucket] = xo
            return xo

    def note_host(self, bucket: int, lanes: int, seconds: float) -> None:
        from . import batch as crypto_batch

        if crypto_batch._adaptive_enabled():
            self._fit(bucket).observe_host(lanes, seconds)

    def note_device(self, bucket: int, lanes: int, seconds: float) -> None:
        from . import batch as crypto_batch

        if crypto_batch._adaptive_enabled():
            self._fit(bucket).observe_device(lanes, seconds)

    def threshold(self, bucket: int) -> int:
        seed = max(2, _SEED_DEVICE_BLOCKS // max(1, bucket))
        from . import batch as crypto_batch

        if not crypto_batch._adaptive_enabled():
            return seed
        t = self._fit(bucket).threshold()
        return seed if t is None else t


CROSSOVER = _BucketCrossover()


class HashCoalescer(BaseService):
    """Background hash executor coalescing concurrent digest callers.

    ``submit`` enqueues message lanes and returns a ticket; the
    executor thread flushes windows by size or deadline, splits each
    window by SHA block bucket, and double-buffers device launches
    (the pack of window N+1 overlaps the execute of window N). See the
    module docstring for the full design.
    """

    _JOIN_TIMEOUT_S = 10.0

    def __init__(
        self,
        window_us: int | None = None,
        max_lanes: int | None = None,
        min_device_lanes: int | None = None,
        device: bool | None = None,
        max_inflight: int | None = None,
        logger=None,
    ):
        super().__init__("HashCoalescer", logger)
        self.window_s = (
            window_us
            if window_us is not None
            else _env_int("COMETBFT_TPU_HASH_WINDOW_US", _DEFAULT_WINDOW_US)
        ) / 1e6
        from ..ops.sha256 import MAX_LANES as _kernel_cap

        # clamped to the kernel's per-launch cap: an oversized knob
        # would make every size-flushed window's launch raise and fall
        # back — the device path would silently never engage
        self.max_lanes = min(
            _kernel_cap,
            max(
                1,
                max_lanes
                if max_lanes is not None
                else _env_int(
                    "COMETBFT_TPU_HASH_MAX_LANES", _DEFAULT_MAX_LANES
                ),
            ),
        )
        # None = defer to the per-bucket crossover at flush time
        self.min_device_lanes: int | None = (
            min_device_lanes
            if min_device_lanes is not None
            else _env_opt_int("COMETBFT_TPU_HASH_MIN_DEVICE_LANES")
        )
        # None = defer to the process-wide accelerator probe; True/False
        # pin (tests, bench, the dead-tunnel host branch).
        self._device = device
        self._mtx = libsync.Mutex("crypto.hashplane._mtx")
        self._cv = libsync.Condition(self._mtx, name="crypto.hashplane._mtx")
        self._pending: deque[tuple] = deque()  # (ticket, msgs)
        self._pending_lanes = 0
        self._pending_blocks = 0  # padded-block sum: the wait budget
        # lockfree: drain gate — locked writes, advisory fast-path reads; a stale read routes one submit to the host fallback
        self._draining = False
        # lock-free running flag, same rationale as the verify coalescer
        # lockfree: locked writes, advisory fast-path reads (see crypto/coalesce.py)
        self._accepting = False
        # lockfree: breaker deadline — locked writes, racy reads re-check under the lock before re-arming
        self._tripped_until = 0.0
        self._thread: threading.Thread | None = None
        # executor-owned mirrors so the rescue paths can always reach a
        # popped window's tickets (see crypto/coalesce.py)
        # lockfree: flight ring — executor appends, drain thread removes, rescues snapshot via tuple(); GIL-atomic list ops, single writer per end
        self._inflights: list[_Inflight] = []
        self._staging: list[tuple] | None = None
        # readback drain handoff, mirroring the verify coalescer's:
        # dispatched windows materialize on a dedicated drain thread in
        # submission order while the executor packs + dispatches the
        # next window; the depth bound keeps the pipeline bounded.
        self.max_inflight = max(
            1,
            max_inflight
            if max_inflight is not None
            else _env_int(
                "COMETBFT_TPU_HASH_INFLIGHT", _DEFAULT_MAX_INFLIGHT
            ),
        )
        self._rb_mtx = libsync.Mutex("crypto.hashplane._rb_mtx")
        self._rb_cv = libsync.Condition(
            self._rb_mtx, name="crypto.hashplane._rb_mtx"
        )
        self._readback: deque[_Inflight] = deque()
        self._rb_busy = 0
        self._rb_closed = False
        self._rb_alive = False
        self._rb_thread: threading.Thread | None = None
        self.windows = 0
        self.device_windows = 0
        self.tickets = 0

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        with self._mtx:
            self._draining = False
        with self._rb_mtx:
            self._rb_closed = False
            self._rb_alive = True
        rt = threading.Thread(
            target=self._drain_run, name="hash-readback", daemon=True
        )
        rt.start()
        # lockfree: start/stop lifecycle handle, written only by the thread driving the service transition
        self._rb_thread = rt
        t = threading.Thread(target=self._run, name="hash-plane", daemon=True)
        t.start()
        # lockfree: start/stop lifecycle handle, written only by the thread driving the service transition
        self._thread = t
        with self._mtx:
            self._accepting = True

    def on_stop(self) -> None:
        """Drain: every pending ticket is resolved before stop returns."""
        with self._mtx:
            self._draining = True
            self._accepting = False
            self._cv.notify_all()
        with self._rb_mtx:
            # wake an executor blocked at the in-flight depth bound
            self._rb_cv.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self._JOIN_TIMEOUT_S)
        rt = self._rb_thread
        if rt is not None and rt is not threading.current_thread():
            self._close_readback()
            rt.join(timeout=self._JOIN_TIMEOUT_S)
        # Safety net mirroring the verify coalescer's: host-resolve
        # anything a dead or wedged executor left behind; done() gates
        # make overlap with a still-alive executor benign.
        with self._mtx:
            leftovers, self._pending = self._pending, deque()
            self._pending_lanes = 0
            self._pending_blocks = 0
        for group in leftovers:
            self._resolve_group_host(group)
        for group in self._staging or ():
            self._resolve_group_host(group)
        for fl in tuple(self._inflights):
            self._rescue_inflight(fl)
            self._drop_inflight(fl)

    # -- submission --------------------------------------------------------

    def submit(self, msgs) -> _Ticket:
        """Queue message lanes; returns the ticket with their digests.
        Raises :class:`HashplaneStoppedError` once the drain began."""
        return self.submit_many([msgs])[0]

    def submit_many(self, groups) -> list[_Ticket]:
        """Batch-submit several lane groups as ONE queue transaction
        (one mutex hold, one executor wake-up) — a chunked oversized
        batch packs into consecutive windows without interleaving."""
        from ..ops.sha256 import n_blocks

        tickets: list[_Ticket] = []
        staged: list[tuple] = []
        cid = libdevledger.current_caller()
        for msgs in groups:
            blocks = 0
            try:
                blocks = sum(n_blocks(len(m)) for m in msgs)
            except TypeError:
                pass  # unsized lanes fail in _stage, per-ticket
            t = _Ticket(len(msgs), blocks, cid)
            tickets.append(t)
            if t.n == 0:
                t.resolve([])
            else:
                staged.append((t, msgs))
        if not staged:
            return tickets
        with self._mtx:
            if self._draining or not self._accepting:
                raise HashplaneStoppedError(self._name)
            for g in staged:
                self._pending.append(g)
                self._pending_lanes += g[0].n
                self._pending_blocks += g[0].blocks
            self.tickets += len(staged)
            self._cv.notify_all()
        return tickets

    def try_hash_many(self, msgs) -> list[bytes] | None:
        """submit + wait with a clean not-routed signal.

        Returns the per-lane digests, or None when the plane cannot
        serve the request (stopped, breaker cooldown, wait expired) —
        the caller then hashes on host, so routing never changes a
        digest. Oversized groups chunk into ``max_lanes`` tickets
        submitted as one batch. Waits honor the thread's
        crypto/coalesce.request_deadline budget; a deadline-capped
        expiry returns None WITHOUT tripping the breaker.
        """
        rem = deadline_remaining()
        if rem is not None and rem <= 0:
            return None
        if not self._claim_probe():
            return None
        n = len(msgs)
        if n <= self.max_lanes:
            groups = [msgs]
        else:
            groups = [
                msgs[i : i + self.max_lanes]
                for i in range(0, n, self.max_lanes)
            ]
        try:
            tickets = self.submit_many(groups)
        except ServiceError:
            return None
        digests: list[bytes] = []
        for ticket in tickets:
            wait_s = _RESULT_TIMEOUT_S
            capped = False
            rem = deadline_remaining()
            if rem is not None and rem < wait_s:
                wait_s, capped = max(rem, 0.0), True
            try:
                digests.extend(ticket.result(wait_s))
            except TimeoutError:
                # full-bound expiry = wedged executor evidence; trip the
                # cooldown breaker so subsequent callers fall back to
                # hashlib instantly (see crypto/coalesce.try_verify —
                # identical containment contract)
                if not capped:
                    self._trip()
                return None
            except Exception:
                return None
        self._rearm()
        return digests

    def batch_worth_routing(self, msgs) -> bool:
        """True when this batch ALONE can put at least one of its block
        buckets over that bucket's device cut (and carries the minimum
        total work). Single-caller batches (merkle levels, Data.hash)
        don't need cross-caller coalescing to win — one below every
        cut would deterministically host-hash inside the executor,
        paying two thread handoffs for a hashlib call the caller could
        run inline. Singles (:func:`hash_bytes`) stay optimistic: a
        storm of concurrent 1-lane callers can only form a winning
        window if each queues before knowing the others exist."""
        counts: dict[int, int] = {}
        total = 0
        from ..ops.sha256 import block_bucket, n_blocks

        for m in msgs:
            nb = n_blocks(len(m))
            total += nb
            bb = block_bucket(nb)
            counts[bb] = counts.get(bb, 0) + 1
        if total < _ROUTE_MIN_BLOCKS:
            return False
        return any(
            c >= self._device_cut(bb) for bb, c in counts.items()
        )

    def device_capable(self) -> bool:
        """Whether windows COULD take a device path at all. The routed
        helpers consult this before queueing: a coalesced host window
        has no batch win for SHA-256 (hashlib is already optimal), so
        on device-less containers callers must stay on plain hashlib
        with zero ticket round trips."""
        if self._device is not None:
            return self._device
        from ..libs.accel import accelerator_backend_live

        return accelerator_backend_live()

    def routable(self) -> bool:
        """Accepting submits and not inside a breaker cooldown. PURE
        query — never consumes the half-open probe."""
        return self._accepting and (
            self._tripped_until == 0.0
            or time.monotonic() >= self._tripped_until
        )

    def _claim_probe(self) -> bool:
        if self._tripped_until == 0.0:
            return True
        with self._mtx:
            if self._tripped_until == 0.0:
                return True
            if time.monotonic() < self._tripped_until:
                return False
            self._tripped_until = time.monotonic() + _TRIP_COOLDOWN_S
            return True

    def _rearm(self) -> None:
        if self._tripped_until == 0.0:
            return
        with self._mtx:
            self._tripped_until = 0.0
        libhealth.note_breaker_rearm()

    def _trip(self) -> None:
        """Unroute a wedged plane for one cooldown; queued groups hand
        to a hashlib rescue thread so no caller hangs behind a wedged
        executor. Feeds the SAME breaker health channel as the verify
        coalescer (EV_BREAKER ring rows + the wedged-coalescer
        watchdog): either plane wedging means the shared device path
        stalled, and it must page + capture a black-box bundle instead
        of failing over silently."""
        leftovers: deque | None = None
        with self._mtx:
            if self._draining or not self._accepting:
                return
            self._tripped_until = time.monotonic() + _TRIP_COOLDOWN_S
            if self._pending:
                leftovers, self._pending = self._pending, deque()
                self._pending_lanes = 0
                self._pending_blocks = 0
            self._cv.notify_all()
        if leftovers:
            groups = tuple(leftovers)
            threading.Thread(
                target=lambda: [
                    self._resolve_group_host(g) for g in groups
                ],
                name="hash-plane-rescue",
                daemon=True,
            ).start()
        # health hook: the wedged-coalescer watchdog converts this
        # notice into a trip + black-box bundle (no lock held here)
        libhealth.note_breaker_trip()
        if self.logger is not None:
            self.logger.error(
                "hash plane unresponsive; unrouted for cooldown",
                timeout_s=_RESULT_TIMEOUT_S,
                cooldown_s=_TRIP_COOLDOWN_S,
            )

    # -- the executor ------------------------------------------------------

    def _run(self) -> None:
        try:
            while True:
                try:
                    groups, lanes, reason = self._collect(block=True)
                    if groups:
                        self._staging = groups
                        handle = self._launch(groups, lanes, reason)
                        if handle is not None:
                            self._inflights.append(handle)
                            self._hand_to_drain(handle)
                        self._staging = None
                    if reason == "quit":
                        return
                except Exception:
                    # survive anything; rescue every slot a ticket can
                    # live in (staging + every drain-queue slot)
                    try:
                        import traceback

                        traceback.print_exc()
                    except Exception:
                        pass
                    staged, self._staging = self._staging, None
                    for group in staged or ():
                        self._resolve_group_host(group)
                    for fl in tuple(self._inflights):
                        self._rescue_inflight(fl)
                        self._drop_inflight(fl)
        finally:
            self._close_readback()
            rt = self._rb_thread
            if rt is not None and rt is not threading.current_thread():
                rt.join(timeout=self._JOIN_TIMEOUT_S)
            with self._mtx:
                self._accepting = False
                leftovers, self._pending = self._pending, deque()
                self._pending_lanes = 0
                self._pending_blocks = 0
            staged, self._staging = self._staging, None
            for group in staged or ():
                self._resolve_group_host(group)
            for group in leftovers:
                self._resolve_group_host(group)
            for fl in tuple(self._inflights):
                self._rescue_inflight(fl)
                self._drop_inflight(fl)

    # -- the readback drain (see crypto/coalesce.py — same design) ---------

    def _hand_to_drain(self, fl: _Inflight) -> None:
        handed = False
        with self._rb_mtx:
            if self._rb_alive and not self._rb_closed:
                self._readback.append(fl)
                handed = True
                self._rb_cv.notify_all()
                while (
                    self._rb_alive
                    and not self._rb_closed
                    and not self._draining
                    and len(self._readback) + self._rb_busy
                    >= self.max_inflight
                ):
                    self._rb_cv.wait(0.2)
        if not handed:
            self._finish(fl)
            self._drop_inflight(fl)

    def _close_readback(self) -> None:
        with self._rb_mtx:
            self._rb_closed = True
            self._rb_cv.notify_all()

    def _drain_run(self) -> None:
        """Materialize dispatched windows in submission order; a finish
        fault falls back to the hashlib rescue for that window only."""
        try:
            while True:
                with self._rb_mtx:
                    while not self._readback and not self._rb_closed:
                        self._rb_cv.wait(0.2)
                    if not self._readback:
                        return
                    fl = self._readback.popleft()
                    self._rb_busy += 1
                try:
                    self._finish(fl)
                except Exception:
                    try:
                        import traceback

                        traceback.print_exc()
                    except Exception:
                        pass
                    self._rescue_inflight(fl)
                finally:
                    self._drop_inflight(fl)
                    with self._rb_mtx:
                        self._rb_busy -= 1
                        self._rb_cv.notify_all()
        finally:
            with self._rb_mtx:
                self._rb_alive = False
                leftovers = list(self._readback)
                self._readback.clear()
                self._rb_cv.notify_all()
            for fl in leftovers:
                self._rescue_inflight(fl)
                self._drop_inflight(fl)

    def _drop_inflight(self, fl: _Inflight) -> None:
        try:
            self._inflights.remove(fl)
        except ValueError:
            pass

    def _collect(self, block: bool):
        """Pop one flush window; same contract as the verify
        coalescer's _collect (reason: size|deadline|drain|idle|quit;
        deadline anchored at the oldest pending ticket) — except the
        deadline budget is work-proportional: min(window knob,
        ~2x the pending lanes' host hashlib cost), recomputed as more
        lanes arrive. A lone tiny key flushes near-instantly instead
        of gambling a full window on future arrivals; heavy windows
        wait the knob like the verify coalescer."""
        with self._mtx:
            if block:
                while not self._pending and not self._draining:
                    self._cv.wait(0.2)
            if not self._pending:
                return None, 0, ("quit" if self._draining else "idle")
            first_t = self._pending[0][0].t_submit
            while self._pending_lanes < self.max_lanes and not self._draining:
                budget = min(
                    self.window_s,
                    _WAIT_COST_FACTOR
                    * _HOST_S_PER_BLOCK
                    * self._pending_blocks,
                )
                rem = budget - (time.perf_counter() - first_t)
                if rem <= 0:
                    break
                self._cv.wait(rem)
            if self._draining:
                reason = "drain"
            elif self._pending_lanes >= self.max_lanes:
                reason = "size"
            else:
                reason = "deadline"
            groups: list[tuple] = []
            lanes = 0
            while self._pending and (
                not groups or lanes + self._pending[0][0].n <= self.max_lanes
            ):
                g = self._pending.popleft()
                groups.append(g)
                lanes += g[0].n
                self._pending_blocks -= g[0].blocks
            self._pending_lanes -= lanes
            return groups, lanes, reason

    def _device_cut(self, bucket: int) -> int:
        """Lane cutover for a block bucket: ctor/env pin > the bucket's
        adaptive crossover > the seed curve."""
        if self.min_device_lanes is not None:
            return self.min_device_lanes
        return CROSSOVER.threshold(bucket)

    def _stage(self, groups):
        """Flatten groups into one window-ordered message list; a lane
        that cannot coerce to bytes fails ONLY its own submit."""
        msgs: list[bytes] = []
        staged: list[tuple] = []  # (ticket, lo, n)
        wire: list[tuple] = []  # (ticket, msgs) for hashlib rescue
        for ticket, raw in groups:
            try:
                lanes = [bytes(m) for m in raw]
                if len(lanes) != ticket.n:
                    raise ValueError(
                        f"lane count mismatch: {len(lanes)} != {ticket.n}"
                    )
            except Exception as e:
                ticket.fail(e)
                continue
            lo = len(msgs)
            msgs.extend(lanes)
            staged.append((ticket, lo, ticket.n))
            wire.append((ticket, lanes))
        return msgs, staged, wire

    def _launch(self, groups, lanes, reason) -> _Inflight | None:
        """Stage + dispatch one window, split by block bucket. Buckets
        the crossover sends to the device dispatch asynchronously (the
        double buffer materializes them NEXT loop turn); host buckets
        resolve inline with hashlib. Returns an in-flight handle when
        any device bucket launched, else resolves synchronously."""
        t_pop = time.perf_counter()
        libdevledger.exec_begin(libdevledger.PLANE_HASH)
        try:
            return self._launch_inner(groups, lanes, reason, t_pop)
        finally:
            libdevledger.exec_end(libdevledger.PLANE_HASH)

    def _launch_inner(self, groups, lanes, reason, t_pop) -> _Inflight | None:
        from ..ops import sha256 as osha

        msgs, staged, wire = self._stage(groups)
        if not staged:
            return None
        n = len(msgs)
        m = libmetrics.node_metrics()
        m.hash_window_lanes.observe(n)
        m.hash_flushes.labels(reason).inc()
        self.windows += 1
        use_device = self.device_capable()
        # split window lanes by block bucket (window order preserved
        # inside each bucket)
        buckets: dict[int, list[int]] = {}
        for i, msg in enumerate(msgs):
            bb = osha.block_bucket(osha.n_blocks(len(msg)))
            buckets.setdefault(bb, []).append(i)
        out: list[bytes | None] = [None] * n
        finishes = []
        host_s = 0.0
        for bb in sorted(buckets):
            idxs = buckets[bb]
            sub = [msgs[i] for i in idxs]
            if use_device and len(idxs) >= self._device_cut(bb):
                t0 = time.perf_counter()
                try:
                    finish = osha.sha256_many_async(sub, bb)
                except Exception:
                    # device staging/dispatch fault: clean hashlib
                    # fallback for this bucket only
                    import traceback

                    traceback.print_exc()
                else:
                    prep = time.perf_counter() - t0
                    libmetrics.observe_hash_phase(
                        "dispatch", prep, len(idxs)
                    )
                    finishes.append((finish, idxs, bb, prep, len(idxs)))
                    continue
            t0 = time.perf_counter()
            for i in idxs:
                out[i] = hashlib.sha256(msgs[i]).digest()
            dt = time.perf_counter() - t0
            host_s += dt
            libmetrics.observe_hash_phase("fallback", dt, len(idxs))
            CROSSOVER.note_host(bb, len(idxs), dt)
        if finishes:
            self.device_windows += 1
            libdevledger.note_window(libdevledger.PLANE_HASH, n, True)
            return _Inflight(
                finishes, out, wire, n, reason,
                t_launch=t_pop, host_s=host_s,
            )
        libdevledger.note_window(libdevledger.PLANE_HASH, n, False)
        self._resolve_bits(
            staged, out, reason, "host", t_launch=t_pop, host_s=host_s
        )
        return None

    def _finish(self, fl: _Inflight) -> None:
        """Materialize a window's device buckets and resolve tickets."""
        t0_ns = time.monotonic_ns()
        busy0 = libdevledger.exec_busy_ns(libdevledger.PLANE_HASH)
        device_s = 0.0
        for finish, idxs, bb, prep, k in fl.finishes:
            t0 = time.perf_counter()
            try:
                digests = finish()
            except Exception:
                # device fault at materialization: hashlib fallback for
                # the bucket — verdict-identical, never an error. The
                # recovery's hashlib time is NOT folded into device_s:
                # the whole window resolves as backend="device", and
                # charging host fault-recovery time as device execute
                # would skew the ledger exactly during the fault
                # episodes attribution exists to explain.
                import traceback

                traceback.print_exc()
                for i in idxs:
                    fl.out[i] = hashlib.sha256(fl_msg(fl, i)).digest()
                continue
            dt = time.perf_counter() - t0
            device_s += prep + dt
            libmetrics.observe_hash_phase("readback", dt, k)
            CROSSOVER.note_device(bb, k, prep + dt)
            for j, i in enumerate(idxs):
                fl.out[i] = digests[j]
        libdevledger.note_readback(libdevledger.PLANE_HASH, t0_ns, busy0)
        staged = []
        lo = 0
        for ticket, lanes in fl.groups:
            staged.append((ticket, lo, ticket.n))
            lo += ticket.n
        self._resolve_bits(
            staged, fl.out, fl.reason, "device",
            t_launch=fl.t_launch, exec_s=device_s, host_s=fl.host_s,
        )

    def _resolve_bits(
        self, staged, out, reason, backend, t_launch=None,
        exec_s=0.0, host_s=0.0,
    ) -> None:
        """Resolve tickets, then account.  ``exec_s`` is the window's
        DEVICE bucket time, ``host_s`` its inline hashlib bucket time —
        a mixed window charges callers both shares separately, so
        /debug/budget's execute_s/host_s split never reports host work
        as device time."""
        for ticket, lo, n in staged:
            ticket.resolve(out[lo : lo + n])
        total = 0
        for _, _, n in staged:
            total += n
        # ledger kill switch gates the whole accounting block
        # (histogram observes + EV_BUDGET rows), same as the verify
        # plane — a dark ledger costs one flag check here
        if libdevledger.enabled():
            m = libmetrics.node_metrics()
            plane = libdevledger.PLANE_HASH
            exec_ns = int(exec_s * 1e9)
            host_ns = int(host_s * 1e9)
            if exec_ns + host_ns > 0:
                libdevledger.note_window_time(plane, exec_ns + host_ns)
            anchor = (
                t_launch if t_launch is not None else time.perf_counter()
            )
            bw = bx = 0  # FSM-adjacent (merkle/mempool) wait/exec sums
            for ticket, lo, n in staged:
                wait_ns = int((anchor - ticket.t_submit) * 1e9)
                if wait_ns < 0:
                    wait_ns = 0
                dev_share = exec_ns * n // total if total else 0
                host_share = host_ns * n // total if total else 0
                cid = ticket.caller
                libdevledger.note_resolve(
                    plane, cid, n, wait_ns, dev_share, host_share
                )
                m.device_queue_wait.labels(
                    "hash", libdevledger.caller_name(cid)
                ).observe(wait_ns / 1e9)
                if cid in libdevledger.BUDGET_HASH_CALLERS:
                    bw += wait_ns
                    bx += dev_share + host_share
            if bw or bx:
                libhealth.record(libhealth.EV_BUDGET, 0, plane, bw, bx)
        if libhealth.enabled():
            libhealth.record(
                libhealth.EV_HASH,
                a=total,
                b=1 if backend == "device" else 0,
            )
        if libtrace.enabled():
            libtrace.event(
                "hash.flush",
                reason=reason,
                backend=backend,
                lanes=total,
                tickets=len(staged),
            )

    def _rescue_inflight(self, fl: _Inflight) -> None:
        """Hashlib-resolve an in-flight window's still-undone tickets
        (executor fault after dispatch, or shutdown with the executor
        wedged); done() gates make racing a live executor benign."""
        for ticket, lanes in fl.groups:
            if ticket.done():
                continue
            try:
                ticket.resolve(
                    [hashlib.sha256(m).digest() for m in lanes]
                )
            except Exception as e:
                ticket.fail(e)

    def _resolve_group_host(self, group) -> None:
        ticket, msgs = group
        if ticket.done():
            return
        try:
            ticket.resolve(
                [hashlib.sha256(bytes(m)).digest() for m in msgs]
            )
        except Exception as e:
            ticket.fail(e)


def fl_msg(fl: _Inflight, i: int) -> bytes:
    """Window-ordered message i of an in-flight window, recovered from
    the per-ticket wire copies (the fallback hash source)."""
    for _, lanes in fl.groups:
        if i < len(lanes):
            return lanes[i]
        i -= len(lanes)
    raise IndexError(i)


# -- process-wide routing switch ------------------------------------------

_ACTIVE: list[HashCoalescer] = []


def push_active(co: HashCoalescer) -> None:
    """Install ``co`` as the process-wide routed hash plane (node boot)."""
    _ACTIVE.append(co)


def pop_active(co: HashCoalescer) -> None:
    for i in range(len(_ACTIVE) - 1, -1, -1):
        if _ACTIVE[i] is co:
            del _ACTIVE[i]
            return


def active() -> HashCoalescer | None:
    """The routed plane, or None when hashing is unrouted."""
    for co in reversed(tuple(_ACTIVE)):
        if co.routable():
            return co
    return None


def configured_mode() -> str:
    """COMETBFT_TPU_HASH: "auto" (default; the node starts a plane only
    on accelerator backends), "1"/"on" force, "0" off."""
    v = os.environ.get("COMETBFT_TPU_HASH", "auto").lower()
    if v in ("1", "on", "true", "yes"):
        return "on"
    if v in ("0", "off", "false", "no"):
        return "off"
    return "auto"


def node_wants_hashplane() -> bool:
    """Whether a booting node should start a HashCoalescer."""
    mode = configured_mode()
    if mode == "on":
        return True
    if mode == "off":
        return False
    from ..libs.accel import accelerator_backend

    return accelerator_backend()


def _routed_device() -> HashCoalescer | None:
    """The routed plane IF it could serve device windows; None
    otherwise. Every routed helper funnels through this gate so a
    device-less container never pays a ticket round trip for work
    hashlib does optimally."""
    co = active()
    if co is not None and co.device_capable():
        return co
    return None


def prewarm() -> bool:
    """Warm the routed device path: push one tiny synthetic window
    through the coalescer so the compiled hash kernels and transfer
    buffers for the next height's PartSet/merkle work are resident
    before the proposer needs them.  Returns True if a device window
    was actually exercised; silently a no-op (False) when hashing is
    unrouted or device-less.  Digests are discarded — this changes
    latency, never results — so the pipelined prestage path may call
    it speculatively."""
    co = _routed_device()
    if co is None:
        return False
    try:
        ticket = co.submit_many([[b"\x00" * 64] * 4])[0]
        ticket.result(timeout=0.5)
        return True
    except Exception:
        return False


def hash_bytes(bz: bytes) -> bytes:
    """Single-message SHA-256, coalesced when it can win.

    THE drop-in for ``tmhash.sum`` on the cross-caller hot paths
    (mempool tx keys, PartSet leaf verification): identical digests,
    and any routing failure falls back to the host hash — never to a
    different answer. Messages under :data:`_SUM_ROUTE_MIN_BYTES` skip
    the queue (a one-block hashlib call beats any round trip).
    """
    if len(bz) >= _SUM_ROUTE_MIN_BYTES:
        co = _routed_device()
        if co is not None:
            digests = co.try_hash_many([bz])
            if digests is not None and len(digests) == 1:
                return digests[0]
    return hashlib.sha256(bz).digest()


def hash_many(msgs) -> list[bytes]:
    """Batch SHA-256 over independent messages, device-routed when the
    batch can actually win there (enough total work AND at least one
    block bucket reaching its device cut on this batch's own lanes —
    :meth:`HashCoalescer.batch_worth_routing`); host hashlib otherwise.
    Digest-identical either way."""
    if msgs:
        co = _routed_device()
        if co is not None and co.batch_worth_routing(msgs):
            digests = co.try_hash_many(msgs)
            if digests is not None and len(digests) == len(msgs):
                return digests
    return [hashlib.sha256(bytes(m)).digest() for m in msgs]


