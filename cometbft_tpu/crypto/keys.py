"""Key types: ed25519 (validator keys) and the PubKey/PrivKey contracts.

Reference surface: crypto/crypto.go:22-54 (PubKey, PrivKey), with the
ed25519 implementation semantics of crypto/ed25519/ed25519.go — ZIP-215
verification, SHA-256[:20] addresses, 32-byte seeds as private keys
(the wire form is seed || pubkey, 64 bytes, like RFC 8032 / golang's
crypto/ed25519 private key layout the reference serializes).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import tmhash
from . import ed25519_ref as ref

ED25519_KEY_TYPE = "ed25519"
SECP256K1_KEY_TYPE = "secp256k1"

PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64  # seed || pubkey
SIGNATURE_SIZE = 64


class Address(bytes):
    """20-byte account/validator address (SHA-256 truncated)."""

    def __str__(self) -> str:  # uppercase hex like the reference's HexBytes
        return self.hex().upper()


@dataclass(frozen=True, slots=True)
class Ed25519PubKey:
    data: bytes  # 32-byte compressed point

    def __post_init__(self) -> None:
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("ed25519 pubkey must be 32 bytes")

    @property
    def type(self) -> str:
        return ED25519_KEY_TYPE

    def address(self) -> Address:
        return Address(tmhash.sum_truncated(self.data))

    def bytes(self) -> bytes:
        return self.data

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """Single-signature ZIP-215 verification (host path).

        The batch path (crypto/batch) is preferred wherever >1 signature is
        in flight; this is the fallback contract of
        types/validation.go:266 (verifyCommitSingle). Routed through the
        OpenSSL fast path with exact ZIP-215 fallback (crypto/fast25519) —
        ~100x the pure-Python oracle on honest inputs.
        """
        from . import fast25519

        return fast25519.verify_one(self.data, msg, sig)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Ed25519PubKey) and self.data == other.data
        )

    def __hash__(self) -> int:
        return hash((ED25519_KEY_TYPE, self.data))


@dataclass(frozen=True, slots=True)
class Ed25519PrivKey:
    data: bytes  # seed || pubkey (64 bytes)

    def __post_init__(self) -> None:
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("ed25519 privkey must be 64 bytes (seed||pub)")

    @classmethod
    def generate(cls, rng=os.urandom) -> "Ed25519PrivKey":
        seed = rng(32)
        return cls.from_seed(seed)

    @classmethod
    def from_seed(cls, seed: bytes) -> "Ed25519PrivKey":
        from . import fast25519

        return cls(seed + fast25519.pubkey_from_seed(seed))

    @property
    def type(self) -> str:
        return ED25519_KEY_TYPE

    @property
    def seed(self) -> bytes:
        return self.data[:32]

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        from . import fast25519

        return fast25519.sign_one(self.seed, msg)

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(self.data[32:])


# Registry used by serialization (libs/json type registry analog) and the
# batch dispatch (crypto/batch/batch.go:11). sr25519/secp256k1 register
# lazily to keep import cycles out of the base module.
PUBKEY_TYPES: dict[str, type] = {ED25519_KEY_TYPE: Ed25519PubKey}


def register_extra_key_types() -> None:
    from .secp256k1 import Secp256k1PubKey
    from .sr25519 import Sr25519PubKey

    PUBKEY_TYPES.setdefault("sr25519", Sr25519PubKey)
    PUBKEY_TYPES.setdefault(SECP256K1_KEY_TYPE, Secp256k1PubKey)


def pubkey_from_type_and_bytes(key_type: str, data: bytes):
    cls = PUBKEY_TYPES.get(key_type)
    if cls is None:
        raise ValueError(f"unknown pubkey type {key_type!r}")
    return cls(data)
