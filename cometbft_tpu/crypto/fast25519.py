"""Fast host-side ed25519 verification with exact ZIP-215 semantics.

Role (TPU-first design): the TPU kernel (ops/verify.py) owns large batches,
but a device round trip has a fixed latency floor (~70 ms through the
relay), so latency-critical small verifies — proposal signatures, p2p
handshake challenges, evidence double-sign checks, sub-threshold commit
batches — run on host. This module is the host path the reference gets
from curve25519-voi (crypto/ed25519/ed25519.go:168): OpenSSL via the
``cryptography`` wheel, ~9k verifies/s/core, ~100x the pure-Python oracle.

Correctness: consensus requires ZIP-215 acceptance (cofactored equation,
liberal point decoding — crypto/ed25519/ed25519.go:26-29). OpenSSL
implements strict-ish RFC 8032 cofactorless verification, which accepts a
SUBSET of ZIP-215: every OpenSSL-valid signature is ZIP-215-valid
(multiply the cofactorless equation by 8), but OpenSSL rejects some
ZIP-215-valid edge encodings (non-canonical y, mixed-order points). So:

  OpenSSL says valid   -> accept (sound, no divergence)
  OpenSSL says invalid -> re-check with the exact pure-Python ZIP-215
                          oracle (ed25519_ref). Honest signatures never
                          take this branch; adversarial edge cases pay
                          ~10 ms — bounded by peer banning upstream.

This two-tier scheme is byte-for-byte equivalent to the ZIP-215 oracle
while being OpenSSL-fast on every honest input.
"""

from __future__ import annotations

from functools import lru_cache

from . import ed25519_ref as ref

try:  # the cryptography wheel is baked in; guard anyway for portability
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey as _OpenSSLKey,
    )

    _HAVE_OPENSSL = True
except Exception:  # pragma: no cover
    _HAVE_OPENSSL = False


@lru_cache(maxsize=4096)
def _loaded_key(pubkey: bytes):
    """Parsed OpenSSL key handle, LRU-cached.

    Validator pubkeys repeat every round; the cache plays the role of the
    reference's 4096-entry expanded-pubkey cache
    (crypto/ed25519/ed25519.go:31,56). Returns None for keys OpenSSL
    refuses to parse (e.g. non-canonical encodings ZIP-215 still admits).
    """
    try:
        return _OpenSSLKey.from_public_bytes(pubkey)
    except Exception:
        return None


def verify_one(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 single verification, OpenSSL fast path."""
    if len(pubkey) == 32 and len(sig) == 64:
        if _HAVE_OPENSSL:
            key = _loaded_key(bytes(pubkey))
            if key is not None:
                try:
                    key.verify(bytes(sig), bytes(msg))
                    return True  # RFC8032-valid implies ZIP-215-valid
                except InvalidSignature:
                    pass  # may still be ZIP-215-valid: recheck below
        # Middle tier: the native batch engine (edbatch.cpp) at n=1 —
        # cofactored RLC with voi/ZIP-215 semantics, ~50x the pure
        # oracle. Primary verify on wheel-less containers; on wheel
        # nodes it also absorbs the ZIP-215 edge encodings OpenSSL
        # refuses to parse or rejects, so only a native REJECT (invalid
        # w.h.p.) pays the exact-oracle recheck.
        from . import host_batch

        if host_batch.available():
            if host_batch.verify_many(
                [bytes(pubkey)], [bytes(msg)], [bytes(sig)]
            )[0]:
                return True
    return ref.verify(bytes(pubkey), bytes(msg), bytes(sig))


def sign_one(seed: bytes, msg: bytes) -> bytes:
    """Deterministic RFC 8032 signing, OpenSSL fast path.

    ed25519 signing is fully deterministic in (seed, msg), so OpenSSL and
    the pure-Python oracle produce identical bytes — this is a pure
    speedup (~100x), not a semantic fork. Equality is pinned in
    tests/test_crypto_host.py."""
    if _HAVE_OPENSSL and len(seed) == 32:
        try:
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey,
            )

            return Ed25519PrivateKey.from_private_bytes(bytes(seed)).sign(
                bytes(msg)
            )
        except Exception:
            pass
    return ref.sign(bytes(seed), bytes(msg))


def pubkey_from_seed(seed: bytes) -> bytes:
    """Public-key derivation, OpenSSL fast path (deterministic, exact)."""
    if _HAVE_OPENSSL and len(seed) == 32:
        try:
            from cryptography.hazmat.primitives import serialization
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey,
            )

            return (
                Ed25519PrivateKey.from_private_bytes(bytes(seed))
                .public_key()
                .public_bytes(
                    serialization.Encoding.Raw,
                    serialization.PublicFormat.Raw,
                )
            )
        except Exception:
            pass
    return ref.pubkey_from_seed(bytes(seed))


def verify_many(pubkeys, msgs, sigs) -> list[bool]:
    """Sequential host verification of a small batch.

    Used below the TPU dispatch threshold (crypto/batch). One CPU core at
    ~9k sigs/s beats the device round-trip latency floor for batches up to
    several hundred signatures.
    """
    return [
        verify_one(p, m, s) for p, m, s in zip(pubkeys, msgs, sigs)
    ]
