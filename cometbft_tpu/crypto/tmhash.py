"""SHA-256 hashing helpers (reference: crypto/tmhash/hash.go:65).

``sum`` is the universal 32-byte hash; ``sum_truncated`` the 20-byte prefix
used for addresses.
"""

from __future__ import annotations

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum(bz: bytes) -> bytes:  # noqa: A001 - mirrors reference name
    return hashlib.sha256(bz).digest()


def sum_truncated(bz: bytes) -> bytes:
    return hashlib.sha256(bz).digest()[:TRUNCATED_SIZE]
