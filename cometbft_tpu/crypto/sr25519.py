"""sr25519: Schnorr signatures over ristretto255 with merlin transcripts.

Reference surface: crypto/sr25519/{privkey,pubkey,batch}.go (backed by
curve25519-voi's schnorrkel). This is a from-scratch TPU-framework
implementation of the full stack:

* keccak-f[1600] permutation (pure Python, host-side — transcripts are
  byte-serial work with no TPU affinity, exactly like SHA-512 in the
  ed25519 path);
* STROBE-128 as specialized by merlin (strobe.rs subset: AD/meta-AD/
  PRF/KEY);
* merlin transcripts (dom-sep framing, LE32 length prefixes) — verified
  against merlin's published protocol test vector;
* ristretto255 encode/decode per RFC 9496 over the same edwards25519
  arithmetic the ed25519 oracle uses — verified against the RFC's
  generator-multiple vectors;
* schnorrkel signing/verification: ``SigningContext`` transcripts,
  ``Schnorr-sig`` protocol framing, 64-byte signatures with the
  schnorrkel v1 marker bit (s[31] |= 0x80).

Key expansion uses ExpansionMode::Uniform (first 32 SHA-512 bytes mod L);
nonces are derived deterministically from the transcript + nonce seed
(schnorrkel mixes an external RNG into its witness — signatures differ
across implementations by design; VERIFICATION is the interoperable
surface, and the verify equation s*B - k*A == R runs on the SAME batched
TPU kernel as ed25519: ristretto equality is Edwards equality modulo
torsion, which is exactly what the cofactored check [8](sB - kA - R) == O
decides."""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

from . import ed25519_ref as ref

SR25519_KEY_TYPE = "sr25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 32  # mini secret
SIGNATURE_SIZE = 64

P = ref.P
L = ref.L
D = ref.D
SQRT_M1 = pow(2, (P - 1) // 4, P)

SIGNING_CTX = b"substrate"  # the conventional substrate signing context

# ---------------------------------------------------------------------------
# keccak-f[1600]
# ---------------------------------------------------------------------------

_ROTC = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_M64 = (1 << 64) - 1


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (64 - n))) & _M64


def keccak_f1600(state: bytearray) -> None:
    """In-place permutation of a 200-byte state (little-endian lanes).

    Routed through the native engine when available (~1000x this
    Python loop; transcripts permute ~6x per signature/challenge) with
    the pure-Python permutation as the toolchain-less fallback."""
    from . import host_batch

    if host_batch.keccak_f1600_inplace(state):
        return
    a = [
        int.from_bytes(state[8 * i : 8 * i + 8], "little") for i in range(25)
    ]
    for rc in _RC:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20]
             for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(
                    a[x + 5 * y], _ROTC[x][y]
                )
        # chi
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ (
                    (~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y]
                ) & _M64
        # iota
        a[0] ^= rc
    for i in range(25):
        state[8 * i : 8 * i + 8] = a[i].to_bytes(8, "little")


# ---------------------------------------------------------------------------
# STROBE-128 (merlin's subset — strobe.rs)
# ---------------------------------------------------------------------------

_STROBE_R = 166
_FLAG_I, _FLAG_A, _FLAG_C, _FLAG_T, _FLAG_M, _FLAG_K = 1, 2, 4, 8, 16, 32


class Strobe128:
    def __init__(self, protocol_label: bytes):
        self.state = bytearray(200)
        init = (
            bytes([1, _STROBE_R + 2, 1, 0, 1, 96]) + b"STROBEv1.0.2"
        )
        self.state[: len(init)] = init
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[_STROBE_R + 1] ^= 0x80
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _overwrite(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] = byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray()
        for _ in range(n):
            out.append(self.state[self.pos])
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            assert flags == self.cur_flags
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = bool(flags & (_FLAG_C | _FLAG_K))
        if force_f and self.pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool) -> bytes:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        self._overwrite(data)

    def clone(self) -> "Strobe128":
        other = object.__new__(Strobe128)
        other.state = bytearray(self.state)
        other.pos = self.pos
        other.pos_begin = self.pos_begin
        other.cur_flags = self.cur_flags
        return other


# ---------------------------------------------------------------------------
# merlin transcripts
# ---------------------------------------------------------------------------


def _le32(n: int) -> bytes:
    return n.to_bytes(4, "little")


class Transcript:
    def __init__(self, label: bytes, _strobe: Strobe128 | None = None):
        if _strobe is not None:
            self.strobe = _strobe
            return
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(label + _le32(len(message)), False)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, n: int) -> None:
        self.append_message(label, n.to_bytes(8, "little"))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label + _le32(n), False)
        return self.strobe.prf(n, False)

    def challenge_scalar(self, label: bytes) -> int:
        return int.from_bytes(self.challenge_bytes(label, 64), "little") % L

    def witness_scalar(self, label: bytes, nonce_seeds: list[bytes]) -> int:
        """Deterministic witness: fork the transcript, rekey with the
        nonce seeds (merlin TranscriptRngBuilder without external
        entropy)."""
        fork = self.strobe.clone()
        for seed in nonce_seeds:
            fork.meta_ad(label + _le32(len(seed)), False)
            fork.key(seed, False)
        return int.from_bytes(fork.prf(64, False), "little") % L

    def clone(self) -> "Transcript":
        return Transcript(b"", _strobe=self.strobe.clone())


# ---------------------------------------------------------------------------
# ristretto255 (RFC 9496) over the shared edwards25519 integer arithmetic
# ---------------------------------------------------------------------------


def _is_neg(x: int) -> bool:
    return x % P % 2 == 1


def _abs(x: int) -> int:
    x %= P
    return P - x if _is_neg(x) else x


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    """(was_square, sqrt(u/v) or sqrt(i*u/v)) per RFC 9496 §4.2."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    correct = check == u % P
    flipped = check == (-u) % P
    flipped_i = check == (-u) % P * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    return correct or flipped, _abs(r)


_INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, (-1 - D) % P)[1]


def ristretto_decode(data: bytes):
    """32 bytes -> Edwards extended point, or None if invalid."""
    if len(data) != 32:
        return None
    s = int.from_bytes(data, "little")
    if s >= P or _is_neg(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P * u1) - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = _abs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P
    if not was_square or _is_neg(t) or y == 0:
        return None
    return (x, y, 1, t)


def ristretto_encode(pt) -> bytes:
    """Edwards extended point -> canonical 32-byte encoding (RFC 9496)."""
    x0, y0, z0, t0 = pt
    u1 = (z0 + y0) % P * ((z0 - y0) % P) % P
    u2 = x0 * y0 % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    if _is_neg(t0 * z_inv % P):
        x, y = y0 * SQRT_M1 % P, x0 * SQRT_M1 % P
        den_inv = den1 * _INVSQRT_A_MINUS_D % P
    else:
        x, y = x0, y0
        den_inv = den2
    if _is_neg(x * z_inv % P):
        y = (-y) % P
    s = _abs(den_inv * ((z0 - y) % P) % P)
    return s.to_bytes(32, "little")


def ristretto_eq(p, q) -> bool:
    """x1*y2 == y1*x2 or y1*y2 == x1*x2 (RFC 9496 / dalek equality)."""
    x1, y1, _, _ = p
    x2, y2, _, _ = q
    return (x1 * y2 - y1 * x2) % P == 0 or (y1 * y2 - x1 * x2) % P == 0


# ---------------------------------------------------------------------------
# schnorrkel
# ---------------------------------------------------------------------------


def _expand_uniform(mini: bytes) -> tuple[int, bytes]:
    """ExpansionMode::Uniform: scalar = SHA512(mini)[:32] mod L, nonce =
    SHA512(mini)[32:]."""
    h = hashlib.sha512(mini).digest()
    return int.from_bytes(h[:32], "little") % L, h[32:]


def _base_mult(scalar: int):
    """[s]B via the native constant-time ladder when available.

    Signing scalars are secrets: the C path (host_batch.scalar_base_mult,
    native/edbatch.cpp) selects table entries with arithmetic masks and
    runs ~100x the pure-Python oracle; the oracle remains the fallback
    when the toolchain is absent (variable-time, as documented there).
    """
    from . import host_batch

    pt = host_batch.scalar_base_mult(scalar)
    return pt if pt is not None else ref.scalar_mult(scalar, ref.BASE)


def public_from_mini(mini: bytes) -> bytes:
    scalar, _ = _expand_uniform(mini)
    return ristretto_encode(_base_mult(scalar))


def _signing_transcript(context: bytes, msg: bytes) -> Transcript:
    """schnorrkel SigningContext: Transcript(b"SigningContext") +
    append(b"", ctx) + append(b"sign-bytes", msg)."""
    t = Transcript(b"SigningContext")
    t.append_message(b"", context)
    t.append_message(b"sign-bytes", msg)
    return t


def sign(mini: bytes, msg: bytes, context: bytes = SIGNING_CTX) -> bytes:
    scalar, nonce_seed = _expand_uniform(mini)
    pub = ristretto_encode(_base_mult(scalar))
    t = _signing_transcript(context, msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    r = t.witness_scalar(b"signing", [nonce_seed])
    r_enc = ristretto_encode(_base_mult(r))
    t.append_message(b"sign:R", r_enc)
    k = t.challenge_scalar(b"sign:c")
    s = (k * scalar + r) % L
    s_bytes = bytearray(s.to_bytes(32, "little"))
    s_bytes[31] |= 0x80  # schnorrkel v1 marker
    return r_enc + bytes(s_bytes)


# ---------------------------------------------------------------------------
# verification challenges — native batched transcript engine
# ---------------------------------------------------------------------------

# Serialized STROBE states of Transcript("SigningContext") +
# append_message(b"", context): a pure function of the signing context,
# shared by every challenge under it. Bounded — contexts are a small
# static set (conventionally just b"substrate").
_CTX_PREFIX_CACHE: dict[bytes, bytes] = {}


def _context_prefix(context: bytes) -> bytes:
    """203-byte serialized STROBE state (sponge || pos || pos_begin ||
    cur_flags) of the per-context transcript prefix, for the native
    engine (native/edbatch.cpp edb_sr_challenge_batch)."""
    st = _CTX_PREFIX_CACHE.get(context)
    if st is None:
        t = Transcript(b"SigningContext")
        t.append_message(b"", context)
        s = t.strobe
        st = bytes(s.state) + bytes([s.pos, s.pos_begin, s.cur_flags])
        if len(_CTX_PREFIX_CACHE) < 64:
            _CTX_PREFIX_CACHE[context] = st
    return st


def _challenge_py(context: bytes, msg: bytes, pubkey: bytes,
                  r_enc: bytes) -> int:
    """Pure-Python transcript challenge (the native engine's oracle)."""
    t = _signing_transcript(context, msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pubkey)
    t.append_message(b"sign:R", r_enc)
    return t.challenge_scalar(b"sign:c")


def challenge_scalars_batch(
    pubkeys, msgs, sigs, context: bytes = SIGNING_CTX
) -> list[int]:
    """k_i for each (pubkey, msg, R=sig[:32]) lane in ONE native call.

    The sr25519 batch hot path (reference crypto/sr25519/batch.go:14-46
    computes these transcript challenges per entry): the whole STROBE
    absorb/permute/squeeze sequence runs in C against the cached
    per-context prefix state; the per-lane Python transcript is the
    toolchain-less fallback."""
    from . import host_batch

    n = len(pubkeys)
    recs = b"".join(p + s[:32] for p, s in zip(pubkeys, sigs))
    offs = [0]
    for m in msgs:
        offs.append(offs[-1] + len(m))
    raw = host_batch.sr_challenge_batch(
        _context_prefix(context), recs, b"".join(msgs), offs, n
    )
    if raw is None:
        return [
            _challenge_py(context, m, p, s[:32])
            for p, m, s in zip(pubkeys, msgs, sigs)
        ]
    return [
        int.from_bytes(raw[32 * i : 32 * i + 32], "little")
        for i in range(n)
    ]


def _admit(pubkey: bytes, sig: bytes):
    """Structural admission shared by every verify path: lengths, the
    schnorrkel v1 marker bit, s < L. Returns the unmasked scalar s, or
    None if malformed."""
    if len(sig) != SIGNATURE_SIZE or len(pubkey) != PUBKEY_SIZE:
        return None
    if not (sig[63] & 0x80):
        return None  # not a schnorrkel v1 signature
    s_bytes = bytearray(sig[32:])
    s_bytes[31] &= 0x7F
    s = int.from_bytes(bytes(s_bytes), "little")
    if s >= L:
        return None
    return s


def _precheck(pubkey: bytes, sig: bytes):
    """Structural admission + ristretto decode: (A_pt, R_pt, s) or None
    if malformed."""
    s = _admit(pubkey, sig)
    if s is None:
        return None
    a_pt = ristretto_decode(pubkey)
    r_pt = ristretto_decode(sig[:32])
    if a_pt is None or r_pt is None:
        return None
    return a_pt, r_pt, s


def verification_parts(
    pubkey: bytes, msg: bytes, sig: bytes, context: bytes = SIGNING_CTX
):
    """Decompose a signature into the kernel equation's inputs.

    Returns (A_edwards, R_edwards, s, k) or None if malformed — exactly
    the (pubkey point, R point, scalar, challenge) quadruple the batched
    TPU verifier consumes; sr25519 rides the ed25519 kernel because
    ristretto equality is Edwards equality modulo torsion, which the
    cofactored check decides."""
    pre = _precheck(pubkey, sig)
    if pre is None:
        return None
    a_pt, r_pt, s = pre
    k = challenge_scalars_batch([pubkey], [msg], [sig], context)[0]
    return a_pt, r_pt, s, k


def verification_parts_batch(
    pubkeys, msgs, sigs, context: bytes = SIGNING_CTX
) -> list:
    """Per-lane (A, R, s, k) quads — None for malformed lanes — with one
    native challenge pass over the structurally valid lanes."""
    n = len(pubkeys)
    parts: list = [None] * n
    pre = [_precheck(pubkeys[i], sigs[i]) for i in range(n)]
    live = [i for i in range(n) if pre[i] is not None]
    if not live:
        return parts
    ks = challenge_scalars_batch(
        [pubkeys[i] for i in live],
        [msgs[i] for i in live],
        [sigs[i] for i in live],
        context,
    )
    for j, i in enumerate(live):
        a_pt, r_pt, s = pre[i]
        parts[i] = (a_pt, r_pt, s, ks[j])
    return parts


def verification_encs_batch(
    pubkeys, msgs, sigs, context: bytes = SIGNING_CTX
) -> list:
    """Per-lane (A_edwards_enc, R_edwards_enc, s, k) — None for
    malformed lanes — with the ristretto decodes AND transcript
    challenges batched through the native engine.

    This is the form both batch consumers want (host MSM and TPU kernel
    take compressed edwards points), so no Python bigint touches the
    per-lane path. Falls back to the pure-Python decode + compress when
    the toolchain is absent."""
    from . import host_batch

    n = len(pubkeys)
    parts: list = [None] * n
    # structural admission (cheap Python): lengths, marker bit, s < L
    svals = [_admit(pubkeys[i], sigs[i]) for i in range(n)]
    cand = [i for i in range(n) if svals[i] is not None]
    if not cand:
        return parts
    conv = host_batch.ristretto_to_edwards_batch(
        b"".join(bytes(pubkeys[i]) + bytes(sigs[i][:32]) for i in cand),
        2 * len(cand),
    )
    if conv is None:
        quads = verification_parts_batch(pubkeys, msgs, sigs, context)
        return [
            (ref.compress(q[0]), ref.compress(q[1]), q[2], q[3])
            if q is not None
            else None
            for q in quads
        ]
    enc_rows, ok = conv
    live = [i for j, i in enumerate(cand) if ok[2 * j] and ok[2 * j + 1]]
    encs = {
        i: (enc_rows[64 * j : 64 * j + 32],
            enc_rows[64 * j + 32 : 64 * j + 64])
        for j, i in enumerate(cand)
    }
    if not live:
        return parts
    ks = challenge_scalars_batch(
        [pubkeys[i] for i in live],
        [msgs[i] for i in live],
        [sigs[i] for i in live],
        context,
    )
    for j, i in enumerate(live):
        a_enc, r_enc = encs[i]
        parts[i] = (a_enc, r_enc, svals[i], ks[j])
    return parts


def verify(
    pubkey: bytes, msg: bytes, sig: bytes, context: bytes = SIGNING_CTX
) -> bool:
    """Host-side verification: s*B - k*A == R in ristretto.

    Routed through the native engine when available: one 3-point
    cofactored MSM. For ristretto-decoded inputs the cofactored check
    [8](sB - kA - R) == O decides exactly ristretto equality — decoded
    points lie in the even subgroup 2E, whose full torsion is E[4], the
    kernel of the ristretto quotient. Pure-Python scalar mults remain
    the toolchain-less fallback."""
    from . import host_batch

    if host_batch.available():
        quad = verification_encs_batch([pubkey], [msg], [sig], context)[0]
        if quad is None:
            return False
        res = host_batch.verify_quads([quad])
        if res is not None:
            return bool(res[0])
    parts = verification_parts(pubkey, msg, sig, context)
    if parts is None:
        return False
    a_pt, r_pt, s, k = parts
    sb = _base_mult(s)
    ka = ref.scalar_mult(k, a_pt)
    lhs = ref.point_add(sb, ref.point_neg(ka))
    return ristretto_eq(lhs, r_pt)


# ---------------------------------------------------------------------------
# key types (crypto.PubKey / PrivKey contracts)
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class Sr25519PubKey:
    data: bytes

    def __post_init__(self) -> None:
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("sr25519 pubkey must be 32 bytes")

    @property
    def type(self) -> str:
        return SR25519_KEY_TYPE

    def address(self) -> bytes:
        from . import tmhash
        from .keys import Address

        return Address(tmhash.sum_truncated(self.data))

    def bytes(self) -> bytes:
        return self.data

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        return verify(self.data, msg, sig)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Sr25519PubKey) and self.data == other.data

    def __hash__(self) -> int:
        return hash((SR25519_KEY_TYPE, self.data))


@dataclass(frozen=True, slots=True)
class Sr25519PrivKey:
    data: bytes  # mini secret

    def __post_init__(self) -> None:
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("sr25519 privkey must be a 32-byte mini secret")

    @classmethod
    def generate(cls, rng=os.urandom) -> "Sr25519PrivKey":
        return cls(rng(32))

    @classmethod
    def from_seed(cls, seed: bytes) -> "Sr25519PrivKey":
        return cls(seed)

    @property
    def type(self) -> str:
        return SR25519_KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        return sign(self.data, msg)

    def pub_key(self) -> Sr25519PubKey:
        return Sr25519PubKey(public_from_mini(self.data))
