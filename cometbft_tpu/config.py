"""Node configuration (reference: config/config.go:73-1135).

The master ``Config`` has the reference's 9 sections; consensus timeouts
follow config.go:908-945. ``test_config()`` mirrors ``TestConfig()``
(config.go:106) — millisecond timeouts so in-process consensus nets
converge fast.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace

_MS = 1_000_000  # ns per ms

# Registry of every COMETBFT_* environment knob the engine reads.
# cometlint (CLNT007, devtools/lint) fails the build when code reads a
# knob that is not declared here, so this dict IS the operator-facing
# catalog — adding an env read and documenting it are one change. Keys
# are knob names, values are one-line operator docs.
ENV_KNOBS: dict[str, str] = {
    "COMETBFT_TPU_KERNEL": (
        "verify-kernel lowering: auto (default) | pallas | pallas8 | "
        "xla | xla8; pins a flavor for benchmarking (ops/verify.py)"
    ),
    "COMETBFT_TPU_PUBKEY_CACHE": (
        "expanded-pubkey device arena: 1 (default) | 0 to disable "
        "(ops/verify.py)"
    ),
    "COMETBFT_TPU_PRESTAGE": (
        "warm the pubkey arena at enter-new-round: auto (default, "
        "accelerator-only) | 1 force | 0 off (ops/verify.py)"
    ),
    "COMETBFT_TPU_SHARD": (
        "multi-chip signature-axis sharding: auto (default, "
        "accelerator-only) | 1 force | 0 off (ops/verify.py)"
    ),
    "COMETBFT_TPU_XLA_CACHE": (
        "persistent XLA compilation-cache directory (default "
        "~/.cache/cometbft_tpu_xla; ops/verify.py)"
    ),
    "COMETBFT_TPU_HOST_THRESHOLD": (
        "batch size below which verification stays on host; overrides "
        "the chip-table-derived crossover (crypto/batch.py)"
    ),
    "COMETBFT_TPU_SR_HOST": (
        "1 routes sr25519 batches to the host verifier — the explicit "
        "dead-tunnel escape (crypto/batch.py)"
    ),
    "COMETBFT_TPU_CHIP_TABLE": (
        "path override for the accelerator-measured bench table "
        "(default <repo>/BENCH_CHIP_TABLE.json; libs/chip_table.py)"
    ),
    "COMETBFT_TPU_DEADLOCK": (
        "1 swaps every libs/sync mutex for a deadlock-detecting "
        "instrumented lock (the go-deadlock build-tag analog)"
    ),
    "COMETBFT_TPU_DEADLOCK_TIMEOUT": (
        "seconds a waiter stalls before the deadlock tier dumps all "
        "thread stacks (default 30; libs/sync.py)"
    ),
    "COMETBFT_TPU_LOCK_ORDER": (
        "lock-order sanitizer: off (default) | record accumulates the "
        "observed acquisition-order edges | enforce raises LockOrderError "
        "on an edge absent from the static lock-order graph (libs/sync.py; "
        "graph from `python -m cometbft_tpu.devtools.lint --graph`)"
    ),
    "COMETBFT_TPU_LOCK_ORDER_GRAPH": (
        "path override for the static lock-order graph that enforce mode "
        "validates against (default: the lockorder.json shipped in "
        "devtools/lint/graph; libs/sync.py)"
    ),
    "COMETBFT_TPU_LOCKSET": (
        "lockset sanitizer: off (default) | record samples (field, "
        "held-lock names) at accessor seams | enforce raises LocksetError "
        "when a seam runs without the field's statically inferred guard "
        "fully held (libs/sync.py; guards from `python -m "
        "cometbft_tpu.devtools.lint --fields`)"
    ),
    "COMETBFT_TPU_LOCKSET_FIELDS": (
        "path override for the guarded-field artifact that enforce mode "
        "validates against (default: the fieldguards.json shipped in "
        "devtools/lint/graph; libs/sync.py)"
    ),
    "COMETBFT_TPU_LOCKPROF": (
        "lock-contention profiler (libs/lockprof): auto (default, on "
        "while a node runs — refcounted in node boot) | 1/on force | "
        "0/off kill switch; feeds lock_wait_seconds{lock}, "
        "/debug/contention and the lock_contended watchdog"
    ),
    "COMETBFT_TPU_LOCKPROF_SLOW_MS": (
        "lock wait/hold duration past which the profiler emits an "
        "EV_LOCK flight-ring row naming the blocking holder's acquire "
        "site, and the lock_contended watchdog's windowed-p99 trip "
        "threshold (default 50; libs/lockprof.py)"
    ),
    "COMETBFT_TPU_PROF": (
        "continuous sampling profiler (libs/profile): auto (default, "
        "on while a node runs — refcounted in node boot) | 1/on force "
        "| 0/off kill switch; feeds /debug/pprof/profile, "
        "profile_samples_total{subsystem,state}, EV_PROF critical-path "
        "rows and the bundle profile.json"
    ),
    "COMETBFT_TPU_PROF_HZ": (
        "sampling-profiler rate in stack walks per second (default "
        "~67, off the round numbers so the sampler never phase-locks "
        "with engine timers; libs/profile.py)"
    ),
    "COMETBFT_TPU_PROF_RING": (
        "sampling-profiler recent-sample ring capacity in samples "
        "(default 32768, ~30 s of pre-trip history for watchdog "
        "bundles; libs/profile.py)"
    ),
    "COMETBFT_TPU_FAIL": (
        "named crash point for fault-injection tests — the process "
        "dies hard when execution reaches it (libs/fail.py)"
    ),
    "COMETBFT_TPU_PIPELINE": (
        "pipelined commit chain (consensus/pipeline.py): save-block + "
        "WAL EndHeight fsync + app commit move onto an ordered "
        "commit-writer worker behind a durability barrier — auto "
        "(default: on for live nodes, inline for sim-driven FSMs) | "
        "1/on force | inline run jobs synchronously on the FSM thread "
        "| 0/off fully serial reference chain"
    ),
    "COMETBFT_TPU_SPEC_EXEC": (
        "speculative block execution at prevote time "
        "(consensus/pipeline.py): auto (default — on when the ABCI "
        "client supports the snapshot/restore speculation extension) "
        "| 1/on force | 0/off; a precommit win consumes the memoized "
        "FinalizeBlock instead of re-executing"
    ),
    "COMETBFT_TPU_TRACE": (
        "span/event tracer: off (default) | on/1 — consensus "
        "height/round/step spans, verify phase events, mempool/p2p/"
        "blocksync/WAL events into the in-memory ring (libs/trace.py; "
        "also /debug/trace on the pprof server)"
    ),
    "COMETBFT_TPU_TRACE_FILE": (
        "JSONL sink path for the tracer — records tee to a rotating "
        "libs/autofile Group when tracing is on (libs/trace.py)"
    ),
    "COMETBFT_TPU_TRACE_RING": (
        "trace ring-buffer capacity in records (default 8192; "
        "libs/trace.py)"
    ),
    "COMETBFT_TPU_DEVSTATS": (
        "device/XLA telemetry (libs/devstats): 1/on enables compile "
        "accounting, device-memory + pubkey-arena sampling and "
        "host<->device transfer counters; default off (a node "
        "auto-enables it when it starts a Prometheus listener)"
    ),
    "COMETBFT_TPU_PROM_ADDR": (
        "Prometheus scrape-listener address (tcp://host:port or "
        ":port); when set (or instrumentation.prometheus in config) "
        "the node serves the metrics registry at GET /metrics on a "
        "dedicated libs/devstats.PrometheusServer"
    ),
    "COMETBFT_TPU_SOFTWARE_VERSION": (
        "node software version advertised in p2p NodeInfo/RPC status "
        "(node/node.py; set per-node by the e2e harness)"
    ),
    "COMETBFT_TPU_COALESCE": (
        "cross-caller verify coalescer: auto (default, node starts it "
        "on accelerator backends) | 1 force | 0 off (crypto/coalesce.py)"
    ),
    "COMETBFT_TPU_COALESCE_WINDOW_US": (
        "coalescer deadline window in microseconds before a sub-size "
        "window flushes (default 500; crypto/coalesce.py)"
    ),
    "COMETBFT_TPU_COALESCE_MAX_LANES": (
        "lanes that trigger an immediate coalescer size flush / the "
        "per-window cap (default 1024; crypto/coalesce.py)"
    ),
    "COMETBFT_TPU_COALESCE_MIN_DEVICE_LANES": (
        "pin the lane count above which coalescer windows go to the "
        "device; unset defers to the live host/device crossover "
        "(crypto/batch.host_batch_threshold) — sub-cutover windows "
        "still coalesce into one host MSM (crypto/coalesce.py)"
    ),
    "COMETBFT_TPU_COALESCE_INFLIGHT": (
        "device verify windows dispatched but not yet materialized "
        "across the executor + readback drain thread (default 2 — the "
        "double buffer: window N's d2h overlaps window N+1's execute; "
        "crypto/coalesce.py)"
    ),
    "COMETBFT_TPU_HASH_INFLIGHT": (
        "hash-plane analog of COMETBFT_TPU_COALESCE_INFLIGHT: device "
        "hash windows in flight across the executor + readback drain "
        "thread (default 2; crypto/hashplane.py)"
    ),
    "COMETBFT_TPU_LANE_ARENA": (
        "persistent donated device staging buffers for per-launch wire "
        "rows (ops/verify.LaneArena): auto (default, accelerator "
        "backends only) | 1 force (tests exercise staging on XLA-CPU) "
        "| 0 off — fresh h2d allocations per launch"
    ),
    "COMETBFT_TPU_HASH": (
        "cross-caller SHA-256 hash plane: auto (default, node starts "
        "it on accelerator backends) | 1 force | 0 off "
        "(crypto/hashplane.py)"
    ),
    "COMETBFT_TPU_HASH_WINDOW_US": (
        "hash-plane deadline window in microseconds before a sub-size "
        "window flushes (default 500; crypto/hashplane.py)"
    ),
    "COMETBFT_TPU_HASH_MAX_LANES": (
        "lanes that trigger an immediate hash-plane size flush / the "
        "per-window cap (default 2048; crypto/hashplane.py)"
    ),
    "COMETBFT_TPU_HASH_MIN_DEVICE_LANES": (
        "pin the lane count above which a hash window's block buckets "
        "go to the device; unset defers to the per-bucket adaptive "
        "crossover seeded at ~2048 total SHA blocks per window "
        "(crypto/hashplane.py)"
    ),
    "COMETBFT_TPU_HEALTH": (
        "consensus flight recorder + SLO watchdogs (libs/health): auto "
        "(default — on while a node runs, refcounted like devstats) | "
        "1 force-on process-wide | 0 off (kill switch: no recording, "
        "no watchdogs, no black-box bundles)"
    ),
    "COMETBFT_TPU_HEALTH_RING": (
        "flight-recorder ring capacity in events (default 4096; "
        "libs/health.py)"
    ),
    "COMETBFT_TPU_HEALTH_STALL_MULT": (
        "consensus stall watchdog window as a multiple of the node's "
        "timeout_commit + timeout_propose cycle (default 25; "
        "libs/health.py HealthMonitor)"
    ),
    "COMETBFT_TPU_HEALTH_BUNDLE_DIR": (
        "black-box bundle directory override for watchdog trips "
        "(default: the node's data/health dir; libs/health.py)"
    ),
    "COMETBFT_TPU_HEALTH_BUNDLE_RL_S": (
        "minimum seconds between black-box bundles (default 60 — a "
        "flapping watchdog must not fill the disk; libs/health.py)"
    ),
    "COMETBFT_TPU_LIGHT": (
        "light-client proof service (light/service.py): 0 (default) | "
        "1/on — the node serves light_verify/light_status over RPC, "
        "funnelling concurrent clients' skipping-verification commit "
        "checks through the shared batch verifiers and coalescer"
    ),
    "COMETBFT_TPU_LIGHT_MAX_INFLIGHT": (
        "light-service requests verifying concurrently before new "
        "arrivals queue (default 64; light/service.py)"
    ),
    "COMETBFT_TPU_LIGHT_MAX_QUEUE": (
        "light-service requests allowed to wait for an in-flight slot; "
        "arrivals beyond it are rejected immediately — the queue-depth "
        "backpressure bound (default 256; light/service.py)"
    ),
    "COMETBFT_TPU_LIGHT_DEADLINE_S": (
        "default per-request deadline in seconds for light_verify; "
        "propagates into coalescer ticket waits and provider fetches "
        "(default 10; light/service.py)"
    ),
    "COMETBFT_TPU_LIGHT_CACHE_SIZE": (
        "commit-verification result-cache LRU bound in entries "
        "(default 4096; light/service.py)"
    ),
    "COMETBFT_TPU_LIGHT_CACHE_TTL_S": (
        "commit-verification result-cache TTL in seconds (default "
        "600; light/service.py)"
    ),
    "COMETBFT_TPU_NET": (
        "network-plane telemetry (libs/netstats): auto (default — on "
        "while a node runs, refcounted like devstats/health) | 1 "
        "force-on process-wide | 0 off (per-peer/per-channel stats, "
        "queue gauges, gossip-lag SLI all dark; the disabled path is "
        "allocation-free)"
    ),
    "COMETBFT_TPU_NET_STAMP": (
        "provenance stamping of p2p messages (libs/netstats): 1 "
        "(default — the node advertises the netstamp capability and "
        "stamps toward peers that advertise it back) | 0 withdraws "
        "the advertisement; wire compat with unstamped peers is "
        "negotiated, never sniffed"
    ),
    "COMETBFT_TPU_NET_TOPK": (
        "peers exported with their own p2p_peer_rate_bytes{peer} "
        "label value, ranked by traffic, before aggregating into "
        "'other' (default 8 — bounds scrape cardinality; "
        "libs/netstats.py)"
    ),
    "COMETBFT_TPU_SIMNET_SEED": (
        "default schedule seed for simnet scenario runs (`python -m "
        "cometbft_tpu.simnet`, e2e --simnet); a run's seed replays it "
        "bit-identically (cometbft_tpu/simnet)"
    ),
    "COMETBFT_TPU_SIMNET_LOG": (
        "1 prints every simnet fault event (partitions, drops, churn, "
        "crash points) to stderr as it fires — scenario debugging "
        "(cometbft_tpu/simnet/net.py)"
    ),
    "COMETBFT_TPU_ADAPTIVE_THRESHOLD": (
        "adaptive host/device batch crossover from measured timings: "
        "auto (default, accelerator-only) | 1 force | 0 static seed "
        "only; a COMETBFT_TPU_HOST_THRESHOLD pin always wins "
        "(crypto/batch.py AdaptiveCrossover)"
    ),
    "COMETBFT_TPU_POSTMORTEM": (
        "timeline.json in watchdog black-box bundles — the merged "
        "cross-node timeline + root-cause verdicts "
        "(cometbft_tpu/postmortem): auto/1 on (default; merges peers "
        "named by COMETBFT_TPU_POSTMORTEM_PEERS when reachable, "
        "local-only otherwise) | 0 skip the pass"
    ),
    "COMETBFT_TPU_POSTMORTEM_PEERS": (
        "comma-separated peer flight-ring URLs (host:port or full "
        "http://host:port/debug/flight) merged into bundle timelines; "
        "unreachable peers degrade to the local view "
        "(cometbft_tpu/postmortem.bundle_timeline)"
    ),
    "COMETBFT_TPU_SUSPICION": (
        "peer-health suspicion scorer (p2p/suspicion.py): evicts gray "
        "(slow-but-alive) peers off the netstats signals — send-queue-"
        "full streaks, stamp staleness, propagation-lag outliers; "
        "default on for every running node, 0 disables"
    ),
    "COMETBFT_TPU_SUSPICION_EVICT": (
        "suspicion score at which a peer is evicted through the switch "
        "(default 3.0 — roughly three consecutive bad check ticks; "
        "scores decay 0.5x per clean tick, p2p/suspicion.py)"
    ),
    "COMETBFT_TPU_SUSPICION_COOLDOWN_S": (
        "minimum seconds between suspicion evictions of the SAME peer "
        "(default 30 — a genuinely-broken link must reconnect-and-"
        "prove-itself, not flap; p2p/suspicion.py)"
    ),
    "COMETBFT_TPU_HEALTH_DISK_EWMA": (
        "window (in fsyncs) of the WAL fsync-latency EWMA behind the "
        "disk_degraded state and the slow_disk watchdog (default 8; "
        "alpha = 2/(window+1), consensus/wal.py)"
    ),
    "COMETBFT_TPU_HEALTH_DISK_MS": (
        "fsync-EWMA milliseconds at which the node enters "
        "disk_degraded — propose timeouts widen, the slow_disk "
        "watchdog trips a black-box bundle; clears below half the "
        "threshold (hysteresis; default 50, consensus/wal.py)"
    ),
    "COMETBFT_TPU_LEDGER": (
        "device-time ledger (libs/devledger): per-(plane, caller) "
        "attribution of the shared verify/hash coalescer planes — "
        "auto (default, on while a node runs, refcounted like "
        "devstats/health) | 1 force-on process-wide | 0 off (the "
        "record path is a single flag check)"
    ),
    "COMETBFT_TPU_LEDGER_STARVE_MS": (
        "consensus-starvation watchdog threshold: consensus-caller "
        "verify queue-wait p99 in milliseconds above which — while "
        "other callers dominate the window's lane share — the "
        "consensus_starved watchdog trips and writes a black-box "
        "bundle (default 50; <=0 disables; libs/health.py)"
    ),
    "COMETBFT_TPU_TX": (
        "transaction-lifecycle plane (libs/txtrace): sampled "
        "end-to-end tx tracing from CheckTx admission through gossip, "
        "proposal inclusion and commit — auto (default, on while a "
        "node runs, refcounted like devstats/netstats) | 1 force-on "
        "process-wide | 0 off (kill switch: the record path is one "
        "flag check)"
    ),
    "COMETBFT_TPU_TX_SAMPLE": (
        "tx-lifecycle sampling denominator: 1/N of tx keys are traced "
        "(deterministic on the key's first 8 bytes, so every node "
        "samples the SAME txs and cross-node joins need no "
        "coordination; default 64, 1 = every tx, <= 0 disables "
        "sampling; libs/txtrace.py)"
    ),
    "COMETBFT_TPU_TX_RING": (
        "tx-lifecycle in-flight table + completion-ring capacity in "
        "rows (default 4096; a colliding sampled key evicts the "
        "oldest row — flight-recorder semantics; libs/txtrace.py)"
    ),
    "COMETBFT_TPU_TX_STARVE_COMMITS": (
        "tx_starved watchdog window in commit intervals: an admitted "
        "tx older than N measured inter-commit intervals WHILE "
        "heights keep committing trips a page + black-box bundle "
        "naming the oldest keys (default 16; <= 0 disables; "
        "libs/health.py HealthMonitor)"
    ),
    "COMETBFT_TPU_STATESYNC_BACKOFF_S": (
        "base seconds of the per-peer exponential backoff the "
        "statesync chunk fetcher applies to a peer whose requests "
        "time out (doubles per consecutive failure, capped; default "
        "1.0, statesync/syncer.py ChunkFetchPlan)"
    ),
}


@dataclass(slots=True)
class BaseConfig:
    home: str = "~/.cometbft-tpu"
    moniker: str = "anonymous"
    proxy_app: str = "kvstore"  # in-process app name or tcp://|unix:// addr
    abci: str = "local"  # local | socket
    db_backend: str = "file"  # file | mem
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    # When set (tcp://host:port or unix:///path), the node LISTENS here
    # for a remote signer instead of using the file PV
    # (config.go PrivValidatorListenAddr; privval/signer_*.go).
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    block_sync: bool = True
    state_sync: bool = False
    log_level: str = "info"  # debug | info | error | none

    def resolve(self, path: str) -> str:
        p = os.path.expanduser(path)
        return p if os.path.isabs(p) else os.path.join(
            os.path.expanduser(self.home), p
        )


@dataclass(slots=True)
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit_ns: int = 10_000 * _MS
    max_body_bytes: int = 1_000_000
    pprof_laddr: str = ""
    # operator-only routes (dial_seeds/dial_peers/unsafe_flush_mempool):
    # rpc/core/routes.go AddUnsafeRoutes, config.go RPC.Unsafe
    unsafe: bool = False


@dataclass(slots=True)
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    flush_throttle_timeout_ns: int = 100 * _MS
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    pex: bool = True
    seed_mode: bool = False
    allow_duplicate_ip: bool = False
    handshake_timeout_ns: int = 20_000 * _MS
    dial_timeout_ns: int = 3_000 * _MS


@dataclass(slots=True)
class MempoolConfig:
    recheck: bool = True
    broadcast: bool = True
    size: int = 5000
    max_txs_bytes: int = 1024 * 1024 * 1024
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1024 * 1024


@dataclass(slots=True)
class StateSyncConfig:
    enable: bool = False
    rpc_servers: list[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_ns: int = 168 * 3600 * 1_000_000_000  # 1 week
    discovery_time_ns: int = 15_000 * _MS
    chunk_request_timeout_ns: int = 10_000 * _MS
    chunk_fetchers: int = 4


@dataclass(slots=True)
class BlockSyncConfig:
    version: str = "v0"
    # bytes/sec floor for peers with pending block requests; peers
    # trickling below it are evicted (blocksync/pool.go:133 minRecvRate).
    # 0 disables rate eviction.
    min_recv_rate: int = 7680


@dataclass(slots=True)
class ConsensusConfig:
    wal_file: str = "data/cs.wal/wal"
    # timeouts (config.go:908-945); _delta grows per round
    timeout_propose_ns: int = 3_000 * _MS
    timeout_propose_delta_ns: int = 500 * _MS
    timeout_prevote_ns: int = 1_000 * _MS
    timeout_prevote_delta_ns: int = 500 * _MS
    timeout_precommit_ns: int = 1_000 * _MS
    timeout_precommit_delta_ns: int = 500 * _MS
    timeout_commit_ns: int = 1_000 * _MS
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval_ns: int = 0
    peer_gossip_sleep_duration_ns: int = 100 * _MS
    peer_query_maj23_sleep_duration_ns: int = 2_000 * _MS
    double_sign_check_height: int = 0

    def propose_timeout(self, round_: int) -> float:
        """Seconds; grows linearly with round (state.go proposeTimeout)."""
        return (
            self.timeout_propose_ns + round_ * self.timeout_propose_delta_ns
        ) / 1e9

    def prevote_timeout(self, round_: int) -> float:
        return (
            self.timeout_prevote_ns + round_ * self.timeout_prevote_delta_ns
        ) / 1e9

    def precommit_timeout(self, round_: int) -> float:
        return (
            self.timeout_precommit_ns
            + round_ * self.timeout_precommit_delta_ns
        ) / 1e9

    def commit_timeout(self) -> float:
        return self.timeout_commit_ns / 1e9


@dataclass(slots=True)
class StorageConfig:
    discard_abci_responses: bool = False


@dataclass(slots=True)
class TxIndexConfig:
    indexer: str = "kv"  # kv | sqlite (external-DB sink) | null


@dataclass(slots=True)
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    max_open_connections: int = 3
    namespace: str = "cometbft"


@dataclass(slots=True)
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig
    )


def default_config() -> Config:
    return Config()


def test_config() -> Config:
    """Millisecond consensus timeouts (config.go TestConfig:106)."""
    c = Config()
    c.consensus = replace(
        c.consensus,
        timeout_propose_ns=40 * _MS,
        timeout_propose_delta_ns=1 * _MS,
        timeout_prevote_ns=10 * _MS,
        timeout_prevote_delta_ns=1 * _MS,
        timeout_precommit_ns=10 * _MS,
        timeout_precommit_delta_ns=1 * _MS,
        timeout_commit_ns=10 * _MS,
        skip_timeout_commit=True,
        peer_gossip_sleep_duration_ns=5 * _MS,
        peer_query_maj23_sleep_duration_ns=250 * _MS,
    )
    return c
