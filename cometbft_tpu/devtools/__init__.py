"""Developer tooling that ships with the tree (lint, future codegen).

Nothing here is imported by production modules — the package exists so
invariant-enforcement tools version together with the code whose
invariants they check.
"""
